"""Placement groups (local + cluster 2PC) and the TPU resource model.

(Reference shapes: python/ray/tests/test_placement_group*.py and
python/ray/tests/accelerators/test_tpu.py — env/metadata mocked.)
"""

import time

import pytest

import ray_tpu
from ray_tpu.accelerators.tpu import (
    TpuAcceleratorManager,
    chips_per_host,
    num_hosts,
    parse_pod_type,
    slice_head_resource,
)
from ray_tpu.util.placement_group import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.tpu import SlicePlacementGroup, get_tpu_coordinator_env_vars


# ---------------------------------------------------------------- local PGs
def test_pg_reserve_and_schedule(rt_start):
    pg = placement_group([{"CPU": 2.0}, {"CPU": 1.0}], strategy="PACK")
    assert pg.ready(timeout=10)

    @ray_tpu.remote(num_cpus=2)
    def inside():
        return "pg"

    strat = PlacementGroupSchedulingStrategy(pg, 0)
    assert ray_tpu.get(
        inside.options(scheduling_strategy=strat).remote(), timeout=30) == "pg"
    remove_placement_group(pg)
    assert ray_tpu.available_resources().get("CPU") == 8.0


def test_pg_reserves_capacity(rt_start):
    pg = placement_group([{"CPU": 6.0}])
    assert pg.ready(timeout=10)
    # only 2 CPUs left outside the group
    assert ray_tpu.available_resources()["CPU"] == 2.0
    remove_placement_group(pg)


def test_pg_infeasible_fails(rt_start):
    pg = placement_group([{"CPU": 100.0}])
    assert not pg.ready(timeout=1.0)


def test_pg_strict_spread_impossible_on_one_node(rt_start):
    pg = placement_group([{"CPU": 1.0}, {"CPU": 1.0}], strategy="STRICT_SPREAD")
    assert not pg.ready(timeout=1.0)


def test_pg_bad_args(rt_start):
    with pytest.raises(ValueError):
        placement_group([], strategy="PACK")
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="DIAGONAL")


# ---------------------------------------------------------------- TPU model
def test_parse_pod_types():
    assert parse_pod_type("v5p-64") == ("v5p", 32)   # 64 cores → 32 chips
    assert parse_pod_type("v5e-16") == ("v5e", 16)
    assert parse_pod_type("v4-8") == ("v4", 4)
    with pytest.raises(ValueError):
        parse_pod_type("gpu-8")


def test_hosts_and_chips():
    assert num_hosts("v5p-64") == 8          # 32 chips / 4 per host
    assert chips_per_host("v5p-64") == 4
    assert num_hosts("v5e-16") == 2          # 16 chips / 8 per host
    assert chips_per_host("v5e-16") == 8
    assert num_hosts("v4-8") == 1


def test_manager_detection_from_env():
    mgr = TpuAcceleratorManager(env={
        "TPU_ACCELERATOR_TYPE": "v5p-64",
        "TPU_WORKER_ID": "0",
        "TPU_NAME": "slice-a",
    })
    assert mgr.get_current_node_accelerator_type() == "v5p"
    assert mgr.get_current_node_num_accelerators() == 4
    res = mgr.get_current_node_resources()
    assert res["TPU"] == 4.0
    assert res[slice_head_resource("v5p-64")] == 1.0  # worker 0 only
    labels = mgr.get_current_node_labels()
    assert labels["rtpu.io/tpu-slice-name"] == "slice-a"
    assert labels["rtpu.io/tpu-worker-id"] == "0"


def test_manager_non_head_worker_has_no_marker():
    mgr = TpuAcceleratorManager(env={
        "TPU_ACCELERATOR_TYPE": "v5p-64", "TPU_WORKER_ID": "3",
    })
    res = mgr.get_current_node_resources()
    assert "TPU" in res and len(res) == 1


def test_manager_visible_chips_env():
    mgr = TpuAcceleratorManager(env={"TPU_VISIBLE_CHIPS": "0,1"})
    assert mgr.get_current_node_num_accelerators() == 2
    assert mgr.set_visible_accelerator_ids(["2", "3"]) == {
        "TPU_VISIBLE_CHIPS": "2,3"}


def test_manager_metadata_fallback():
    mgr = TpuAcceleratorManager(
        env={},
        metadata_getter={"accelerator-type": "v5e-16",
                         "agent-worker-number": "0"}.get,
    )
    assert mgr.get_current_node_num_accelerators() == 8
    assert slice_head_resource("v5e-16") in mgr.get_current_node_resources()


def test_coordinator_env_vars():
    env = get_tpu_coordinator_env_vars("10.0.0.1:8080", 4, 2)
    assert env["MEGASCALE_NUM_SLICES"] == "4"
    assert env["MEGASCALE_SLICE_ID"] == "2"


# ------------------------------------------------------------ cluster 2PC
@pytest.fixture(scope="module")
def pg_cluster():
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.worker import global_worker
    from ray_tpu.utils.ids import JobID

    c = Cluster()
    # a fake v5e-16 slice: 2 hosts × 8 chips, worker 0 carries the marker
    c.add_node(num_cpus=2, resources={"TPU": 8.0,
                                      slice_head_resource("v5e-16"): 1.0},
               labels={"rtpu.io/tpu-worker-id": "0"})
    c.add_node(num_cpus=2, resources={"TPU": 8.0},
               labels={"rtpu.io/tpu-worker-id": "1"})
    rt = c.connect()
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    yield c
    rt.shutdown()
    c.shutdown()
    global_worker.runtime = None


def test_slice_placement_group_cluster(pg_cluster):
    spg = SlicePlacementGroup("v5e-16").reserve()
    assert spg.hosts_per_slice == 2
    assert spg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=0, num_tpus=8)
    def on_host():
        return "got slice host"

    out = ray_tpu.get([
        on_host.options(
            scheduling_strategy=spg.worker_strategy(0, h)).remote()
        for h in range(2)
    ], timeout=60)
    assert out == ["got slice host"] * 2
    spg.remove()


def test_cluster_pg_strict_spread(pg_cluster):
    pg = placement_group([{"CPU": 1.0}, {"CPU": 1.0}], strategy="STRICT_SPREAD")
    assert pg.ready(timeout=30)
    remove_placement_group(pg)


def test_cluster_pg_infeasible_stays_pending(pg_cluster):
    pg = placement_group([{"CPU": 50.0}])
    assert not pg.ready(timeout=1.5)
    remove_placement_group(pg)
