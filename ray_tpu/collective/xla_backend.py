"""XLA collective backend: every op is a compiled shard_map program over a
device mesh — the ICI-native replacement for NCCL rings.

Where the reference's NCCLGroup (reference:
python/ray/util/collective/collective_group/nccl_collective_group.py:121)
drives cupy-NCCL kernels on dedicated CUDA streams, this backend builds a
jitted `shard_map` per (op, shape, dtype, axes): XLA lowers `lax.psum` /
`all_gather` / `psum_scatter` / `all_to_all` / `ppermute` to ICI DMA with
compiler-scheduled overlap. Inputs are global jax.Arrays sharded over the
group's mesh (or host arrays, which are device_put first); membership IS the
mesh — no rank bookkeeping, no id exchange, no streams.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ray_tpu.parallel.mesh import MeshSpec, build_mesh

_REDUCERS = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}


class XlaCollectiveGroup:
    """Collectives over one named mesh axis (default: all axes flattened).

    Tensors are sharded along their leading dimension over ``axis`` unless a
    PartitionSpec is given explicitly.
    """

    def __init__(self, group_name: str = "default", mesh: Mesh | None = None,
                 axis: str = "dp", devices: list | None = None,
                 world_size: int | None = None):
        if mesh is None:
            n = world_size or len(devices or jax.devices())
            mesh = build_mesh(MeshSpec(dp=n), devices)
        self.mesh = mesh
        self.axis = axis
        self.group_name = group_name
        self._p2p: dict[int, list] = {}  # src_rank -> buffered sends

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    # -- compiled-op cache -------------------------------------------------
    @functools.lru_cache(maxsize=256)  # noqa: B019 - deliberate per-group cache
    def _compiled(self, op: str, extra=None):
        mesh, axis = self.mesh, self.axis
        shard = P(axis)  # leading-dim sharded
        repl = P()

        if op.startswith("allreduce_"):
            reducer = _REDUCERS[op.split("_")[1]]

            @jax.jit
            def fn(x):
                return shard_map(
                    lambda s: reducer(s, axis), mesh=mesh,
                    in_specs=repl, out_specs=repl, check_vma=False,
                )(x)
            # replicated-in / replicated-out: each member's copy is reduced
            # pointwise. For sharded arrays use spec-aware path below.
            return fn

        if op.startswith("psum_sharded_"):
            reducer = _REDUCERS[op.split("_")[2]]

            @jax.jit
            def fn(x):
                return shard_map(
                    lambda s: reducer(s, axis), mesh=mesh,
                    in_specs=shard, out_specs=shard, check_vma=False,
                )(x)
            return fn

        if op == "allgather":
            @jax.jit
            def fn(x):
                return shard_map(
                    lambda s: lax.all_gather(s, axis, axis=0, tiled=True),
                    mesh=mesh, in_specs=shard, out_specs=repl, check_vma=False,
                )(x)
            return fn

        if op.startswith("reducescatter_"):
            reducer_name = op.split("_")[1]

            @jax.jit
            def fn(x):
                return shard_map(
                    lambda s: lax.psum_scatter(s, axis, scatter_dimension=0,
                                               tiled=True),
                    mesh=mesh, in_specs=repl, out_specs=shard, check_vma=False,
                )(x)
            return fn

        if op == "alltoall":
            @jax.jit
            def fn(x):
                # split leading dim across members, concat received chunks
                return shard_map(
                    lambda s: lax.all_to_all(s, axis, split_axis=0,
                                             concat_axis=0, tiled=True),
                    mesh=mesh, in_specs=shard, out_specs=shard,
                )(x)
            return fn

        if op == "ppermute":
            perm = list(extra)

            @jax.jit
            def fn(x):
                return shard_map(
                    lambda s: lax.ppermute(s, axis, perm=perm),
                    mesh=mesh, in_specs=shard, out_specs=shard,
                )(x)
            return fn

        if op.startswith("reduce_"):
            reducer = _REDUCERS[op.split("_")[1]]
            dst = int(extra)

            @jax.jit
            def fn(x):
                def inner(s):
                    r = reducer(s, axis)
                    keep = lax.axis_index(axis) == dst
                    return jnp.where(keep, r, s)[None]
                # Members differ post-reduce (dst holds the reduction, the
                # rest keep their input), so the global result is the
                # per-member stack [world, ...].
                return shard_map(inner, mesh=mesh, in_specs=repl,
                                 out_specs=P(axis), check_vma=False)(x)
            return fn

        if op == "broadcast":
            src = int(extra)

            @jax.jit
            def fn(x):
                def inner(s):
                    # every member takes src's shard (gather then select —
                    # ppermute can't fan out one source to all)
                    g = lax.all_gather(s, axis, axis=0, tiled=False)
                    return g[src]
                return shard_map(inner, mesh=mesh, in_specs=shard,
                                 out_specs=shard, check_vma=False)(x)
            return fn

        raise ValueError(f"unknown op {op}")

    # -- public ops --------------------------------------------------------
    def _device_put_sharded(self, x, spec: P):
        x = jnp.asarray(x)
        sharding = NamedSharding(self.mesh, spec)
        if hasattr(x, "sharding") and x.sharding == sharding:
            return x
        return jax.device_put(x, sharding)

    def allreduce(self, x, op: str = "sum"):
        """Pointwise reduce replicated copies across the axis. For a global
        array sharded on the axis, this is psum of shards (sharded in/out)."""
        x = jnp.asarray(x)
        if hasattr(x, "sharding") and not x.sharding.is_fully_replicated:
            return self._compiled(f"psum_sharded_{op}")(x)
        x = self._device_put_sharded(x, P())
        return self._compiled(f"allreduce_{op}")(x)

    def allgather(self, x):
        x = self._device_put_sharded(x, P(self.axis))
        return self._compiled("allgather")(x)

    def reducescatter(self, x, op: str = "sum"):
        x = self._device_put_sharded(x, P())
        return self._compiled(f"reducescatter_{op}")(x)

    def alltoall(self, x):
        x = self._device_put_sharded(x, P(self.axis))
        return self._compiled("alltoall")(x)

    def broadcast(self, x, src_rank: int = 0):
        x = self._device_put_sharded(x, P(self.axis))
        return self._compiled("broadcast", src_rank)(x)

    def reduce(self, x, dst_rank: int = 0, op: str = "sum"):
        """Reduce replicated copies to ``dst_rank``. Members diverge after a
        reduce (only dst holds the reduction; the rest keep their input —
        reference: collective.py:356 reduce semantics), so the result is the
        per-member stack ``[world, *x.shape]``: ``out[dst_rank]`` is the
        reduction, ``out[r]`` is member r's original value."""
        x = self._device_put_sharded(jnp.asarray(x), P())
        return self._compiled(f"reduce_{op}", int(dst_rank))(x)

    def ppermute(self, x, perm: list[tuple[int, int]]):
        x = self._device_put_sharded(x, P(self.axis))
        return self._compiled("ppermute", tuple(perm))(x)

    def barrier(self):
        # A zero-byte psum forces a cross-device sync point.
        x = jnp.zeros((self.world_size,), jnp.float32)
        self.allreduce(x).block_until_ready()

    def send(self, x, dst_rank: int, src_rank: int = 0):
        """Point-to-point shard move src→dst, lowered to a one-pair
        ``lax.ppermute`` over ICI (reference: send/recv
        collective.py:576/:639 — NCCL p2p). The group is single-controller
        SPMD, so one call expresses both sides; the moved array is also
        buffered for a matching ``recv``."""
        out = self.ppermute(x, [(int(src_rank), int(dst_rank))])
        buf = self._p2p.setdefault(int(src_rank), [])
        buf.append(out)
        if len(buf) > 64:
            # Dropping entries would silently pair a later recv with the
            # wrong send; fail loudly instead (send-only callers should use
            # ppermute directly).
            buf.clear()
            raise RuntimeError(
                "send(): >64 unmatched sends buffered for rank "
                f"{src_rank}; pair each send with a recv, or use "
                "ppermute() for one-sided transfers")
        return out

    def recv(self, shape, dtype, src_rank: int):
        """Take the oldest buffered ``send`` from ``src_rank`` (matched-pair
        protocol of the two-sided API, collapsed into one process)."""
        buf = self._p2p.get(int(src_rank))
        if not buf:
            raise RuntimeError(
                f"recv: no buffered send from rank {src_rank}; in the "
                "single-controller XLA group send() and recv() form a "
                "matched pair in the same process")
        out = buf.pop(0)
        if tuple(shape) != tuple(out.shape) or jnp.dtype(dtype) != out.dtype:
            raise ValueError(
                f"recv: shape/dtype mismatch: sent {out.shape}/{out.dtype}, "
                f"expected {tuple(shape)}/{jnp.dtype(dtype)}")
        return out

    def destroy(self):
        self._compiled.cache_clear()
        self._p2p.clear()
