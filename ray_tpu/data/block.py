"""Block model for ray_tpu.data.

A Block is the unit of data that flows between operators as an object-store
ref (reference capability: python/ray/data/block.py — Arrow/pandas blocks in
plasma). TPU-first choice: the canonical in-memory block is a **columnar dict
of numpy arrays** — the zero-copy feed format for `jax.device_put` / host
input pipelines — with conversion shims for rows, pandas, and pyarrow.
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np

# A Block is dict[str, np.ndarray]; all columns share length == num_rows.
Block = dict


def _to_column(values: list) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype.kind in ("U", "S"):
        arr = np.asarray(values, dtype=object)
    if arr.dtype.kind == "O" and arr.ndim > 1:
        # ragged nested lists — keep one object per row
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        arr = out
    return arr


def block_from_rows(rows: list[dict]) -> Block:
    """Build a columnar block from a list of row dicts."""
    if not rows:
        return {}
    cols: dict[str, list] = {k: [] for k in rows[0]}
    for r in rows:
        if r.keys() != cols.keys():
            for k in r:
                if k not in cols:
                    cols[k] = [None] * (len(cols[next(iter(cols))]) if cols else 0)
        for k in cols:
            cols[k].append(r.get(k))
    return {k: _to_column(v) for k, v in cols.items()}


def block_from_arrow(table) -> Block:
    """pyarrow.Table → columnar block."""
    out: Block = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            out[name] = col.to_numpy(zero_copy_only=False)
        except Exception:
            out[name] = np.asarray(col.to_pylist(), dtype=object)
    return out


def block_from_pandas(df) -> Block:
    out: Block = {}
    for name in df.columns:
        out[str(name)] = df[name].to_numpy()
    return out


def block_from_numpy(data) -> Block:
    """An ndarray (→ column "data") or a dict of ndarrays."""
    if isinstance(data, dict):
        return {k: np.asarray(v) for k, v in data.items()}
    return {"data": np.asarray(data)}


class BlockAccessor:
    """Uniform view over a columnar block (reference capability:
    python/ray/data/block.py BlockAccessor)."""

    def __init__(self, block: Block):
        self._block = block or {}

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        for col in self._block.values():
            return len(col)
        return 0

    def size_bytes(self) -> int:
        total = 0
        for col in self._block.values():
            if col.dtype.kind == "O":
                total += sum(_approx_obj_size(v) for v in col)
            else:
                total += col.nbytes
        return total

    def columns(self) -> list[str]:
        return list(self._block.keys())

    def schema(self) -> dict[str, str]:
        return {k: str(v.dtype) for k, v in self._block.items()}

    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self._block.items()}

    def take_rows(self, indices: np.ndarray) -> Block:
        return {k: v[indices] for k, v in self._block.items()}

    def iter_rows(self) -> Iterator[dict]:
        keys = list(self._block.keys())
        for i in range(self.num_rows()):
            yield {k: _unbox(self._block[k][i]) for k in keys}

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                             for k, v in self._block.items()})

    def to_arrow(self):
        import pyarrow as pa

        return pa.Table.from_pydict({k: list(v) for k, v in self._block.items()})

    def to_numpy(self) -> Block:
        return dict(self._block)

    def to_batch(self, batch_format: str):
        if batch_format in ("numpy", "default", None):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "pyarrow":
            return self.to_arrow()
        raise ValueError(f"unknown batch_format {batch_format!r}")


def _unbox(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def _approx_obj_size(v: Any) -> int:
    if isinstance(v, (bytes, str)):
        return len(v)
    if isinstance(v, np.ndarray):
        return v.nbytes
    return 8


def concat_blocks(blocks: list[Block]) -> Block:
    blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
    if not blocks:
        return {}
    keys = list(blocks[0].keys())
    out: Block = {}
    for k in keys:
        cols = [b[k] for b in blocks]
        if any(c.dtype.kind == "O" for c in cols):
            merged = np.empty(sum(len(c) for c in cols), dtype=object)
            i = 0
            for c in cols:
                merged[i:i + len(c)] = c
                i += len(c)
            out[k] = merged
        else:
            out[k] = np.concatenate(cols)
    return out


def batch_to_block(batch: Any) -> Block:
    """Normalize a user map_batches return value into a block."""
    if batch is None:
        return {}
    if isinstance(batch, dict):
        return {k: np.asarray(v) if not isinstance(v, np.ndarray) else v
                for k, v in batch.items()}
    if isinstance(batch, np.ndarray):
        return {"data": batch}
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return block_from_pandas(batch)
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return block_from_arrow(batch)
    except ImportError:
        pass
    raise TypeError(
        f"map_batches must return dict/ndarray/DataFrame/Table, got {type(batch)}"
    )


def split_block(block: Block, num_splits: int) -> list[Block]:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    sizes = [n // num_splits + (1 if i < n % num_splits else 0)
             for i in range(num_splits)]
    out, start = [], 0
    for s in sizes:
        out.append(acc.slice(start, start + s))
        start += s
    return out
