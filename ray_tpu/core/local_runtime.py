"""In-process runtime: executes the full tasks/actors/objects semantics inside
one process, with threads standing in for workers.

Capability parity with the reference's local mode + single-node semantics
(reference: python/ray/_private/worker.py local-mode path and the semantics
of core_worker task submission/execution, src/ray/core_worker/core_worker.cc
SubmitTask :1957 / CreateActor :2037 / SubmitActorTask :2372): resource-aware
scheduling with dependency resolution *before* resource acquisition (the
reference pulls lease dependencies before granting a worker —
lease_dependency_manager.cc), ordered actor mailboxes with optional
concurrency/async execution, named actors, restarts, and error propagation
into result objects.

The distributed runtime (ray_tpu/core/cluster/) speaks the same ``Runtime``
interface; tests of API semantics run against this one.
"""

from __future__ import annotations

import asyncio
import contextlib
import inspect
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any

from ray_tpu.core.events import global_event_buffer
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    OutOfMemoryError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.object_ref import ObjectRef, refcounting_suppressed
from ray_tpu.core.store import LocalObjectStore, ReferenceCounter
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.utils import serialization
from ray_tpu.utils.ids import ActorID, ObjectID, WorkerID

# Execution-thread pool cap AND the overflow threshold in submit_task: past
# this many in-flight tasks, new submissions get dedicated threads so pool
# threads blocked in nested get() can never starve the tasks they wait on.
_TASK_POOL_SIZE = 64


class _ResourcePool:
    """Blocking counted-resource pool (CPU/TPU/custom), FIFO-fair."""

    def __init__(self, totals: dict[str, float]):
        self._avail = dict(totals)
        self._totals = dict(totals)
        self._cv = threading.Condition()

    def acquire(self, demand: dict[str, float], timeout: float | None = None) -> bool:
        if not demand:
            return True
        with self._cv:
            def fits():
                return all(self._avail.get(k, 0.0) >= v for k, v in demand.items())

            for k, v in demand.items():
                if self._totals.get(k, 0.0) < v:
                    raise ValueError(
                        f"infeasible resource demand {k}={v} (total {self._totals.get(k, 0.0)})"
                    )
            if not self._cv.wait_for(fits, timeout):
                return False
            for k, v in demand.items():
                self._avail[k] = self._avail.get(k, 0.0) - v
            return True

    def release(self, demand: dict[str, float]) -> None:
        if not demand:
            return
        with self._cv:
            for k, v in demand.items():
                self._avail[k] = self._avail.get(k, 0.0) + v
            self._cv.notify_all()

    def available(self) -> dict[str, float]:
        with self._cv:
            return dict(self._avail)

    def totals(self) -> dict[str, float]:
        with self._cv:
            return dict(self._totals)

    def add_resources(self, extra: dict[str, float]) -> None:
        with self._cv:
            for k, v in extra.items():
                self._totals[k] = self._totals.get(k, 0.0) + v
                self._avail[k] = self._avail.get(k, 0.0) + v
            self._cv.notify_all()

    def remove_resources(self, extra: dict[str, float]) -> None:
        with self._cv:
            for k in extra:
                self._totals.pop(k, None)
                self._avail.pop(k, None)


@dataclass
class _ActorState:
    spec: ActorCreationSpec
    instance: Any = None
    mailbox: "queue.Queue[TaskSpec | None]" = None
    thread: threading.Thread = None
    dead: bool = False
    death_reason: str = ""
    restarts_used: int = 0
    loop: asyncio.AbstractEventLoop | None = None
    pool: ThreadPoolExecutor | None = None


_SENTINEL_CANCEL = object()


class LocalRuntime:
    """Single-process implementation of the Runtime interface."""

    def __init__(self, num_cpus: float = 8, resources: dict[str, float] | None = None):
        totals = {"CPU": float(num_cpus)}
        totals.update(resources or {})
        self.worker_id = WorkerID.from_random()
        self.store = LocalObjectStore()
        # Event-driven wait(): seals notify the condition so wait() wakes
        # immediately instead of polling (same pattern as the cluster
        # runtime's _wait_cond — reference: wait_manager.cc callbacks).
        self._wait_cond = threading.Condition()

        def _notify():
            with self._wait_cond:
                self._wait_cond.notify_all()

        self.store.on_seal = _notify
        self._task_pool = ThreadPoolExecutor(
            max_workers=_TASK_POOL_SIZE, thread_name_prefix="task")
        self._tasks_inflight = 0  # includes tasks blocked in nested get()
        self._inflight_lock = threading.Lock()
        self._released: set[ObjectID] = set()
        # container object -> ObjectIDs nested inside its stored value
        # (reference semantics: reference_counter.h nested refs keep the inner
        # object alive until the outer object is GC'd)
        self._nested: dict[ObjectID, list[ObjectID]] = {}
        self.refs = ReferenceCounter(on_release=self._on_release)
        self.resources = _ResourcePool(totals)
        self._actors: dict[ActorID, _ActorState] = {}
        self._named_actors: dict[tuple[str, str], ActorID] = {}
        self._pg_states: dict = {}
        self._pg_reserved: dict = {}
        self._cancelled: set[ObjectID] = set()
        self._kv: dict[str, dict[str, bytes]] = {}
        # Content-addressed definition registry (cluster parity: the head
        # KV function table). blob by id, plus a deserialized cache so a
        # definition is unpickled once per process, not once per task.
        self._fn_defs: dict[str, bytes] = {}
        self._fns: dict[str, Any] = {}
        self._lock = threading.RLock()
        self._shutdown = False

    def _on_release(self, oid: ObjectID, rec=None) -> None:
        # Tombstone so a result landing after all refs died is dropped, not
        # stored forever (fire-and-forget tasks).
        self._released.add(oid)
        self.store.delete(oid)
        for nid in self._nested.pop(oid, ()):  # release refs the value held
            self.refs.remove_local_ref(nid)

    def _register_nested(self, oid: ObjectID, value: Any) -> None:
        """Refs nested in a stored value are held by the container object."""
        nested = serialization.find_nested_refs(value)
        if nested:
            for r in nested:
                self.refs.add_local_ref(r.id)
            self._nested[oid] = [r.id for r in nested]

    # ------------------------------------------------------------------ put/get
    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(self.worker_id)
        self.store.put(oid, serialization.serialize(value), self.worker_id)
        lr = 0 if refcounting_suppressed() else 1
        self.refs.add_owned(oid, self.worker_id, local_refs=lr)
        self._register_nested(oid, value)
        return (ObjectRef.counted if lr else ObjectRef)(oid, self.worker_id)

    @contextlib.contextmanager
    def _yield_task_resources(self):
        """Release the calling task's acquired resources for the duration of
        a blocking get()/wait() and re-acquire afterwards (reference: a
        worker blocked in ray.get returns its CPU to the raylet so the
        tasks it waits on can run — otherwise parents waiting on children
        deadlock the resource ledger). Actors hold their resources for
        their lifetime (the reference doesn't return them while blocked) —
        only plain tasks yield."""
        from ray_tpu.core.worker import _task_context

        res = getattr(_task_context, "resources", None)
        if not res or getattr(_task_context, "actor_id", None) is not None:
            yield
            return
        self.resources.release(res)
        try:
            yield
        finally:
            self.resources.acquire(res, timeout=None)

    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list[Any]:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        out = []
        with self._yield_task_resources():
            for ref in refs:
                remaining = None if deadline is None else max(0.0, deadline - _time.monotonic())
                try:
                    data = self.store.get(ref.id, timeout=remaining)
                except TimeoutError:
                    raise GetTimeoutError(f"get() timed out waiting for {ref}") from None
                value = serialization.deserialize(data)
                if isinstance(value, (TaskError, ActorDiedError, TaskCancelledError,
                          OutOfMemoryError)):
                    raise value
                out.append(value)
        return out

    def wait(
        self,
        refs: list[ObjectRef],
        num_returns: int = 1,
        timeout: float | None = None,
        fetch_local: bool = True,
    ) -> tuple[list[ObjectRef], list[ObjectRef]]:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        ready: list[ObjectRef] = []
        pending = list(refs)
        with self._yield_task_resources():
            return self._wait_loop(ready, pending, num_returns, deadline)

    def _wait_loop(self, ready, pending, num_returns, deadline):
        import time as _time

        while len(ready) < num_returns:
            progressed = False
            still = []
            for r in pending:
                if self.store.contains(r.id):
                    ready.append(r)
                    progressed = True
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and _time.monotonic() >= deadline:
                break
            if not progressed:
                remaining = (None if deadline is None
                             else max(0.0, deadline - _time.monotonic()))
                with self._wait_cond:
                    # Recheck under the lock: a seal between the scan above
                    # and this acquire would otherwise be a lost wakeup
                    # (notify_all fires outside the store lock, so this
                    # nesting cannot deadlock).
                    if not any(self.store.contains(r.id) for r in pending):
                        self._wait_cond.wait(
                            0.05 if remaining is None else min(remaining, 0.05))
        return ready, pending

    # ------------------------------------------------------------------ tasks
    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        return_ids = spec.return_ids()
        # Fused ownership + returned-ref count (see ObjectRef.counted);
        # suppressed inside refcount_disabled() (proxy layers).
        lr = 0 if refcounting_suppressed() else 1
        for oid in return_ids:
            self.refs.add_owned(oid, self.worker_id, lineage_task=spec.task_id,
                                local_refs=lr)
        self.refs.on_task_submitted(spec.arg_ref_ids)
        global_event_buffer().record(
            spec.task_id.hex(), spec.name, "SUBMITTED",
            worker_id=self.worker_id.hex(), job_id=spec.job_id.hex())
        # Pooled execution threads: ThreadPoolExecutor reuses idle threads
        # (thread-per-task spent ~0.2 ms/task on thread start alone). The
        # thread-per-task property that mattered — a task blocked on a
        # nested get() never starves the tasks it waits on — is preserved
        # by overflow: when every pool thread is occupied (possibly all
        # blocked in nested gets), new submissions get dedicated threads
        # instead of queueing behind the blocked ones.
        with self._inflight_lock:
            self._tasks_inflight += 1
            overflow = self._tasks_inflight > _TASK_POOL_SIZE
        if overflow:
            threading.Thread(
                target=self._run_pooled, args=(spec, return_ids),
                daemon=True, name=f"task-ovf-{spec.name[:20]}").start()
        else:
            self._task_pool.submit(self._run_pooled, spec, return_ids)
        make = ObjectRef.counted if lr else ObjectRef
        return [make(oid, self.worker_id) for oid in return_ids]

    def _run_pooled(self, spec: TaskSpec, return_ids: list[ObjectID]) -> None:
        try:
            self._run_normal_task(spec, return_ids)
        finally:
            with self._inflight_lock:
                self._tasks_inflight -= 1

    def _run_normal_task(self, spec: TaskSpec, return_ids: list[ObjectID]) -> None:
        from ray_tpu.core.events import task_execution
        from ray_tpu.core.worker import set_task_context

        wid = self.worker_id.hex()
        attempts = 0
        try:
            while True:
                if return_ids[0] in self._cancelled:
                    self._store_error(return_ids, TaskCancelledError(spec.name))
                    global_event_buffer().record(
                        spec.task_id.hex(), spec.name, "CANCELLED", worker_id=wid)
                    return
                try:
                    if spec.runtime_env:
                        from ray_tpu.runtime_env import get_manager

                        get_manager().ensure(spec.runtime_env, self)
                    fn = self._load_definition(spec.fn_id, spec.fn_blob)
                    args, kwargs = self._resolve_args(spec)
                    if not self.resources.acquire(spec.resources, timeout=None):
                        raise RuntimeError("resource acquisition failed")
                    set_task_context(spec.task_id, None, spec.resources)
                    try:
                        with task_execution(spec, wid):
                            result = fn(*args, **kwargs)
                    finally:
                        set_task_context(None, None, None)
                        self.resources.release(spec.resources)
                    self._store_results(spec, return_ids, result)
                    return
                except (TaskError, ActorDiedError, TaskCancelledError) as e:
                    # dependency failed: propagate, don't retry (matches reference
                    # behavior — errors in args poison downstream tasks)
                    self._store_error(return_ids, e)
                    return
                except BaseException as e:  # noqa: BLE001
                    attempts += 1
                    if spec.retry_exceptions and attempts <= spec.max_retries:
                        continue
                    self._store_error(return_ids, TaskError(e, task_desc=spec.name))
                    from ray_tpu.core import flight_recorder

                    flight_recorder.record(
                        "task_failure", reason=repr(e),
                        task_id=spec.task_id.hex(),
                        extra={"task": spec.name, "attempts": attempts})
                    return
        finally:
            # Exactly once per task, regardless of retries.
            self.refs.on_task_finished(spec.arg_ref_ids)

    def export_function(self, fn_id: str, fn_blob: bytes) -> None:
        """Registry export (idempotent): submitters publish a definition
        once; specs then carry only the content id."""
        if fn_id not in self._fn_defs:
            self._fn_defs[fn_id] = fn_blob

    def _load_definition(self, fn_id: str, fn_blob: bytes):
        if not fn_id:
            return serialization.loads_function(fn_blob)
        fn = self._fns.get(fn_id)
        if fn is None:
            # Thin-client proxies export through the KV namespace (their
            # runtime interface has no direct registry): honor both tables.
            from ray_tpu.core.fn_registry import FN_NS

            blob = fn_blob or self._fn_defs.get(fn_id) or \
                self._kv.get(FN_NS, {}).get(fn_id)
            if blob is None:
                raise KeyError(
                    f"function definition {fn_id} not in the registry")
            fn = serialization.loads_function(blob)
            self._fns[fn_id] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec) -> tuple[tuple, dict]:
        args, kwargs = serialization.deserialize(spec.args_blob)
        return self._replace_refs(args), self._replace_refs(kwargs)

    def _replace_refs(self, obj: Any) -> Any:
        # Top-level ObjectRefs in args are resolved to values (reference
        # semantics: dependency_resolver.cc inlines ready deps). Nested refs
        # inside containers are passed through un-resolved, same as reference.
        if isinstance(obj, ObjectRef):
            return self.get([obj])[0]
        if isinstance(obj, tuple):
            return tuple(self._replace_refs(o) if isinstance(o, ObjectRef) else o for o in obj)
        if isinstance(obj, dict):
            return {k: (self._replace_refs(v) if isinstance(v, ObjectRef) else v) for k, v in obj.items()}
        return obj

    def _store_results(self, spec: TaskSpec, return_ids: list[ObjectID], result: Any) -> None:
        if spec.num_returns == "streaming":
            # Drive the generator here (executor side); each yield becomes an
            # object the consumer's ObjectRefGenerator picks up, the item
            # count lands under STREAM_END_INDEX (reference: streaming
            # generator returns, _raylet.pyx ObjectRefGenerator).
            from ray_tpu.core.object_ref import STREAM_END_INDEX

            i = 0
            try:
                for v in result:
                    oid = ObjectID.for_task_return(spec.task_id, i)
                    self.store.put(oid, serialization.serialize(v),
                                   self.worker_id)
                    self.refs.add_owned(oid, self.worker_id)
                    i += 1
            except BaseException as e:  # noqa: BLE001 - stream error → end marker
                end = ObjectID.for_task_return(spec.task_id, STREAM_END_INDEX)
                self.store.put(end, serialization.serialize(
                    TaskError(e, task_desc=spec.name)), self.worker_id)
                return
            end = ObjectID.for_task_return(spec.task_id, STREAM_END_INDEX)
            self.store.put(end, serialization.serialize(i), self.worker_id)
            return
        if spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                self._store_error(
                    return_ids,
                    TaskError(
                        ValueError(
                            f"task declared num_returns={spec.num_returns} but returned {len(values)}"
                        ),
                        task_desc=spec.name,
                    ),
                )
                return
        for oid, v in zip(return_ids, values):
            if isinstance(v, ObjectRef):
                # Returning a ref forwards the underlying value (ownership note:
                # the reference tracks this as a nested return; we materialize).
                v = self.get([v])[0]
            if oid not in self._released:
                self.store.put(oid, serialization.serialize(v), self.worker_id)
                self._register_nested(oid, v)

    def _store_error(self, return_ids: list[ObjectID], err: BaseException) -> None:
        blob = serialization.serialize(err)
        for oid in return_ids:
            if oid not in self._released:
                self.store.put(oid, blob, self.worker_id)

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        self._cancelled.add(ref.id)

    # ------------------------------------------------------------------ actors
    def create_actor(self, spec: ActorCreationSpec) -> None:
        state = _ActorState(spec=spec, mailbox=queue.Queue())
        with self._lock:
            if spec.name:
                key = (spec.namespace, spec.name)
                if key in self._named_actors:
                    raise ValueError(f"actor name {spec.name!r} already taken in {spec.namespace!r}")
                self._named_actors[key] = spec.actor_id
            self._actors[spec.actor_id] = state
        state.thread = threading.Thread(
            target=self._actor_main, args=(state,), daemon=True, name=f"actor-{spec.actor_id.hex()[:8]}"
        )
        state.thread.start()

    def _actor_main(self, state: _ActorState) -> None:
        spec = state.spec
        try:
            if not self.resources.acquire(spec.resources, timeout=None):
                raise RuntimeError("actor resource acquisition failed")
        except BaseException as e:  # noqa: BLE001
            self._mark_actor_dead(state, f"resource acquisition failed: {e}")
            return
        # Restart-on-init-failure up to max_restarts (reference: GcsActorManager
        # RESTARTING FSM — local mode restarts cover __init__ failures; process
        # death restarts belong to the cluster runtime).
        while True:
            try:
                self._actor_init(state)
                break
            except BaseException as e:  # noqa: BLE001
                if state.restarts_used < spec.max_restarts:
                    state.restarts_used += 1
                    continue
                self.resources.release(spec.resources)
                self._mark_actor_dead(state, f"__init__ failed: {e!r}")
                return
        if state.spec.max_concurrency > 1:
            state.pool = ThreadPoolExecutor(max_workers=state.spec.max_concurrency)
        try:
            while True:
                item = state.mailbox.get()
                if item is None:
                    break
                self._execute_actor_task(state, item)
        finally:
            if state.pool:
                state.pool.shutdown(wait=False)
            if state.loop:
                state.loop.call_soon_threadsafe(state.loop.stop)
            self.resources.release(spec.resources)

    def _actor_init(self, state: _ActorState) -> None:
        if state.spec.runtime_env:
            from ray_tpu.runtime_env import get_manager

            get_manager().ensure(state.spec.runtime_env, self)
        cls = self._load_definition(getattr(state.spec, "cls_id", ""),
                                    state.spec.cls_blob)
        args, kwargs = serialization.deserialize(state.spec.args_blob)
        args = self._replace_refs(args)
        kwargs = self._replace_refs(kwargs)
        state.instance = cls(*args, **kwargs)
        # Async actor: any coroutine method => dedicated event loop thread.
        if any(
            inspect.iscoroutinefunction(getattr(type(state.instance), m, None))
            for m in dir(type(state.instance))
            if not m.startswith("__")
        ):
            state.loop = asyncio.new_event_loop()
            t = threading.Thread(target=state.loop.run_forever, daemon=True)
            t.start()

    def _execute_actor_task(self, state: _ActorState, spec: TaskSpec) -> None:
        return_ids = spec.return_ids()

        def run():
            from ray_tpu.core.events import task_execution
            from ray_tpu.core.worker import set_task_context

            try:
                set_task_context(spec.task_id, state.spec.actor_id, state.spec.resources)
                args, kwargs = self._resolve_args(spec)
                if spec.method_name == "__rtpu_call_fn__":
                    # Internal hook: run fn(instance, *args) in actor context
                    # (reference: __ray_call__ — used by compiled graphs to
                    # install per-actor execution loops).
                    import functools

                    method = functools.partial(args[0], state.instance)
                    args = args[1:]
                else:
                    method = getattr(state.instance, spec.method_name)
                with task_execution(spec, self.worker_id.hex()):
                    if inspect.iscoroutinefunction(method):
                        fut = asyncio.run_coroutine_threadsafe(method(*args, **kwargs), state.loop)
                        result = fut.result()
                    else:
                        result = method(*args, **kwargs)
                self._store_results(spec, return_ids, result)
            except (TaskError, ActorDiedError, TaskCancelledError) as e:
                self._store_error(return_ids, e)
            except BaseException as e:  # noqa: BLE001
                self._store_error(return_ids, TaskError(e, task_desc=f"{spec.method_name}"))
            finally:
                set_task_context(None, None, None)

        if state.loop is not None and inspect.iscoroutinefunction(
            getattr(state.instance, spec.method_name, None)
        ):
            # Async actor methods interleave on the loop; completion is out of
            # band (reference: async actors via fibers, task_execution/fiber.h).
            threading.Thread(target=run, daemon=True).start()
        elif spec.method_name == "__rtpu_call_fn__":
            # Injected functions may be long-running loops (compiled-graph
            # schedules); never let them wedge the ordered mailbox.
            threading.Thread(target=run, daemon=True).start()
        elif state.pool is not None:
            state.pool.submit(run)
        else:
            run()

    def submit_actor_task(self, spec: TaskSpec) -> list[ObjectRef]:
        return_ids = spec.return_ids()
        lr = 0 if refcounting_suppressed() else 1
        make = ObjectRef.counted if lr else ObjectRef
        for oid in return_ids:
            self.refs.add_owned(oid, self.worker_id, lineage_task=spec.task_id,
                                local_refs=lr)
        global_event_buffer().record(
            spec.task_id.hex(), spec.name, "SUBMITTED",
            worker_id=self.worker_id.hex(),
            actor_id=spec.actor_id.hex() if spec.actor_id else "",
            job_id=spec.job_id.hex())
        with self._lock:
            state = self._actors.get(spec.actor_id)
        if state is None or state.dead:
            reason = state.death_reason if state else "unknown actor"
            # The call never entered the mailbox: flagged never_sent so
            # serve's router may safely re-route it to a live replica.
            err = ActorDiedError(spec.actor_id.hex() if spec.actor_id else "",
                                 reason, never_sent=True)
            self._store_error(return_ids, err)
            return [make(oid, self.worker_id) for oid in return_ids]
        state.mailbox.put(spec)
        return [make(oid, self.worker_id) for oid in return_ids]

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        with self._lock:
            state = self._actors.get(actor_id)
        if state is None:
            return
        self._mark_actor_dead(state, "killed via kill()")
        state.mailbox.put(None)

    def _mark_actor_dead(self, state: _ActorState, reason: str) -> None:
        from ray_tpu.core import flight_recorder

        state.dead = True
        state.death_reason = reason
        if "killed via kill()" not in reason:  # intentional kills aren't failures
            flight_recorder.record("actor_death", reason=reason,
                                   actor_id=state.spec.actor_id.hex())
        with self._lock:
            if state.spec.name:
                self._named_actors.pop((state.spec.namespace, state.spec.name), None)
        # Fail everything still queued. Queued-but-unstarted calls are
        # never_sent: they provably did not execute on the dead actor.
        try:
            while True:
                item = state.mailbox.get_nowait()
                if item is not None:
                    self._store_error(
                        item.return_ids(),
                        ActorDiedError(state.spec.actor_id.hex(), reason,
                                       never_sent=True)
                    )
        except queue.Empty:
            pass

    def get_named_actor(self, name: str, namespace: str = "default") -> ActorID | None:
        with self._lock:
            return self._named_actors.get((namespace, name))

    def actor_is_alive(self, actor_id: ActorID) -> bool:
        with self._lock:
            st = self._actors.get(actor_id)
            return st is not None and not st.dead

    # ------------------------------------------------------------------ placement groups
    # (single-node semantics: bundles reserve base resources and expose
    # derived per-bundle resources; strategies are trivially satisfiable on
    # one node except STRICT_SPREAD)
    def create_placement_group(self, pg_id, bundles, strategy, name=None,
                               labels=None) -> None:
        if strategy == "STRICT_SPREAD" and len(bundles) > 1:
            self._pg_states[pg_id] = "FAILED"  # single node: can't spread
            return
        total_demand: dict[str, float] = {}
        for b in bundles:
            for k, v in b.items():
                total_demand[k] = total_demand.get(k, 0.0) + v
        self._pg_states[pg_id] = "PENDING"

        def reserve():
            try:
                ok = self.resources.acquire(total_demand, timeout=60.0)
            except ValueError:
                ok = False
            if not ok:
                if self._pg_states.get(pg_id) == "PENDING":
                    self._pg_states[pg_id] = "FAILED"
                return
            with self._lock:
                # remove() may have arrived while we were waiting to reserve
                if self._pg_states.get(pg_id) != "PENDING":
                    self.resources.release(total_demand)
                    return
                derived: dict[str, float] = {}
                for idx, b in enumerate(bundles):
                    for k, v in b.items():
                        derived[f"{k}_pg_{pg_id.hex()[:16]}_{idx}"] = v
                    derived[f"bundle_pg_{pg_id.hex()[:16]}_{idx}"] = 1000.0
                self.resources.add_resources(derived)
                self._pg_reserved[pg_id] = (total_demand, derived)
                self._pg_states[pg_id] = "CREATED"

        threading.Thread(target=reserve, daemon=True).start()

    def remove_placement_group(self, pg_id) -> None:
        with self._lock:
            # Mark first so a reserve() still blocked in acquire() aborts
            # instead of resurrecting a removed PG.
            self._pg_states[pg_id] = "REMOVED"
            reserved = self._pg_reserved.pop(pg_id, None)
        if reserved is None:
            return
        base, derived = reserved
        self.resources.remove_resources(derived)
        self.resources.release(base)

    def placement_group_state(self, pg_id) -> str:
        return self._pg_states.get(pg_id, "PENDING")

    # ------------------------------------------------------------------ KV
    # (parity with the cluster runtime's head-backed KV — reference:
    # gcs_kv_manager.cc internal KV; local mode keeps tables in-process)
    def kv_put(self, key: str, value: bytes, ns: str = "default",
               overwrite: bool = True) -> bool:
        with self._lock:
            table = self._kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            return True

    def kv_get(self, key: str, ns: str = "default") -> bytes | None:
        with self._lock:
            return self._kv.get(ns, {}).get(key)

    def kv_del(self, key: str, ns: str = "default") -> None:
        with self._lock:
            self._kv.get(ns, {}).pop(key, None)

    def kv_keys(self, prefix: str = "", ns: str = "default") -> list[str]:
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    # ------------------------------------------------------------------ misc
    def node_summary(self) -> dict:
        """Single-node aggregate matching the cluster runtime's shape."""
        return {
            "nodes_total": 1, "nodes_alive": 1,
            "resources": self.resources.totals(),
            "available": self.resources.available(),
        }

    def state_snapshot(self, parts: list | None = None) -> dict:
        """Cluster-state view for the state API (reference: the GCS-backed
        sources behind python/ray/util/state/api.py — GcsTaskManager for tasks,
        actor/node/PG tables for the rest). ``parts`` is accepted for
        interface parity with the cluster runtime; the local tables are
        small enough that the full dict is always built."""
        with self._lock:
            actors = {
                aid.hex(): {
                    "state": ("DEAD" if st.dead else "ALIVE"),
                    "name": st.spec.name,
                    "namespace": st.spec.namespace,
                    "node_id": "local",
                    "resources": st.spec.resources,
                    "restarts": st.restarts_used,
                    "death_reason": st.death_reason,
                }
                for aid, st in self._actors.items()
            }
            pgs = {
                pg_id.hex(): {"state": state}
                for pg_id, state in self._pg_states.items()
            }
        return {
            "nodes": {
                "local": {
                    "alive": True,
                    "resources": self.resources.totals(),
                    "available": self.resources.available(),
                    "labels": {},
                }
            },
            "actors": actors,
            "placement_groups": pgs,
            "workers": {self.worker_id.hex(): {"node_id": "local", "type": "driver"}},
            "objects": self.store.stats(),
        }

    def cluster_resources(self) -> dict[str, float]:
        return self.resources.totals()

    def available_resources(self) -> dict[str, float]:
        return self.resources.available()

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        with self._lock:
            actors = list(self._actors.values())
        for st in actors:
            st.mailbox.put(None)
        self._task_pool.shutdown(wait=False, cancel_futures=True)
