import sys, jax, jax.numpy as jnp, numpy as np
from ray_tpu.models.llama import LlamaConfig, init_params, forward
from ray_tpu.ops.norms import rms_norm
cfg = LlamaConfig(vocab_size=32128, hidden_size=2048, intermediate_size=8192,
    num_layers=2, num_heads=32, num_kv_heads=8, head_dim=64,
    max_seq_len=2048, tie_embeddings=True, dtype="bfloat16")
params = jax.jit(lambda k: init_params(cfg, k))(jax.random.PRNGKey(0))
rng = np.random.default_rng(0)
tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 2048), dtype=np.int32))

def body_only(p, t):
    # forward but stop before lm head: reuse forward by taking logits? no - sum of hidden
    import ray_tpu.models.llama as L
    from jax import lax
    from functools import partial
    b, s = t.shape
    positions = jnp.arange(s)
    x = p["embed_tokens"][t]
    inv_freq = L.rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    layer_fn = partial(L._layer, cfg, inv_freq=inv_freq, positions=positions,
                       attn_impl="blockwise", sp_axis=None)
    x, _ = lax.scan(lambda x, lp: (layer_fn(x, lp), None), x, p["layers"])
    x = rms_norm(x, p["final_norm"], cfg.norm_eps)
    return x.astype(jnp.float32).sum()

val, grads = jax.jit(jax.value_and_grad(body_only))(params, tokens)
nans = [jax.tree_util.keystr(p) for p,g in jax.tree_util.tree_flatten_with_path(grads)[0]
        if bool(jnp.isnan(g.astype(jnp.float32)).any())]
print("body-only:", float(val), "nans:", nans, flush=True)
