"""Object serialization for the object store and RPC layer.

Capability parity with the reference's serialization layer
(reference: python/ray/_private/serialization.py + cloudpickle/): arbitrary
Python objects via cloudpickle, with a zero-copy fast path for numpy / JAX
host arrays (raw buffer + dtype/shape header instead of pickling), and
out-of-band ObjectRef tracking so refs nested inside arguments/returns are
discovered for ownership/refcounting.
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import cloudpickle
import numpy as np

# Wire format: 1-byte tag + payload.
_TAG_PICKLE = b"P"
_TAG_NDARRAY = b"N"
_TAG_RAW = b"R"  # pre-serialized bytes passthrough


def _extract_refs(obj: Any) -> list:
    """Find ObjectRefs nested anywhere in ``obj`` (via pickle traversal)."""
    from ray_tpu.core.object_ref import ObjectRef

    found: list = []

    class _Scanner(cloudpickle.CloudPickler):
        def persistent_id(self, o):  # noqa: N802 - pickle API name
            if isinstance(o, ObjectRef):
                found.append(o)
                return ("ref", len(found) - 1)
            return None

    _Scanner(io.BytesIO()).dump(obj)
    return found


def find_nested_refs(obj: Any) -> list:
    try:
        return _extract_refs(obj)
    except Exception:
        return []


def serialize(obj: Any) -> bytes:
    """Serialize ``obj`` to a self-describing byte string."""
    if isinstance(obj, np.ndarray) and obj.dtype != object:
        header = cloudpickle.dumps((obj.dtype.str, obj.shape))
        buf = np.ascontiguousarray(obj)
        return (
            _TAG_NDARRAY
            + len(header).to_bytes(4, "little")
            + header
            + memoryview(buf).cast("B").tobytes()
        )
    return _TAG_PICKLE + cloudpickle.dumps(obj)


def deserialize(data: bytes | memoryview) -> Any:
    data = bytes(data) if isinstance(data, memoryview) else data
    tag, payload = data[:1], data[1:]
    if tag == _TAG_NDARRAY:
        hlen = int.from_bytes(payload[:4], "little")
        dtype_str, shape = cloudpickle.loads(payload[4 : 4 + hlen])
        arr = np.frombuffer(payload[4 + hlen :], dtype=np.dtype(dtype_str)).reshape(shape)
        return arr.copy()  # writable
    if tag == _TAG_PICKLE:
        return cloudpickle.loads(payload)
    if tag == _TAG_RAW:
        return payload
    raise ValueError(f"unknown serialization tag {tag!r}")


def dumps_function(fn) -> bytes:
    """Serialize a function/class definition for code shipping (reference:
    python/ray/_private/function_manager.py ships pickled defs via GCS KV)."""
    return cloudpickle.dumps(fn, protocol=pickle.HIGHEST_PROTOCOL)


def loads_function(data: bytes):
    return cloudpickle.loads(data)
