"""Thousand-node control-plane scale bench: simulated fleets vs the head.

Stands up SIMULATED fleets (core/cluster/sim_fleet.py — real NodeDaemons
over the real RPC stack, fake inventories, one timer wheel, no forked
workers) against a real head on this box and measures where the head's
fast paths saturate, BEFORE and AFTER the scale optimizations:

- ``before``: full-map heartbeats every beat, linear ``_pick_node``/
  ``_assign_bundles`` scans, per-event-per-subscriber pubsub.
- ``after``: delta heartbeats (changed keys only), indexed scheduling
  (CPU-free heap + label inverted index + free-sum cache), coalesced
  pubsub fan-out (one batched notify per subscriber per window).

Phases:

- ``registration`` — cold-register storms at each fleet size: wall time,
  nodes/s, failures.
- ``heartbeat`` — steady-state beat ingest across fleet sizes with 20%
  of nodes churning availability each period; reports head heartbeat
  duty (handler-seconds per wall-second), per-beat cost, beat loss,
  wheel lag, head loop lag. The knee is the duty-derived capacity
  ``nodes / duty`` — the fleet size one head-core could sustain at this
  beat rate. A PR-6 chaos drill (daemon.tick kill rules) fires mid-run
  at the largest AFTER fleet; recovery (head declares deaths, keeps
  answering, survivors keep beating) is gated.
- ``placement`` — actor-placement storms (register_actor →
  place_actor → actor_ready round trips against sim daemons) and PG
  churn (create/ready-poll/remove with real 2PC prepare/commit);
  reports head microseconds per placement op from the per-method RPC
  ledger.
- ``fanout`` — N subscriber connections × M events through the pubsub
  plane; delivery wall time and completeness.
- ``autoscaler`` — pending lease demands injected on K daemons;
  convergence = demand burst → visible in the head's ``cluster_load``
  aggregation (bounded by one beat period).
- ``ingest`` — streaming-split throughput with the bounded per-consumer
  prefetch, fast and deliberately-slow consumers; stall/empty-poll
  counters and the queue bound are checked. (Sim nodes carry no data
  plane; this phase prices the ingest backpressure machinery itself.)

Run: python devbench/scale_bench.py [--quick]
Writes PERF_SCALE.json (quick runs refresh under ``quick_refresh``).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fresh_config(**env):
    from ray_tpu.utils import config as config_mod

    for k, v in env.items():
        os.environ[k] = str(v)
    config_mod.set_config(config_mod.Config.load())


def _mode_env(mode: str) -> dict:
    on = mode == "after"
    return {
        "RTPU_DELTA_HEARTBEAT_ENABLED": 1 if on else 0,
        "RTPU_INDEXED_SCHEDULER_ENABLED": 1 if on else 0,
        "RTPU_PUBSUB_BATCH_WINDOW_S": 0.005 if on else 0,
        "RTPU_HEAD_METRICS_PERIOD_S": 0.25,
    }


def _io():
    from ray_tpu.core.cluster.protocol import EventLoopThread

    return EventLoopThread.get()


def _wait(pred, timeout: float, desc: str) -> float:
    t0 = time.monotonic()
    deadline = t0 + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {desc}")
        time.sleep(0.02)
    return time.monotonic() - t0


async def _rpc_stats(head) -> dict:
    return {m: list(v) for m, v in head.rpc.stats.items()}


def _handler_seconds(stats: dict, methods=None) -> float:
    return sum(v[1] for m, v in stats.items()
               if methods is None or m in methods)


# Synthetic inventory width: production nodes advertise far more than
# CPU/TPU (memory, object store, PG-derived bundle keys); full-map
# heartbeats pay for every key every beat, deltas only for changed ones.
_EXTRA_KEYS = {f"bundle_slot_{i}": 1.0 for i in range(24)}
_EXTRA_KEYS.update({"memory": 64.0e9, "object_store_memory": 16.0e9})


def _start_cluster(n_nodes: int, hb_period: float, **env):
    """Fresh head + sim fleet under a fresh config.

    The head gets its OWN loop thread (not the process io-loop singleton
    the daemons and drivers share): everything head-side — frame decode,
    dispatch, handlers, reply encode, health/publish loops — then runs on
    one dedicated thread, so ``time.thread_time()`` on that thread is the
    head's exact CPU bill. Handler-only timing (rpc.stats) misses the
    msgpack decode of N full resource maps per period, which is most of
    what delta heartbeats delete.

    Returns (head, head_io, fleet).
    """
    _fresh_config(**env)
    from ray_tpu.core.cluster.head import HeadServer
    from ray_tpu.core.cluster.protocol import EventLoopThread
    from ray_tpu.core.cluster.sim_fleet import SimFleet

    head_io = EventLoopThread()
    head = HeadServer("127.0.0.1", 0)
    head_io.run(head.start())
    fleet = SimFleet.launch(head.rpc.host, head.rpc.port, n_nodes=n_nodes,
                            heartbeat_period_s=hb_period,
                            extra_resources=_EXTRA_KEYS)
    return head, head_io, fleet


def _stop_cluster(head, head_io, fleet):
    fleet.shutdown()
    head_io.run(head.stop(), timeout=60)
    head_io.loop.call_soon_threadsafe(head_io.loop.stop)
    head_io._thread.join(timeout=10)


async def _head_cpu_s() -> float:
    """CPU seconds consumed by the calling thread — run on the head's
    loop thread, this is the head's total control-plane cost."""
    return time.thread_time()


async def _churn_loop(fleet, period_s: float, stop: asyncio.Event):
    """Mutate 20% of the fleet's availability each period — realistic
    steady-state (some nodes busy) so AFTER-mode deltas are non-empty."""
    tick = 0
    while not stop.is_set():
        tick += 1
        for d in fleet.daemons[::5]:
            d.available["CPU"] = d.resources["CPU"] - float(tick % 4)
        try:
            await asyncio.wait_for(stop.wait(), period_s)
        except asyncio.TimeoutError:
            pass


def _phase_heartbeat(counts, hb_period: float, window_s: float,
                     mode: str, chaos_at_max: bool) -> dict:
    points = []
    chaos = None
    for n in counts:
        head, head_io, fleet = _start_cluster(n, hb_period, **_mode_env(mode))
        io = _io()
        try:
            _wait(lambda: fleet.wheel.fired >= len(fleet.daemons),
                  30, "first full beat round")
            # Membership convergence must finish BEFORE the window: each
            # daemon's first sent beat (idle-skip defers it past the idle
            # gap in after mode) pulls the full O(n) peers map once.
            # Measuring that one-time O(n^2) boot storm inside the window
            # would bill steady-state sync for convergence cost — and
            # only in after mode, since before-mode daemons beat (and
            # converge) immediately, before the window opens.
            _wait(lambda: fleet.hb_stats()["sent"] >= len(fleet.daemons),
                  60, "peers-map convergence")
            stop_evt = io.run(_make_event())
            churn = io.spawn(_churn_loop(fleet, hb_period, stop_evt))
            s0 = head_io.run(_rpc_stats(head))
            cpu0 = head_io.run(_head_cpu_s())
            hb0 = fleet.hb_stats()
            fired0 = fleet.wheel.fired
            t0 = time.monotonic()
            time.sleep(window_s)
            s1 = head_io.run(_rpc_stats(head))
            cpu1 = head_io.run(_head_cpu_s())
            hb1 = fleet.hb_stats()
            fired1 = fleet.wheel.fired
            wall = time.monotonic() - t0
            io.run(_set_event(stop_evt))
            churn.result(timeout=10)
            beats = hb1["sent"] - hb0["sent"]
            hb_calls = s1.get("heartbeat", [0, 0, 0])[0] - \
                s0.get("heartbeat", [0, 0, 0])[0]
            hb_secs = s1.get("heartbeat", [0, 0, 0])[1] - \
                s0.get("heartbeat", [0, 0, 0])[1]
            duty = (cpu1 - cpu0) / wall
            loss = (hb1["failed"] - hb0["failed"]) / max(1, beats)
            # Wheel-delivery normalization: on this shared single core the
            # wheel itself can fall behind at the biggest counts, so the
            # head only saw fire_ratio of the load a real fleet (with its
            # own cores) would impose. Scale the capacity extrapolation by
            # it — deflating the saturated points rather than letting an
            # under-driven baseline inflate its own capacity. Skipped idle
            # beats are NOT missing load (the fire happened; the daemon
            # chose to send nothing), so the after-mode accounting is
            # untouched at counts the wheel keeps pace with.
            nominal_fires = wall * len(fleet.daemons) / hb_period
            fire_ratio = min(1.0, (fired1 - fired0) / max(1.0, nominal_fires))
            point = {
                "nodes": len(fleet.daemons),
                "beats": beats,
                "beat_rate_hz": round(beats / wall, 1),
                "head_hb_calls": hb_calls,
                "head_duty": round(duty, 4),
                "handler_us_per_beat": round(
                    1e6 * hb_secs / max(1, hb_calls), 1),
                "head_us_per_beat": round(
                    1e6 * (cpu1 - cpu0) / max(1, beats), 1),
                "loss_rate": round(loss, 5),
                "wheel_max_lag_s": fleet.hb_stats()["wheel_max_lag_s"],
                "head_loop_lag_max_s": round(head.loop_lag_max_s, 4),
                "wheel_fire_ratio": round(fire_ratio, 4),
                "capacity_nodes_per_core": (
                    round(fire_ratio * len(fleet.daemons) / duty)
                    if duty > 0 else None),
                "wire": {k: hb1[k] - hb0[k]
                         for k in ("full", "delta", "empty", "skipped",
                                   "resync")},
            }
            points.append(point)
            if chaos_at_max and n == max(counts):
                chaos = _chaos_drill(head, head_io, fleet, hb_period)
        finally:
            _stop_cluster(head, head_io, fleet)
    return {"mode": mode, "hb_period_s": hb_period, "points": points,
            **({"chaos": chaos} if chaos else {})}


async def _make_event() -> asyncio.Event:
    return asyncio.Event()


async def _set_event(evt: asyncio.Event):
    evt.set()


def _chaos_drill(head, head_io, fleet, hb_period: float) -> dict:
    """PR-6 chaos ride-along: daemon.tick kill rules take out ~5% of the
    fleet mid-run; the head must declare exactly those nodes dead and
    keep answering (no wedge), survivors keep beating at <1% loss."""
    from ray_tpu.chaos import injector

    n = len(fleet.daemons)
    kill_n = max(3, n // 20)
    victims = {d.node_id for d in fleet.daemons[:kill_n]}
    pattern = "|".join(sorted(victims))
    injector.reset_for_tests()
    injector.install([{"point": "daemon.tick", "action": "kill",
                       "match": {"node": f"^({pattern})$"},
                       "count": kill_n, "mark": None}])
    hb0 = fleet.hb_stats()
    t0 = time.monotonic()

    async def _alive_count():
        return sum(1 for i in head.nodes.values() if i.alive)

    try:
        declare_s = _wait(lambda: head_io.run(_alive_count()) <= n - kill_n,
                          30 + 10 * hb_period,
                          "head to declare chaos-killed nodes dead")
    except TimeoutError:
        declare_s = None
    finally:
        injector.reset_for_tests()
    # Head responsive after the kills?
    status = head_io.run(head._head_status(None), timeout=10)
    hb1 = fleet.hb_stats()
    survivor_beats = hb1["sent"] - hb0["sent"]
    survivor_fail = hb1["failed"] - hb0["failed"]
    return {
        "killed": kill_n,
        "declared_dead_s": (round(declare_s, 2)
                            if declare_s is not None else None),
        "head_responsive": bool(status.get("boot_id")),
        "head_loop_lag_max_s": round(head.loop_lag_max_s, 4),
        "survivor_loss_rate": round(
            survivor_fail / max(1, survivor_beats), 5),
        "wall_s": round(time.monotonic() - t0, 2),
        "recovered": declare_s is not None and bool(status.get("boot_id")),
    }


async def _actor_storm(head, n_actors: int, conc: int) -> dict:
    from ray_tpu.core.cluster.protocol import AsyncRpcClient

    cli = AsyncRpcClient(head.rpc.host, head.rpc.port)
    await cli.connect()
    loop = asyncio.get_running_loop()
    sem = asyncio.Semaphore(conc)
    run = uuid.uuid4().hex[:6]

    async def one(i):
        async with sem:
            r = await cli.call(
                "register_actor", actor_id=f"bench-{run}-{i}", spec_blob=b"",
                resources={"CPU": 1.0}, name=None, namespace="bench",
                max_restarts=0, req_id=f"bench-{run}-{i}", timeout=60)
            return bool(r.get("ok"))

    t0 = loop.time()
    oks = await asyncio.gather(*[one(i) for i in range(n_actors)])
    placed = sum(oks)
    # Wait until the placements fully round-trip (daemon ACKs actor_ready),
    # polling through the parts-scoped state API (which this also exercises
    # at fleet scale — the poll must not pay for the node table).
    deadline = loop.time() + 60
    alive = 0
    while loop.time() < deadline:
        snap = await cli.call("state_snapshot", parts=["actors"], timeout=30)
        alive = sum(1 for aid, a in (snap.get("actors") or {}).items()
                    if aid.startswith(f"bench-{run}-")
                    and a["state"] == "ALIVE")
        if alive >= placed:
            break
        await asyncio.sleep(0.05)
    wall = loop.time() - t0
    await cli.close()
    return {"requested": n_actors, "placed": placed, "alive": alive,
            "wall_s": round(wall, 3),
            "actors_per_s": round(placed / wall, 1)}


async def _pg_churn(head, rounds: int, bundles_per: int, conc: int) -> dict:
    from ray_tpu.core.cluster.protocol import AsyncRpcClient

    cli = AsyncRpcClient(head.rpc.host, head.rpc.port)
    await cli.connect()
    loop = asyncio.get_running_loop()
    sem = asyncio.Semaphore(conc)
    run = uuid.uuid4().hex[:6]
    created = removed = 0

    async def one(i):
        nonlocal created, removed
        pg_id = f"bench-pg-{run}-{i}"
        async with sem:
            r = await cli.call(
                "create_placement_group", pg_id=pg_id,
                bundles=[{"CPU": 1.0}] * bundles_per, strategy="PACK",
                req_id=pg_id, timeout=60)
            if not r.get("ok"):
                return
            for _ in range(400):
                st = await cli.call("placement_group_state", pg_id=pg_id,
                                    timeout=30)
                if st.get("state") == "CREATED":
                    created += 1
                    break
                await asyncio.sleep(0.02)
            await cli.call("remove_placement_group", pg_id=pg_id, timeout=30)
            removed += 1

    t0 = loop.time()
    await asyncio.gather(*[one(i) for i in range(rounds)])
    wall = loop.time() - t0
    await cli.close()
    return {"rounds": rounds, "created": created, "removed": removed,
            "bundles_per": bundles_per, "wall_s": round(wall, 3),
            "pgs_per_s": round(created / wall, 1)}


def _phase_placement(n_nodes: int, n_actors: int, pg_rounds: int,
                     mode: str) -> dict:
    head, head_io, fleet = _start_cluster(n_nodes, 1.0, **_mode_env(mode))
    io = _io()
    try:
        s0 = head_io.run(_rpc_stats(head))
        actors = io.run(_actor_storm(head, n_actors, conc=24), timeout=300)
        s1 = head_io.run(_rpc_stats(head))
        pgs = io.run(_pg_churn(head, pg_rounds, bundles_per=4, conc=8),
                     timeout=300)
        s2 = head_io.run(_rpc_stats(head))
        actor_secs = _handler_seconds(
            s1, {"register_actor", "actor_ready"}) - _handler_seconds(
            s0, {"register_actor", "actor_ready"})
        pg_secs = _handler_seconds(
            s2, {"create_placement_group", "placement_group_state",
                 "remove_placement_group"}) - _handler_seconds(
            s1, {"create_placement_group", "placement_group_state",
                 "remove_placement_group"})
        return {
            "mode": mode, "nodes": len(fleet.daemons),
            "actor_storm": actors,
            "head_us_per_actor": round(
                1e6 * actor_secs / max(1, actors["placed"]), 1),
            "pg_churn": pgs,
            "head_us_per_pg": round(1e6 * pg_secs / max(1, pgs["created"]),
                                    1),
        }
    finally:
        _stop_cluster(head, head_io, fleet)


async def _fanout(head, head_io, n_subs: int, n_events: int) -> dict:
    from ray_tpu.core.cluster.protocol import AsyncRpcClient

    loop = asyncio.get_running_loop()
    received = [0]
    clients = []

    def on_pub(**kw):
        received[0] += 1

    def on_batch(events=None, **kw):
        received[0] += len(events or [])

    for _ in range(n_subs):
        c = AsyncRpcClient(head.rpc.host, head.rpc.port)
        await c.connect()
        c.on_notify("pub", on_pub)
        c.on_notify("pub_batch", on_batch)
        await c.call("subscribe", channel="bench-fan")
        clients.append(c)
    expected = n_subs * n_events

    def _pub(seq):
        # publish() touches head connections — must run on the HEAD's loop.
        return asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
            head.publish("bench-fan", seq=seq), head_io.loop))

    t0 = loop.time()
    for e in range(n_events):
        await _pub(e)
    publish_wall = loop.time() - t0
    deadline = loop.time() + 60
    while received[0] < expected and loop.time() < deadline:
        await asyncio.sleep(0.01)
    deliver_wall = loop.time() - t0
    for c in clients:
        await c.close()
    return {"subscribers": n_subs, "events": n_events,
            "delivered": received[0], "expected": expected,
            "publish_wall_s": round(publish_wall, 3),
            "deliver_wall_s": round(deliver_wall, 3),
            "notifications_per_s": round(received[0] / deliver_wall, 0)}


def _phase_fanout(n_subs: int, n_events: int, mode: str) -> dict:
    head, head_io, fleet = _start_cluster(5, 1.0, **_mode_env(mode))
    try:
        out = _io().run(_fanout(head, head_io, n_subs, n_events),
                        timeout=180)
        out["mode"] = mode
        return out
    finally:
        _stop_cluster(head, head_io, fleet)


async def _inject_demands(fleet, k: int) -> int:
    from ray_tpu.core.cluster.node_daemon import _PendingLease

    loop = asyncio.get_running_loop()
    for d in fleet.daemons[:k]:
        fut = loop.create_future()
        d._pending.append(_PendingLease({"TPU": 8.0}, fut, "", "", count=2))
    return k


def _phase_autoscaler(n_nodes: int, k_demand: int, hb_period: float) -> dict:
    head, head_io, fleet = _start_cluster(n_nodes, hb_period,
                                          **_mode_env("after"))
    io = _io()
    try:
        _wait(lambda: fleet.wheel.fired >= len(fleet.daemons),
              30, "first beat round")
        io.run(_inject_demands(fleet, k_demand))
        t0 = time.monotonic()

        def visible():
            load = head_io.run(head._cluster_load(None))
            return len(load["pending_demands"]) >= 2 * k_demand

        converge_s = _wait(visible, 30 + 4 * hb_period,
                           "demand burst visible in cluster_load")
        return {"nodes": len(fleet.daemons), "demand_nodes": k_demand,
                "demands": 2 * k_demand,
                "convergence_s": round(converge_s, 3),
                "hb_period_s": hb_period,
                "within_two_beats": converge_s <= 2 * hb_period + 1.0}
    finally:
        _stop_cluster(head, head_io, fleet)


def _phase_ingest(quick: bool) -> dict:
    from ray_tpu.data.iterator import SplitCoordinator

    _fresh_config(RTPU_DATA_SPLIT_PREFETCH_BLOCKS=4)
    blocks = 240 if quick else 800
    results = {}
    for n_consumers, slow_one in ((2, False), (8, True)):
        class _DS:
            def iter_block_refs(self):
                for i in range(blocks):
                    yield (i, {})

        coord = SplitCoordinator(_DS(), n=n_consumers, equal=False)
        got = [0] * n_consumers
        max_q = [0]

        def consume(split, slow):
            while True:
                with coord._lock:
                    max_q[0] = max(max_q[0],
                                   max(len(q) for q in coord._queues))
                status, _ = coord.get_next(split)
                if status == "done":
                    return
                if status == "block":
                    got[split] += 1
                    if slow:
                        time.sleep(0.002)
                elif status == "empty":
                    time.sleep(0.0005)

        threads = [threading.Thread(
            target=consume, args=(i, slow_one and i == 0), daemon=True)
            for i in range(n_consumers)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        wall = time.monotonic() - t0
        results[f"consumers_{n_consumers}"] = {
            "blocks": sum(got), "wall_s": round(wall, 3),
            "blocks_per_s": round(sum(got) / wall, 0),
            "producer_stalls": coord.stalls,
            "consumer_empty_polls": coord.empty_polls,
            "max_queue_depth": max_q[0],
            "prefetch_bound": 4,
            "bounded": max_q[0] <= 4,
        }
    return results


def _phase_registration(counts, hb_period: float) -> dict:
    points = []
    for n in counts:
        head, head_io, fleet = _start_cluster(n, hb_period,
                                              **_mode_env("after"))
        try:
            points.append({
                "nodes": len(fleet.daemons),
                "failures": fleet.register_failures,
                "wall_s": round(fleet.register_wall_s, 3),
                "registrations_per_s": round(
                    len(fleet.daemons) / max(1e-9, fleet.register_wall_s)),
            })
        finally:
            _stop_cluster(head, head_io, fleet)
    return {"points": points}


def _knee(points, duty_limit=0.5, loss_limit=0.01):
    """First swept fleet size where the head left its comfort zone, or
    None when the whole sweep stayed inside it."""
    for p in points:
        if p["head_duty"] > duty_limit or p["loss_rate"] > loss_limit:
            return p["nodes"]
    return None


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    if quick:
        hb_counts, hb_period, window = [60, 150], 0.25, 4.0
        pl_nodes, n_actors, pg_rounds = 80, 60, 12
        subs, events = 40, 40
        as_nodes, as_k = 80, 20
    else:
        hb_counts, hb_period, window = [100, 250, 500, 750], 0.5, 8.0
        pl_nodes, n_actors, pg_rounds = 500, 150, 30
        subs, events = 150, 100
        as_nodes, as_k = 300, 50

    reg = _phase_registration(hb_counts, 1.0)
    hb = {m: _phase_heartbeat(hb_counts, hb_period, window, m,
                              chaos_at_max=(m == "after"))
          for m in ("before", "after")}
    pl = {m: _phase_placement(pl_nodes, n_actors, pg_rounds, m)
          for m in ("before", "after")}
    fan = {m: _phase_fanout(subs, events, m) for m in ("before", "after")}
    autos = _phase_autoscaler(as_nodes, as_k, hb_period)
    ingest = _phase_ingest(quick)

    def _cap(mode):
        pts = hb[mode]["points"]
        caps = [p["capacity_nodes_per_core"] for p in pts
                if p["capacity_nodes_per_core"]]
        return max(caps) if caps else None

    cap_before, cap_after = _cap("before"), _cap("after")
    hb_ratio = (cap_after / cap_before
                if cap_before and cap_after else None)
    pl_ratio = (pl["before"]["head_us_per_actor"] /
                pl["after"]["head_us_per_actor"]
                if pl["after"]["head_us_per_actor"] else None)
    chaos = hb["after"].get("chaos") or {}
    after_top = hb["after"]["points"][-1]
    acceptance = {
        "sim_fleet_500_nodes": max(p["nodes"]
                                   for p in hb["after"]["points"]) >= (
                                       500 if not quick else 100),
        "heartbeat_capacity_2x": hb_ratio is not None and hb_ratio >= 2.0,
        "placement_head_cost_2x": pl_ratio is not None and pl_ratio >= 2.0,
        "heartbeat_loss_under_1pct": after_top["loss_rate"] < 0.01,
        "chaos_kills_recovered_no_wedge": bool(chaos.get("recovered")),
        "fanout_no_loss_batched": (fan["after"]["delivered"] ==
                                   fan["after"]["expected"]),
        "autoscaler_converged": bool(autos["within_two_beats"]),
        "ingest_prefetch_bounded": all(
            v["bounded"] for v in ingest.values()),
    }
    report = {
        "bench": "scale",
        "quick": quick,
        "phases": {
            "registration": reg,
            "heartbeat": hb,
            "placement": pl,
            "fanout": fan,
            "autoscaler": autos,
            "ingest": ingest,
        },
        "knees": {
            "heartbeat_duty_knee_nodes": {
                m: _knee(hb[m]["points"]) for m in ("before", "after")},
            "heartbeat_capacity_nodes_per_core": {
                "before": cap_before, "after": cap_after,
                "ratio": round(hb_ratio, 2) if hb_ratio else None},
            "placement_head_us_per_actor": {
                "before": pl["before"]["head_us_per_actor"],
                "after": pl["after"]["head_us_per_actor"],
                "ratio": round(pl_ratio, 2) if pl_ratio else None},
            "fanout_deliver_wall_s": {
                m: fan[m]["deliver_wall_s"] for m in ("before", "after")},
        },
        "acceptance": acceptance,
        "provenance": {
            "date": time.strftime("%Y-%m-%d %H:%M:%S"),
            "cpus": os.cpu_count(),
            "loadavg": list(os.getloadavg()),
            "box_note": (
                "single host, one core: head + sim daemons + drivers share "
                "one process (daemons on the io-loop thread, real RPC over "
                "loopback). Head cost is measured from the per-method "
                "handler-time ledger (protocol.RpcServer.stats), so the "
                "duty/capacity numbers isolate the head's share of the "
                "core. capacity_nodes_per_core extrapolates the fleet one "
                "head-core sustains at this beat rate and inventory width "
                "(26 resource keys + 20% availability churn). Sim nodes "
                "have no data plane, so the ingest phase prices the "
                "bounded-prefetch machinery locally, not cross-node."),
        },
    }
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PERF_SCALE.json")
    doc = report
    if quick and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                existing = json.load(f)
            if not existing.get("quick"):
                existing["quick_refresh"] = report
                doc = existing
        except Exception:
            pass
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    return report


if __name__ == "__main__":
    rep = run_bench(quick="--quick" in sys.argv[1:])
    print(json.dumps(rep, indent=2))
