"""Streaming anomaly detectors over watchdog series: O(1) state per series.

Every rule consumes samples AS THEY ARRIVE (no batch re-scan): state per
series is a handful of floats (EWMA mean, EWMA absolute deviation, breach
streak, last-trip instant), so a head ingesting 1000 nodes' samples pays a
few arithmetic ops per sample — the fleet-size regime ROADMAP item 5
targets. The shared firing discipline lives in :class:`Rule`:

- **warmup**: no verdicts until ``warmup`` samples built a baseline (a
  fresh series' first steps must not be "anomalous vs nothing");
- **debounce**: ``debounce`` CONSECUTIVE breaching samples before a trip
  (one garbage-collection hiccup is not an incident);
- **cooldown**: after a trip the series is muted for ``cooldown_s`` (the
  watchdog captures evidence once, not once per sample while the incident
  is live).

Detector families (rule -> series, built in :func:`build_rules`):

- :class:`SpikeRule` — robust z-score (EWMA mean + EWMA |dev|, the
  streaming stand-in for median/MAD) AND a ratio guard ``value >
  ratio * mean`` so microscopic-scale series can't trip on noise. Covers
  train step-time drift, per-(op,group) collective-latency outliers, serve
  p99 TTFT/TPOT spikes, and node heartbeat-gap jitter.
- :class:`ThresholdRule` — absolute level. Covers shed/expiry rate (the
  healthy baseline is exactly zero, so "above X/s" is the right shape).
- :class:`DerivativeRule` — EWMA of d(value)/dt above a floor. Covers
  router queue growth (a queue LEVEL is fine; sustained growth is the
  death spiral).
- :class:`SlopeRule` — least-squares slope over the series' rolling tail.
  Covers per-process RSS leak detection (monotone drift that never looks
  like a spike).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field


@dataclass
class Trip:
    rule: str
    kind: str  # train | collective | serve | node | memory
    series: object  # timeseries.Series
    ts: float
    value: float
    baseline: float
    reason: str


@dataclass
class _SeriesState:
    n: int = 0
    mean: float = 0.0
    dev: float = 0.0
    streak: int = 0
    last_trip: float = -1e18
    prev: tuple | None = None  # (ts, value) for derivative rules
    tail: deque = field(default_factory=lambda: deque(maxlen=64))


class Rule:
    """Shared warmup/debounce/cooldown machinery; subclasses implement
    ``_breach(state, ts, value) -> (breaching, baseline, detail)`` and must
    keep their own state update O(1)."""

    kind = "generic"

    def __init__(self, name: str, series: tuple[str, ...],
                 warmup: int = 10, debounce: int = 2,
                 cooldown_s: float = 30.0):
        self.name = name
        self.series_names = tuple(series)
        self.warmup = int(warmup)
        self.debounce = max(1, int(debounce))
        self.cooldown_s = float(cooldown_s)
        self._state: dict = {}

    def matches(self, series_name: str) -> bool:
        return series_name in self.series_names

    def drop_source(self, source: str) -> None:
        """Forget a dead reporter's per-series state (paired with
        SeriesStore.drop_source: the rings are bounded, detector state
        must be too — and a recycled key must not inherit a dead
        process's baseline)."""
        for key in [k for k in self._state if k.source == source]:
            self._state.pop(key, None)

    def drop_key(self, key) -> None:
        self._state.pop(key, None)

    def update(self, series, ts: float, value: float) -> Trip | None:
        st = self._state.get(series.key)
        if st is None:
            st = self._state[series.key] = _SeriesState()
        breaching, baseline, detail = self._breach(st, ts, value)
        st.n += 1
        if st.n <= self.warmup:
            st.streak = 0
            return None
        if ts - st.last_trip < self.cooldown_s:
            return None
        if not breaching:
            st.streak = 0
            return None
        st.streak += 1
        if st.streak < self.debounce:
            return None
        st.streak = 0
        st.last_trip = ts
        return Trip(rule=self.name, kind=self.kind, series=series, ts=ts,
                    value=value, baseline=baseline,
                    reason=f"{series.key.name} {detail}")

    # subclass hook
    def _breach(self, st: _SeriesState, ts: float,
                value: float) -> tuple[bool, float, str]:
        raise NotImplementedError


class SpikeRule(Rule):
    """Robust-z high-side spike vs the series' own streaming baseline."""

    def __init__(self, name: str, series: tuple[str, ...], kind: str,
                 z: float = 6.0, ratio: float = 2.0, abs_floor: float = 0.0,
                 alpha: float = 0.08, **kw):
        super().__init__(name, series, **kw)
        self.kind = kind
        self.z = float(z)
        self.ratio = float(ratio)
        self.abs_floor = float(abs_floor)
        self.alpha = float(alpha)

    def _breach(self, st, ts, value):
        mean, dev = st.mean, st.dev
        if st.n == 0:
            st.mean, st.dev = value, 0.0
            return False, value, ""
        # Scale floor: 5 % of the baseline — a perfectly steady series'
        # dev collapses toward 0 and any wobble would be "infinite sigma".
        scale = max(dev * 1.4826, 0.05 * abs(mean), 1e-12)
        z = (value - mean) / scale
        breaching = (z > self.z and value > self.ratio * mean
                     and value > self.abs_floor)
        # WINSORIZED baseline update: adapt with the sample clamped to
        # mean ± 3·scale. A raw EWMA of |dev| would swallow the anomaly it
        # is judging — two spike samples inflate the deviation enough to
        # drop z below threshold before a debounce of 3 is ever reached
        # (the robust-z stops being robust exactly when it matters). With
        # the clamp, an outlier nudges the baseline instead of absorbing
        # into it, so a sustained regression keeps reading anomalous and
        # re-trips after every cooldown until it is actually fixed.
        lo, hi = mean - 3.0 * scale, mean + 3.0 * scale
        clamped = min(max(value, lo), hi)
        st.mean = mean + self.alpha * (clamped - mean)
        st.dev = dev + self.alpha * (abs(clamped - mean) - dev)
        return breaching, mean, (
            f"spiked to {value:.4g} (baseline {mean:.4g}, z={z:.1f})")


class ThresholdRule(Rule):
    """Absolute level breach — for series whose healthy value is ~0."""

    def __init__(self, name: str, series: tuple[str, ...], kind: str,
                 threshold: float, **kw):
        super().__init__(name, series, **kw)
        self.kind = kind
        self.threshold = float(threshold)

    def _breach(self, st, ts, value):
        return (value > self.threshold, self.threshold,
                f"at {value:.4g}/s (threshold {self.threshold:.4g}/s)")


class DerivativeRule(Rule):
    """Sustained positive growth: EWMA of d(value)/dt above a floor."""

    def __init__(self, name: str, series: tuple[str, ...], kind: str,
                 growth_per_s: float, alpha: float = 0.3, **kw):
        super().__init__(name, series, **kw)
        self.kind = kind
        self.growth = float(growth_per_s)
        self.alpha = float(alpha)

    def _breach(self, st, ts, value):
        prev, st.prev = st.prev, (ts, value)
        if prev is None or ts <= prev[0]:
            return False, 0.0, ""
        d = (value - prev[1]) / (ts - prev[0])
        st.mean = st.mean + self.alpha * (d - st.mean)  # mean reused: d/dt
        return (st.mean > self.growth, self.growth,
                f"growing {st.mean:.3g}/s (floor {self.growth:.3g}/s, "
                f"level {value:.4g})")


class SlopeRule(Rule):
    """Least-squares slope over the rolling tail — monotone-leak shape.
    ``min_span_s`` of history required before a verdict (a slope fit over
    half a second of samples is noise)."""

    def __init__(self, name: str, series: tuple[str, ...], kind: str,
                 slope_per_s: float, min_span_s: float = 10.0, **kw):
        super().__init__(name, series, **kw)
        self.kind = kind
        self.slope = float(slope_per_s)
        self.min_span_s = float(min_span_s)

    def _breach(self, st, ts, value):
        st.tail.append((ts, value))
        if len(st.tail) < 4 or ts - st.tail[0][0] < self.min_span_s:
            return False, 0.0, ""
        t0 = st.tail[0][0]
        xs = [t - t0 for t, _ in st.tail]
        ys = [v for _, v in st.tail]
        n = len(xs)
        mx, my = sum(xs) / n, sum(ys) / n
        denom = sum((x - mx) ** 2 for x in xs)
        if denom <= 0:
            return False, 0.0, ""
        slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
        return (slope > self.slope, self.slope,
                f"rising {slope / 1e6:.2f} MB/s over {ts - t0:.0f}s "
                f"(floor {self.slope / 1e6:.2f} MB/s)")


def build_rules(cfg) -> list[Rule]:
    """The production rule set, thresholds from config (documented in
    utils/config.py's watchdog block)."""
    common = dict(warmup=cfg.watchdog_warmup_samples,
                  debounce=cfg.watchdog_debounce,
                  cooldown_s=cfg.watchdog_cooldown_s)
    z, ratio = cfg.watchdog_z_threshold, cfg.watchdog_spike_ratio
    return [
        SpikeRule("train_step_drift", ("train_step_time_s",), "train",
                  z=z, ratio=ratio, **common),
        SpikeRule("collective_latency", ("collective_op_latency_s:mean",
                                         "collective_op_latency_s:p99"),
                  "collective", z=z, ratio=ratio, **common),
        SpikeRule("serve_latency", ("serve_ttft_s:p99", "serve_tpot_s:p99"),
                  "serve", z=z, ratio=ratio, **common),
        ThresholdRule("shed_rate", ("serve_shed_total:rate",
                                    "serve_expired_total:rate"),
                      "serve", threshold=cfg.watchdog_shed_rate_per_s,
                      **{**common, "warmup": 0}),
        DerivativeRule("queue_growth", ("serve_router_queue_depth",),
                       "serve",
                       growth_per_s=cfg.watchdog_queue_growth_per_s,
                       **common),
        SlopeRule("memory_leak", ("proc_rss_bytes", "proc_hbm_bytes"),
                  "memory",
                  slope_per_s=cfg.watchdog_mem_slope_mb_s * 1e6, **common),
        SpikeRule("heartbeat_jitter", ("node_heartbeat_gap_s",), "node",
                  z=z, ratio=ratio, abs_floor=0.25, **common),
    ]
