"""Train controller: the off-driver control loop.

Capability parity with the reference's TrainController (reference:
python/ray/train/v2/_internal/execution/controller/controller.py:105 — async
control loop `run` :634, one iteration :612: poll worker group → scaling
decision → failure decision; FailurePolicy restart-from-latest-checkpoint;
runs as an actor so driver death doesn't kill training).

Recovery tiers (beyond the reference): on a worker/slice failure the
controller first tries a **fast restart** — rebuild the group from
pre-warmed hot spares (SparePool) and restore state from in-cluster
replica shards (train/replica.py) pushed by session.replicate() — and only
falls back to the orbax checkpoint when replicas don't cover the new world.
Every restart decision (tier, trigger, detection latency, world change) is
recorded as a flight-recorder bundle (kind ``train_restart``) and counted
in ``train_restarts_total{run,tier}`` so post-mortems read one artifact,
not log archaeology.
"""

from __future__ import annotations

import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu.train.backend import JaxBackendConfig, free_port
from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.replica import ReplicaManager
from ray_tpu.train.worker_group import SparePool, WorkerGroup


import threading as _threading

_metrics = None
_metrics_lock = _threading.Lock()


def _controller_metrics():
    """Process-wide singletons: a fresh controller must extend these
    counters, not re-register and zero them (lock-guarded so concurrent
    controller constructions can't register duplicates)."""
    global _metrics
    with _metrics_lock:
        if _metrics is not None:
            return _metrics
        from ray_tpu.util.metrics import Counter, Gauge

        _metrics = {
            "restarts": Counter(
                "train_restarts_total",
                "worker-group restarts after failures, by recovery tier "
                "(replica | checkpoint | elastic_shrink)",
                tag_keys=("run", "tier")),
            "failures": Counter(
                "train_worker_failures_total",
                "train workers that reported an error", tag_keys=("run",)),
            "world": Gauge(
                "train_world_size", "current worker-group world size",
                tag_keys=("run",)),
        }
    return _metrics


@dataclass
class Result:
    metrics: dict[str, Any] = field(default_factory=dict)
    checkpoint: Any = None
    error: str | None = None
    metrics_history: list[dict] = field(default_factory=list)
    # One entry per worker-group restart: the recorded restart decision
    # (tier, trigger, detection latency, world change — same dict as the
    # train_restart flight-recorder bundle).
    restarts: list[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.error is None


class _GroupFailure(RuntimeError):
    """A poll observed the group failing; carries attribution for the
    restart decision record."""

    def __init__(self, trigger: str, message: str,
                 dead: dict[int, str] | None = None,
                 errors: dict[int, str] | None = None,
                 since_last_ok_s: float | None = None):
        super().__init__(message)
        self.trigger = trigger
        self.dead = dict(dead or {})
        self.errors = dict(errors or {})
        self.since_last_ok_s = since_last_ok_s
        # Stamped at OBSERVATION: the tier decision (replica settle window,
        # manifest RPCs) happens after this, and detection latency must not
        # include it.
        self.detected_ts = time.time()


class TrainController:
    """Runs as an actor (created by the Trainer); drives the worker group."""

    def __init__(self, train_fn: Callable, train_loop_config: dict | None,
                 scaling_config: ScalingConfig, run_config: RunConfig,
                 backend_config: JaxBackendConfig | None = None,
                 datasets: dict | None = None):
        self.train_fn = train_fn
        self.train_loop_config = train_loop_config
        self.datasets = datasets or {}
        self.scaling = scaling_config
        self.run_config = run_config
        self.backend_config = backend_config or JaxBackendConfig()
        storage = run_config.storage_path or "/tmp/ray_tpu/train"
        name = run_config.name or f"train-{int(time.time())}"
        self.ckpt_manager = CheckpointManager(
            f"{storage}/{name}",
            num_to_keep=run_config.checkpoint_config.num_to_keep,
        )
        self.metrics_history: list[dict] = []
        self.restart_log: list[dict] = []
        self._status = "PENDING"
        self._callbacks = list(run_config.callbacks)
        self._run_name = name
        self._rank0_reports = 0  # callback iteration counter (rank-0 only)
        # Controller-side run health (the worker-side throughput gauges live
        # in train/session.py): restarts and failures as counters, the live
        # world size as a gauge — the first things to look at when a run's
        # tokens/sec sags.
        m = _controller_metrics()
        self._m_restarts = m["restarts"]
        self._m_failures = m["failures"]
        self._m_world = m["world"]
        # Goodput: an open restart-downtime window (stamped at the
        # restart decision, closed by the first post-restart report) —
        # the detection + tier + time-to-first-step seconds the ledger
        # attributes to `restart_downtime`.
        self._goodput_pending: dict | None = None

    def _cb(self, hook: str, *args) -> None:
        for cb in self._callbacks:
            try:
                getattr(cb, hook)(*args)
            except Exception:  # noqa: BLE001 - a tracker must not kill a run
                traceback.print_exc()

    def status(self) -> str:
        return self._status

    def get_restart_log(self) -> list[dict]:
        return list(self.restart_log)

    # ------------------------------------------------------------- tiers
    def _choose_tier(self, world: int,
                     prev_world: int | None) -> tuple[str, int | None]:
        """Restore tier for the NEXT group after a failure:

        - ``replica``: surviving ReplicaStores cover every rank of the new
          world at a step at least as new as the latest checkpoint — restore
          in-cluster, skip storage entirely.
        - ``elastic_shrink``: capacity loss forced a smaller world; replica
          shards are world-shaped, so the resharded resume goes through the
          checkpoint (orbax reshards on load).
        - ``checkpoint``: replicas are gone (buddy slice lost too) or
          replication is off — the reference behavior.
        """
        best = None
        if self._replicas.enabled:
            # The writers push asynchronously: a failure can race the final
            # shard of an otherwise complete step set by milliseconds. Give
            # the plane a short settle window before falling back to the
            # (much slower) checkpoint tier. Load-scaled: on a contended
            # host the surviving workers' in-flight pushes take
            # proportionally longer to land (same policy as the CLI kill
            # deadlines in tests/test_start_cli.py).
            settle = 2.0
            try:
                import os as _os

                per_core = _os.getloadavg()[0] / max(_os.cpu_count() or 1, 1)
                # Capped at 4x (8 s): the window only spins while the
                # ReplicaStores are alive but coverage is incomplete, so
                # the cost of a miss is bounded checkpoint-fallback delay,
                # not correctness.
                settle *= max(1.0, min(4.0, per_core))
            except OSError:
                pass
            deadline = time.monotonic() + settle
            while True:
                try:
                    best = self._replicas.best_restore(world)
                except Exception:  # noqa: BLE001 - replica plane down
                    best = None
                    break
                if best is not None or time.monotonic() >= deadline:
                    break
                time.sleep(0.2)
        latest = self.ckpt_manager.latest()
        ck_step = None
        if latest is not None:
            ck_step = latest.metadata().get("step")
        if best is not None and (ck_step is None or best["step"] >= ck_step):
            return "replica", best["step"]
        if prev_world is not None and world < prev_world:
            return "elastic_shrink", None
        return "checkpoint", None

    def _record_restart(self, failure: _GroupFailure | None, tier: str,
                        restart_index: int, world_before: int | None,
                        world_after: int, restore_step: int | None,
                        spares_taken: int) -> None:
        from ray_tpu.core import flight_recorder

        latest = self.ckpt_manager.latest()
        decision = {
            "run": self._run_name,
            "restart_index": restart_index,
            "tier": tier,
            "trigger": getattr(failure, "trigger", "controller_error"),
            "detected_ts": getattr(failure, "detected_ts", time.time()),
            "detection_latency_s": getattr(failure, "since_last_ok_s", None),
            "dead_ranks": sorted(getattr(failure, "dead", {})),
            "error_ranks": sorted(getattr(failure, "errors", {})),
            "world_before": world_before,
            "world_after": world_after,
            "restore_step": restore_step,
            "checkpoint": latest.path if latest else None,
            "spares_promoted": spares_taken,
        }
        if self._replicas.enabled:
            try:
                decision["replica_coverage"] = self._replicas.manifests()
            except Exception:  # noqa: BLE001
                pass
        # Straggler breadcrumb (PR-5 signals): the fleet's per-rank step
        # stats at decision time — a slice that was flagged lagging before
        # it died turns a mystery restart into a diagnosis.
        try:
            from ray_tpu.core.worker import global_worker

            rt = global_worker.runtime
            if rt is not None and hasattr(rt, "train_stats"):
                decision["straggler_stats"] = rt.train_stats()
        except Exception:  # noqa: BLE001
            pass
        self.restart_log.append(decision)
        self._m_restarts.inc(tags={"run": self._run_name, "tier": tier})
        flight_recorder.record(
            "train_restart", reason=decision["trigger"],
            extra=decision)
        if tier != "abort":
            # Chips proxy: one chip per rank of the NEW world (exact on
            # single-device-per-rank rigs; the rank ledgers carry real
            # local device counts for their own phases).
            self._goodput_pending = {
                "start_ts": decision["detected_ts"],
                "tier": tier,
                "restart_index": restart_index,
                "chips": float(world_after or 0),
                "trigger": decision["trigger"],
                "detection_latency_s": decision["detection_latency_s"],
            }

    # --------------------------------------------------------------- run
    def run(self) -> Result:
        """The control loop (reference: controller.py:634). Each (re)start
        consults the scaling policy — elastic configs resume at a smaller
        world size after capacity loss (reference: elastic.py:29) — then
        picks a restore tier (_choose_tier) and builds the group from hot
        spares where available."""
        from ray_tpu.train.scaling_policy import make_scaling_policy

        self._status = "RUNNING"
        self._cb("on_run_start", self._run_name, self.train_loop_config)
        max_failures = self.run_config.failure_config.max_failures
        policy = make_scaling_policy(self.scaling,
                                     getattr(self, "_resources_fn", None))
        num_slices = max(1, getattr(self.backend_config, "num_slices", 1))
        rep_every = int(getattr(self.run_config.checkpoint_config,
                                "replicate_every", 0) or 0)
        self._replicas = ReplicaManager(self._run_name, num_slices,
                                        enabled=rep_every > 0)
        try:
            self._replicas.create()
        except Exception:  # noqa: BLE001 - no replica plane: checkpoint tier
            self._replicas.enabled = False
            rep_every = 0
        self._spares = SparePool(self.scaling, self._run_name,
                                 self.ckpt_manager.storage_path,
                                 getattr(self.scaling, "hot_spares", 0),
                                 warmup=getattr(self.scaling,
                                                "hot_spare_warmup", None))
        restart_count = 0
        prev_world: int | None = None
        pending_failure: _GroupFailure | None = None
        try:
            while True:
                group = None
                try:
                    world = policy.decide_world_size(restart_count)
                    tier, restore_step = (None, None)
                    recycled: list = []
                    if restart_count > 0:
                        tier, restore_step = self._choose_tier(world,
                                                               prev_world)
                        recycled = self._spares.take(world)
                        self._record_restart(
                            pending_failure, tier, restart_count,
                            prev_world, world, restore_step, len(recycled))
                        pending_failure = None
                    self._m_world.set(world, tags={"run": self._run_name})
                    group = WorkerGroup(
                        self.scaling, self.run_config.name or "train",
                        self.ckpt_manager.storage_path, num_workers=world,
                        recycled=recycled,
                    )
                    prev_world = world
                    coordinator = f"127.0.0.1:{free_port()}" \
                        if self.backend_config.distributed else None
                    latest = self.ckpt_manager.latest()
                    replica_info = None
                    if rep_every > 0 or restore_step is not None:
                        replica_info = {
                            "run": self._run_name, "every": rep_every,
                            "num_slices": num_slices,
                            "restore_step": restore_step,
                        }
                    group.setup(coordinator, restart_count,
                                latest.path if latest else None,
                                num_slices=getattr(self.backend_config,
                                                   "num_slices", 1),
                                replica=replica_info)
                    self.backend_config.make_backend().on_start(group,
                                                                coordinator)
                    if self.datasets:
                        # Split per (re)start so elastic world-size changes
                        # get fresh equal splits (reference: datasets= are
                        # streaming_split across the current worker group).
                        splits = {name: ds.streaming_split(world, equal=True)
                                  for name, ds in self.datasets.items()}
                        group.assign_dataset_shards([
                            {name: its[rank] for name, its in splits.items()}
                            for rank in range(world)])
                    group.run(self.train_fn, self.train_loop_config)
                    # Replenish the spare pool only once the group is up:
                    # the run's own workers always get capacity first.
                    self._spares.fill()
                    failures_left = (float("inf") if max_failures < 0
                                     else max_failures - restart_count)
                    result = self._poll_until_done(group, failures_left)
                    self._status = "FINISHED" if result.ok else "ERRORED"
                    result.restarts = list(self.restart_log)
                    self._cb("on_run_end", result)
                    return result
                except Exception as e:  # noqa: BLE001 - worker/actor failures
                    restart_count += 1
                    if isinstance(e, _GroupFailure):
                        pending_failure = e
                    else:
                        # A pure head-connectivity failure that outlived
                        # the retry wrapper's budget is an INFRASTRUCTURE
                        # trigger, not a training failure — name it so the
                        # restart record reads as "head outage", and the
                        # headft bench can assert zero of these on a
                        # bounded outage.
                        from ray_tpu.core.cluster.protocol import (
                            RpcConnectionLost)

                        trigger = ("head_unreachable"
                                   if isinstance(e, RpcConnectionLost)
                                   else "controller_error")
                        pending_failure = _GroupFailure(trigger, str(e))
                    # The single failure budget: restart_count consumes it on
                    # EVERY path (poll-observed failures raise _GroupFailure
                    # with budget > 0 left; setup/backend errors land here
                    # directly) — max_failures means the same thing
                    # everywhere.
                    if max_failures >= 0 and restart_count > max_failures:
                        self._record_restart(
                            pending_failure, "abort", restart_count,
                            prev_world, 0, None, 0)
                        self._status = "ERRORED"
                        result = Result(
                            error=traceback.format_exc(),
                            checkpoint=self.ckpt_manager.latest(),
                            metrics_history=self.metrics_history,
                            restarts=list(self.restart_log))
                        self._cb("on_run_end", result)
                        return result
                    # else: loop → new worker group, tier chosen at the top
                finally:
                    if group is not None:
                        group.shutdown()
        finally:
            self._spares.shutdown()
            try:
                self._replicas.drop()
            except Exception:  # noqa: BLE001
                pass
            self._replicas.shutdown()

    def _poll_until_done(self, group: WorkerGroup,
                         failures_left: float) -> Result:
        """Poll loop; ``failures_left`` is the REMAINING restart budget
        (max_failures minus restarts already consumed), so whether a
        failure triggers a restart or ends the run is decided by the same
        counter run() enforces."""
        last_ok = time.monotonic()
        while True:
            status = group.poll_status(timeout=60)
            if status.reports and self._goodput_pending is not None:
                # First post-restart report: the run is stepping again —
                # close the downtime window [failure detected → now] and
                # queue the event for this process's telemetry flush.
                pg, self._goodput_pending = self._goodput_pending, None
                try:
                    from ray_tpu.observability import goodput as _goodput

                    # Close at the earliest worker-stamped report instant
                    # (session.report "ts"): downtime ends when a worker
                    # stepped, not when this poll happened to observe it.
                    end_ts = min((r.get("ts") for r in status.reports
                                  if r.get("ts")), default=None) or time.time()
                    _goodput.record_event(
                        "restart_downtime", run=self._run_name,
                        seconds=max(0.0, end_ts - pg["start_ts"]),
                        chips=pg["chips"], start_ts=pg["start_ts"],
                        detail={k: pg[k] for k in
                                ("tier", "restart_index", "trigger",
                                 "detection_latency_s")})
                except Exception:  # noqa: BLE001 - never break the poll
                    pass
            for rep in status.reports:
                self.metrics_history.append(rep["metrics"])
                if rep.get("rank", 0) == 0:
                    self._rank0_reports += 1
                    self._cb("on_result", rep["metrics"], self._rank0_reports)
                if rep.get("checkpoint") and rep.get("rank", 0) == 0:
                    self.ckpt_manager.register(rep["checkpoint"], rep["metrics"])
                    self._cb("on_checkpoint", rep["checkpoint"], rep["metrics"])
            if status.errors or status.dead:
                n = len(status.errors) + len(status.dead)
                self._m_failures.inc(n, tags={"run": self._run_name})
                parts = [f"rank {r}: {e}"
                         for r, e in sorted(status.errors.items())]
                parts += [f"rank {r} died: {e}"
                          for r, e in sorted(status.dead.items())]
                err = "\n".join(parts)
                trigger = "worker_dead" if status.dead else "worker_error"
                if failures_left > 0:
                    raise _GroupFailure(
                        trigger, f"worker failure (will restart): {err}",
                        dead=status.dead, errors=status.errors,
                        since_last_ok_s=time.monotonic() - last_ok)
                return Result(error=err, checkpoint=self.ckpt_manager.latest(),
                              metrics_history=self.metrics_history,
                              restarts=list(self.restart_log))
            last_ok = time.monotonic()
            if status.finished:
                last = self.metrics_history[-1] if self.metrics_history else {}
                return Result(metrics=last,
                              checkpoint=self.ckpt_manager.latest(),
                              metrics_history=self.metrics_history,
                              restarts=list(self.restart_log))
            time.sleep(0.05)
