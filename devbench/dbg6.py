import jax, jax.numpy as jnp, numpy as np
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.ops.attention import blockwise_attention

rng = np.random.default_rng(0)
def chk(name, f, *args):
    val, grads = jax.jit(jax.value_and_grad(f, argnums=tuple(range(len(args)))))(*args)
    nan = [bool(jnp.isnan(g.astype(jnp.float32)).any()) for g in grads]
    print(name, float(val), "nan:", nan, flush=True)

x = jnp.asarray(rng.standard_normal((2,2048,2048)), jnp.bfloat16)
w = jnp.ones((2048,), jnp.bfloat16)
chk("rms_norm", lambda x,w: rms_norm(x,w,1e-5).astype(jnp.float32).sum(), x, w)

B,H,HK,S,D = 2,32,8,2048,64
q = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((B,HK,S,D)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((B,HK,S,D)), jnp.bfloat16)
inv_freq = rope_frequencies(D, 500000.0, None)
pos = jnp.arange(S)
chk("rope", lambda q: apply_rope(q, pos, inv_freq).astype(jnp.float32).sum(), q)
chk("blockwise-gqa", lambda q,k,v: blockwise_attention(q,k,v,causal=True).astype(jnp.float32).sum(), q,k,v)
def rope_attn(q,k,v):
    qr = apply_rope(q, pos, inv_freq); kr = apply_rope(k, pos, inv_freq)
    return blockwise_attention(qr,kr,v,causal=True).astype(jnp.float32).sum()
chk("rope+attn", rope_attn, q,k,v)

h = jnp.asarray(rng.standard_normal((2,2048,2048)), jnp.bfloat16)
wg = jnp.asarray(rng.standard_normal((2048,8192))/45, jnp.bfloat16)
wu = jnp.asarray(rng.standard_normal((2048,8192))/45, jnp.bfloat16)
wd = jnp.asarray(rng.standard_normal((8192,2048))/90, jnp.bfloat16)
def mlp(h,wg,wu,wd):
    gate = jax.nn.silu((h @ wg).astype(jnp.float32)).astype(h.dtype)
    up = h @ wu
    return ((gate*up) @ wd).astype(jnp.float32).sum()
chk("mlp", mlp, h,wg,wu,wd)
