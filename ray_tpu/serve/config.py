"""Serve configuration types.

Capability parity with the reference's serve config surface (reference:
python/ray/serve/config.py — AutoscalingConfig, DeploymentConfig shapes in
serve/schema.py / _private/config.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ray_tpu.serve.resilience import (
    CircuitBreakerConfig,
    ResilienceSettings,
    RetryPolicy,
)


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.2


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    autoscaling_config: AutoscalingConfig | None = None
    user_config: Any = None
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 5.0
    max_consecutive_health_failures: int = 3
    graceful_shutdown_timeout_s: float = 5.0
    version: str | None = None

    # --- request resilience (see ray_tpu/serve/resilience.py) ---
    # Default per-request budget: requests carry an absolute deadline of
    # now + request_timeout_s from the handle (overridable per call via
    # handle.options(timeout_s=...)); the router bounds queue waits by it
    # and the replica drops requests that expire before execution starts.
    request_timeout_s: float = 30.0
    # Router-side admission control: callers parked waiting for replica
    # capacity beyond this count are shed with Overloaded (HTTP 503 /
    # gRPC RESOURCE_EXHAUSTED) instead of queuing unboundedly. -1 removes
    # the bound (pre-resilience behavior).
    max_queued_requests: int = 256
    # Replica-side admission: a replica rejects with Overloaded once its
    # in-progress requests exceed max_ongoing_requests + this slack. The
    # router already caps per-router in-flight at max_ongoing_requests;
    # the slack absorbs the overshoot of several routers (driver handles +
    # proxies) honestly filling their own caps at once.
    replica_queue_slack: int = 8
    # Assignment-level retry/hedge policy (replica deaths, replica-side
    # sheds, optional tail hedging). RetryPolicy(max_retries=0) disables
    # policy retries; never-sent failures are still retried once.
    retry_policy: RetryPolicy = field(default_factory=RetryPolicy)
    # Per-replica circuit breaker (consecutive failures / latency outlier
    # → blacklist with half-open recovery probes).
    circuit_breaker: CircuitBreakerConfig = field(
        default_factory=CircuitBreakerConfig)
    # Head-sampling rate for request tracing, per deployment: fraction of
    # requests whose trace is recorded up front (the rest ride the tail
    # ring, promotable retroactively). None inherits the cluster default
    # (Config.trace_sample_rate).
    trace_sample_rate: float | None = None

    def resilience_settings(self) -> ResilienceSettings:
        """The router-facing view of these knobs (published with every
        replica snapshot)."""
        return ResilienceSettings(
            request_timeout_s=self.request_timeout_s,
            max_queued_requests=self.max_queued_requests,
            retry=self.retry_policy,
            breaker=self.circuit_breaker,
            trace_sample_rate=self.trace_sample_rate)

    # resources per replica
    ray_actor_options: dict = field(default_factory=dict)
    # Gang resources per replica (reference: serve deployment
    # placement_group_bundles/strategy — each replica gets its own PG and
    # its actor runs in bundle 0; multi-host LLM replicas reserve one
    # bundle per TP/PP worker host via LLMConfig.placement_group_config).
    placement_group_bundles: list | None = None
    placement_group_strategy: str = "PACK"


@dataclass
class ReplicaInfo:
    """What routers need to know about one live replica (published via
    long-poll, reference: _private/common.py RunningReplicaInfo).

    ``draining`` replicas are still finishing in-flight work but must not
    receive new assignments (graceful shutdown / rolling update). The
    ``settings`` dict is the deployment's ResilienceSettings
    (deployment-level, duplicated per replica so the snapshot stays a flat
    list routers already understand).

    ``prefix_blocks`` is the replica's published prefix-cache state for
    KV-block-aware routing (serve/prefix.py chain hashes, collected by the
    controller through ServeReplica.router_meta on a cadence and
    piggybacked here): None = the replica doesn't publish (non-LLM
    deployments); a tuple = the chain hashes of every cached prompt prefix
    it holds, with ``prefix_block`` the block size they were computed
    with."""

    replica_id: str
    deployment_name: str
    actor_name: str
    max_ongoing_requests: int
    draining: bool = False
    settings: dict | None = None
    prefix_blocks: tuple | None = None
    prefix_block: int = 0


@dataclass
class DeploymentStatus:
    name: str
    status: str  # UPDATING | HEALTHY | UNHEALTHY
    replica_states: dict[str, int] = field(default_factory=dict)
    message: str = ""
