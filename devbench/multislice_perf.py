"""Multi-slice training fast-path scale proof (PERF_MULTISLICE.json).

Measures, on the 2-simulated-slice 8-device dryrun topology (dp=2 crossing
slices over DCN, fsdp=4 inside each slice over ICI, pure-DDP rules so params
replicate), the four gradient-sync modes of train/spmd.make_train_step:

- flat      — stock step: XLA all-reduces the full gradient over all 8
              devices; the DCN hop carries full-size payloads.
- hier      — hierarchical (arxiv 2004.13336): weight update sharded within
              the slice; reduce-scatter(ICI) → shard-sized cross-slice
              reduce(DCN) → all-gather(ICI).
- zero1     — update + optimizer moments sharded over the WHOLE dp world
              (1/8 optimizer HBM per device), shard-sized DCN RS/AG.
- zero1_q8  — zero1 + EQuARX-style int8 cross-slice stage (arxiv
              2506.17615): only int8 values + per-bucket f32 scales cross
              the slice boundary.

Cross-slice bytes per step are measured from the compiled partitioned HLO
(ray_tpu/parallel/hlo_stats.py — ring cost model, stated in the output), so
the number is real even on CPU hosts where no DCN exists. tokens/sec/chip on
a CPU host compares modes against each other, not against TPU numbers.

Run: JAX_PLATFORMS=cpu python devbench/multislice_perf.py [--quick]
(also wired into the dryrun entrypoint, __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _force_cpu_devices(n: int = 8) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}")
    os.environ["JAX_PLATFORMS"] = "cpu"


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    import jax
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.parallel.hlo_stats import (
        COST_MODEL,
        collective_stats,
        mesh_slice_map,
    )
    from ray_tpu.parallel.mesh import MeshSpec, hybrid_mesh
    from ray_tpu.parallel.sharding import ShardingRules
    from ray_tpu.train.optim import optimizer_state_bytes
    from ray_tpu.train.spmd import make_llama_train_step

    num_slices, per_slice = 2, 4
    devices = jax.devices()[: num_slices * per_slice]
    assert len(devices) == num_slices * per_slice, (
        f"need {num_slices * per_slice} devices, have {len(devices)}")
    spec = MeshSpec(dp=num_slices, fsdp=per_slice, dcn_axes=("dp",))
    mesh = hybrid_mesh(spec, num_slices=num_slices,
                       devices_per_slice=per_slice, devices=devices)
    # Pure data-parallel: params replicated everywhere, batch over (dp,fsdp)
    # — the Llama-DDP-fine-tune geometry the north star names.
    ddp_rules = ShardingRules().override(
        vocab=None, embed=None, mlp=None, heads=None, kv_heads=None)

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=8, num_kv_heads=4, head_dim=16,
        max_seq_len=128, dtype="float32",
    )
    batch, seq = 16, 64
    steps = 4 if quick else 12
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)
    slice_of = mesh_slice_map(len(devices), num_slices)

    modes = {
        "flat": {},
        "hier": dict(dcn_axes=("dp",)),
        "zero1": dict(zero1=True, dcn_axes=("dp",)),
        "zero1_q8": dict(zero1=True, dcn_axes=("dp",), dcn_quant="int8"),
    }
    if not quick:
        modes["zero1_accum4"] = dict(zero1=True, dcn_axes=("dp",),
                                     grad_accum=4)

    opt = optax.adamw(1e-2)
    report: dict = {
        "what": ("Multi-slice fast path: flat vs hierarchical vs zero1 vs "
                 "int8-quantized-DCN gradient sync on a 2-simulated-slice "
                 "8-device CPU mesh (dp=2 over DCN x fsdp=4 over ICI, "
                 "pure-DDP Llama)."),
        "geometry": {
            "num_slices": num_slices, "devices_per_slice": per_slice,
            "batch": batch, "seq": seq, "steps_timed": steps,
            "params": int(sum(np.prod(l.shape) for l in jax.tree.leaves(
                jax.eval_shape(lambda k: init_params(cfg, k),
                               jax.random.PRNGKey(0))))),
        },
        "modes": {},
    }

    flat_losses = None
    flat_dcn = None
    for name, kw in modes.items():
        step, init, shard = make_llama_train_step(
            cfg, mesh, rules=ddp_rules, optimizer=opt,
            attn_impl="blockwise", remat=False, **kw)
        state = init()
        ts, tg = shard(tokens), shard(targets)
        stats = collective_stats(
            step.lower(state, ts, tg).compile().as_text(), slice_of,
            n_partitions=len(devices))
        opt_bytes = optimizer_state_bytes(
            opt, state.params,
            shardings=jax.tree.map(lambda l: l.sharding, state.opt_state))
        state, m = step(state, ts, tg)  # warmup (donates + re-inits below)
        jax.block_until_ready(m["loss"])
        state = init()
        losses = []
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, ts, tg)
            losses.append(float(m["loss"]))  # also syncs
        dt = time.perf_counter() - t0
        row = {
            "dcn_bytes_per_step": stats.dcn_bytes,
            "dcn_collective_ops": stats.dcn_ops,
            # non-zero = the HLO had collectives the parser could not price,
            # so dcn_bytes_per_step UNDERCOUNTS for this row
            **({"dcn_unpriced_ops": stats.skipped_ops}
               if stats.skipped_ops else {}),
            "tokens_per_sec_per_chip": round(
                batch * seq * steps / dt / len(devices), 1),
            "step_ms": round(dt / steps * 1e3, 2),
            "opt_state_bytes_per_device": opt_bytes,
            "losses": [round(l, 6) for l in losses],
        }
        if name == "flat":
            flat_losses, flat_dcn = losses, stats.dcn_bytes
        else:
            row["dcn_reduction_vs_flat"] = round(
                flat_dcn / max(stats.dcn_bytes, 1), 2)
            n = min(len(losses), len(flat_losses))
            row["max_loss_delta_vs_flat"] = round(float(np.max(np.abs(
                np.asarray(losses[:n]) - np.asarray(flat_losses[:n])))), 6)
        report["modes"][name] = row

    report["dcn_cost_model"] = (
        "bytes from the compiled partitioned HLO; " + COST_MODEL)
    report["parity"] = {
        # fp32 hierarchy is a pure reorder of the same sums; allow float
        # reassociation noise across XLA versions/backends (the step-level
        # test asserts the same claim at rtol 1e-6)
        "hier_fp32_delta_lt_1e-6": report["modes"]["hier"][
            "max_loss_delta_vs_flat"] < 1e-6,
        "zero1_tolerance_1e-4": report["modes"]["zero1"][
            "max_loss_delta_vs_flat"] < 1e-4,
        "zero1_q8_tolerance_2e-2": report["modes"]["zero1_q8"][
            "max_loss_delta_vs_flat"] < 2e-2,
        "zero1_q8_dcn_reduction_ge_2x": report["modes"]["zero1_q8"][
            "dcn_reduction_vs_flat"] >= 2.0,
        "zero1_dcn_reduction_ge_2x": report["modes"]["zero1"][
            "dcn_reduction_vs_flat"] >= 2.0,
    }

    # Satellite: grad-norm amortization — the same flat step with the norm
    # computed every 8 steps instead of every step, timed back-to-back
    # (best-of-2 interleaved rounds so box-load drift can't flip the sign).
    # Skipped in quick (dryrun-embedded) runs: two extra compiles for a
    # number the committed full-run PERF_MULTISLICE.json already carries.
    if quick:
        out_path = out_path or os.path.join(REPO_ROOT,
                                            "PERF_MULTISLICE.json")
        # A committed full-run file keeps ALL its sections (geometry,
        # parity, rows) untouched — a quick (dryrun-embedded, fewer-steps)
        # refresh lands under its own key with its own geometry so rows are
        # never attributed to a configuration they weren't measured with.
        try:
            with open(out_path) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            merged = {}
        if merged.get("modes"):
            merged["quick_dryrun_refresh"] = {
                "geometry": report["geometry"],
                "modes": report["modes"],
                "parity": report["parity"],
            }
        else:
            merged = report
        with open(out_path, "w") as f:
            json.dump(merged, f, indent=1)
        return report
    steps_fns = {}
    for every in (1, 8):
        step, init, shard = make_llama_train_step(
            cfg, mesh, rules=ddp_rules, optimizer=opt, attn_impl="blockwise",
            remat=False, grad_norm_every=every)
        state = init()
        ts, tg = shard(tokens), shard(targets)
        state, m = step(state, ts, tg)
        jax.block_until_ready(m["loss"])
        steps_fns[every] = (step, state, ts, tg)
    # The differential is a few ms/step — smaller than this box's slow
    # thermal/load drift. Pair the two variants back-to-back within each
    # round (drift cancels in the difference), sync once per window
    # (per-step float(loss) sync injects more jitter than the signal), and
    # report the median of the per-round paired differences.
    round_ms = {1: [], 8: []}
    for _round in range(5):
        for every, (step, state, ts, tg) in steps_fns.items():
            t0 = time.perf_counter()
            for _ in range(steps):
                state, m = step(state, ts, tg)
            jax.block_until_ready(m["loss"])
            round_ms[every].append((time.perf_counter() - t0) / steps * 1e3)
            steps_fns[every] = (step, state, ts, tg)
    diffs = sorted(a - b for a, b in zip(round_ms[1], round_ms[8]))
    median = diffs[len(diffs) // 2]
    report["grad_norm_amortization"] = {
        "grad_norm_every": 8,
        "step_ms_every1": round(min(round_ms[1]), 2),
        "step_ms_every8": round(min(round_ms[8]), 2),
        "reclaimed_ms_per_step": round(median, 2),
        "per_round_diffs_ms": [round(d, 2) for d in diffs],
        "note": ("CPU-host numbers: median of 5 paired (back-to-back, "
                 "end-of-window-sync) round differences; per-round spread "
                 "shows the box noise floor. On the v5e chip the norm "
                 "reduction is 7.8 ms of a 505 ms step (PERF_STEP.json "
                 "r05), so grad_norm_every=8 reclaims ~1.4% of step time."),
    }

    out_path = out_path or os.path.join(REPO_ROOT, "PERF_MULTISLICE.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    return report


def main(argv: list[str] | None = None) -> dict:
    argv = sys.argv[1:] if argv is None else argv
    _force_cpu_devices()
    report = run_bench(quick="--quick" in argv)
    summary = {name: (row["dcn_bytes_per_step"],
                      row["tokens_per_sec_per_chip"])
               for name, row in report["modes"].items()}
    print("multislice_perf:", json.dumps(summary))
    return report


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    main()
