"""rtlint engine: file discovery, the shared parse, rule dispatch, and
the allowlist filter.

Findings print as ``file:line RULE message``. True-but-accepted findings
live in an allowlist file (default ``ray_tpu/devtools/rtlint_allow.txt``)
whose every entry carries a justification string — an entry without one
is a hard error, and entries that no longer match anything are reported
as stale so the file can't rot.
"""

from __future__ import annotations

import ast
import os
import time
from dataclasses import dataclass, field

from ray_tpu.devtools.findings import Finding
from ray_tpu.devtools.model import ModuleInfo, parse_module
from ray_tpu.devtools.rules import ALL_RULES, RuleContext

DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(__file__),
                                 "rtlint_allow.txt")

_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}


class AllowlistError(ValueError):
    """Malformed allowlist entry (missing justification, bad shape)."""


class LintUsageError(ValueError):
    """Bad invocation (unknown rule id, etc.)."""


@dataclass
class AllowEntry:
    rule: str
    relpath: str
    symbol: str
    justification: str
    lineno: int

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.relpath, self.symbol)


@dataclass
class LintResult:
    findings: list[Finding]           # active (unallowlisted) findings
    allowlisted: list[Finding]        # matched an allowlist entry
    stale_entries: list[AllowEntry]   # allowlist rows that matched nothing
    files: int
    rule_seconds: dict[str, float]
    wall_seconds: float
    counts: dict[str, int] = field(default_factory=dict)  # per-rule active
    allowlist_path: str | None = None  # the file stale line numbers refer to

    @property
    def ok(self) -> bool:
        return not self.findings


def _symbol_match(pattern: str, symbol: str) -> bool:
    """Exact match, or a trailing ``*`` wildcard so one justified entry
    can baseline a class (``HeadServer.*``) instead of forty rows."""
    if pattern.endswith("*"):
        return symbol.startswith(pattern[:-1])
    return pattern == symbol


def load_allowlist(path: str) -> list[AllowEntry]:
    entries: list[AllowEntry] = []
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for lineno, raw in enumerate(f, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if " -- " not in line:
                raise AllowlistError(
                    f"{path}:{lineno}: allowlist entry has no "
                    f"' -- justification' suffix: {line!r}")
            head, justification = line.split(" -- ", 1)
            justification = justification.strip()
            if not justification:
                raise AllowlistError(
                    f"{path}:{lineno}: empty justification")
            parts = head.split()
            if len(parts) != 3:
                raise AllowlistError(
                    f"{path}:{lineno}: expected 'RULE path symbol -- "
                    f"justification', got {line!r}")
            entries.append(AllowEntry(parts[0], parts[1], parts[2],
                                      justification, lineno))
    return entries


def discover_files(paths: list[str]) -> list[str]:
    files: list[str] = []
    seen: set[str] = set()  # realpath-dedup: overlapping args (a file AND
    # its parent dir) must not parse a module twice — R4 would see every
    # metric constructor at "two" call sites

    def _add(p: str) -> None:
        real = os.path.realpath(p)
        if real not in seen:
            seen.add(real)
            files.append(p)

    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            _add(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = [d for d in sorted(dirs) if d not in _SKIP_DIRS]
            for n in sorted(names):
                if n.endswith(".py"):
                    _add(os.path.join(root, n))
    return files


def _repo_base(paths: list[str]) -> str:
    """Directory findings are reported relative to: the nearest ancestor
    of the first target holding pyproject.toml, else the target's
    parent."""
    start = os.path.abspath(paths[0])
    cur = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.dirname(start) if os.path.isdir(start) \
                else os.path.dirname(os.path.dirname(start))
        cur = nxt


def _load_config_registry(modules: list[ModuleInfo],
                          ctx: RuleContext) -> None:
    """Locate the knob registry of record among the scanned modules (R5).
    When the scan doesn't include one (fixture corpus runs), every RTPU_*
    read is undocumented by definition — which is what fixture tests
    want."""
    for mod in modules:
        if mod.relpath.replace("\\", "/").endswith("utils/config.py"):
            ctx.config_source = mod.source
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef) and node.name == "Config":
                    for item in node.body:
                        if isinstance(item, ast.AnnAssign) and isinstance(
                                item.target, ast.Name):
                            ctx.config_fields.add(item.target.id)
            return


def run_lint(paths: list[str] | None = None,
             allowlist: str | None = DEFAULT_ALLOWLIST,
             rules: list[str] | None = None,
             base_dir: str | None = None) -> LintResult:
    """Run the rule suite over ``paths`` (default: the installed ray_tpu
    package) and filter through the allowlist. ``allowlist=None``
    disables filtering (fixture tests)."""
    t0 = time.perf_counter()
    if not paths:
        import ray_tpu

        paths = [os.path.dirname(os.path.abspath(ray_tpu.__file__))]
    base = base_dir or _repo_base(paths)
    files = discover_files(paths)
    modules: list[ModuleInfo] = []
    parse_failures: list[Finding] = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(path), base).replace(
            "\\", "/")
        mod = parse_module(path, rel, source)
        if mod is not None:
            modules.append(mod)
        else:
            # A file the analyzer cannot parse must be a finding, not a
            # silent skip — otherwise a syntax error exempts a module
            # from every rule.
            parse_failures.append(Finding(
                "R0", rel, 1, "syntax-error",
                "file does not parse — no rule can check it"))

    ctx = RuleContext()
    _load_config_registry(modules, ctx)

    selected = [r.strip().upper() for r in rules if r.strip()] \
        if rules else sorted(ALL_RULES)
    unknown = [r for r in selected if r not in ALL_RULES]
    if unknown:
        raise LintUsageError(
            f"unknown rule id(s) {', '.join(unknown)}; "
            f"valid: {', '.join(sorted(ALL_RULES))}")
    all_findings: list[Finding] = list(parse_failures)
    rule_seconds: dict[str, float] = {}
    for rid in selected:
        rt0 = time.perf_counter()
        all_findings.extend(ALL_RULES[rid](modules, ctx))
        rule_seconds[rid] = round(time.perf_counter() - rt0, 4)

    # Dedup exact repeats (two opens on one line, etc.).
    seen_f: set[tuple] = set()
    deduped: list[Finding] = []
    for f in all_findings:
        k = (f.rule, f.relpath, f.line, f.symbol)
        if k not in seen_f:
            seen_f.add(k)
            deduped.append(f)
    all_findings = deduped

    entries = load_allowlist(allowlist) if allowlist else []
    matched: set[int] = set()
    active: list[Finding] = []
    allowed: list[Finding] = []
    for f in all_findings:
        hit = None
        for idx, e in enumerate(entries):
            if e.rule == f.rule and e.relpath == f.relpath and \
                    _symbol_match(e.symbol, f.symbol):
                hit = idx
                break
        if hit is not None:
            matched.add(hit)
            allowed.append(f)
        else:
            active.append(f)
    # An entry is stale only when its FILE was in scope AND its rule ran
    # and nothing matched — a partial run (`ray_tpu lint one/file.py`,
    # `--rules R1`) must not report the rest of the baseline as rot.
    scanned = {m.relpath for m in modules}
    stale = [e for i, e in enumerate(entries)
             if i not in matched and e.relpath in scanned
             and e.rule in selected]
    active.sort(key=lambda f: (f.relpath, f.line, f.rule))
    counts: dict[str, int] = {}
    for f in active:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return LintResult(
        findings=active, allowlisted=allowed, stale_entries=stale,
        files=len(modules), rule_seconds=rule_seconds,
        wall_seconds=round(time.perf_counter() - t0, 4), counts=counts,
        allowlist_path=allowlist)


def format_findings(result: LintResult, verbose: bool = False) -> str:
    lines = [f.render() for f in result.findings]
    if result.stale_entries:
        allow = result.allowlist_path or DEFAULT_ALLOWLIST
        for e in result.stale_entries:
            lines.append(
                f"{allow}:{e.lineno} STALE allowlist entry "
                f"matches nothing: {e.rule} {e.relpath} {e.symbol}")
    summary = (
        f"rtlint: {len(result.findings)} finding(s), "
        f"{len(result.allowlisted)} allowlisted, "
        f"{len(result.stale_entries)} stale allowlist entr(ies) over "
        f"{result.files} files in {result.wall_seconds}s")
    if verbose:
        per = ", ".join(f"{k}={v}s" for k, v in
                        sorted(result.rule_seconds.items()))
        summary += f" ({per})"
    lines.append(summary)
    return "\n".join(lines)
