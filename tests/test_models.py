"""Llama model: shapes, loss, determinism, sharded execution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
    loss_fn,
    param_logical_axes,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_forward_shapes(tiny):
    cfg, params = tiny
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = forward(cfg, params, tokens, attn_impl="blockwise")
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality(tiny):
    # changing a future token must not affect past logits
    cfg, params = tiny
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(5)
    l1 = forward(cfg, params, t1, attn_impl="blockwise")
    l2 = forward(cfg, params, t2, attn_impl="blockwise")
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_loss_decreases_with_sgd(tiny):
    cfg, params = tiny
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (4, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p: loss_fn(cfg, p, tokens, targets, attn_impl="blockwise")))
    loss0, g = grad_fn(params)
    p2 = jax.tree.map(lambda p, g: p - 0.5 * g, params, g)
    loss1, _ = grad_fn(p2)
    assert float(loss1) < float(loss0)


def test_remat_modes_grad_equivalence(tiny):
    # Every remat policy must produce the same gradients as saving
    # everything — 'attn' in particular recomputes the SwiGLU activations
    # from the saved attention projection in the backward pass.
    cfg, params = tiny
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (2, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    def grads(remat):
        return jax.grad(lambda p: loss_fn(
            cfg, p, tokens, targets, attn_impl="blockwise", remat=remat,
        ))(params)

    ref = grads("none")
    for mode in ("attn", "dots", "dots+", True):
        got = grads(mode)
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def test_num_params_formula(tiny):
    cfg, params = tiny
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_logical_axes_cover_params(tiny):
    cfg, params = tiny
    axes = param_logical_axes(cfg)
    flat_p = jax.tree.leaves(params)
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    # rank of each axes tuple matches the param rank
    p_struct = jax.tree.structure(params)
    a_struct = jax.tree.structure(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert p_struct == a_struct


def test_sharded_forward_matches_single(tiny, cpu_mesh_devices):
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.sharding import ShardingRules, shard_params

    cfg, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0,
                                cfg.vocab_size)
    expected = forward(cfg, params, tokens, attn_impl="blockwise")

    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2), cpu_mesh_devices)
    sharded = shard_params(params, mesh, param_logical_axes(cfg))
    out = jax.jit(lambda p, t: forward(cfg, p, t, attn_impl="blockwise"))(
        sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-4, rtol=2e-4)


def test_llama3_8b_param_count():
    cfg = LlamaConfig.llama3_8b()
    assert abs(cfg.num_params() - 8.03e9) / 8.03e9 < 0.01


class TestViT:
    def test_forward_shapes_and_cls(self):
        from ray_tpu.models.vit import ViTConfig, forward, init_params, patchify
        import jax
        import jax.numpy as jnp

        cfg = ViTConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        imgs = jax.random.uniform(jax.random.PRNGKey(1), (3, 16, 16, 3))
        patches = patchify(cfg, imgs)
        assert patches.shape == (3, 16, 4 * 4 * 3)  # (16/4)^2 patches
        logits = forward(cfg, params, imgs, attn_impl="blockwise")
        assert logits.shape == (3, 10)
        assert jnp.isfinite(logits).all()

    def test_patchify_preserves_pixels(self):
        from ray_tpu.models.vit import ViTConfig, patchify
        import numpy as np

        cfg = ViTConfig.tiny()
        imgs = np.arange(16 * 16 * 3, dtype=np.float32).reshape(1, 16, 16, 3)
        patches = np.asarray(patchify(cfg, imgs))
        # first patch row 0 == image rows 0..3, cols 0..3 flattened
        expect = imgs[0, :4, :4, :].reshape(-1)
        np.testing.assert_array_equal(patches[0, 0], expect)

    def test_spmd_train_step_learns(self, cpu_mesh_devices):
        """make_vit_train_step over a dp*tp mesh: loss decreases on a
        learnable synthetic task (brightness-quadrant classification)."""
        import numpy as np
        import optax

        from ray_tpu.models.vit import ViTConfig, make_vit_train_step
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = ViTConfig.tiny()
        mesh = build_mesh(MeshSpec(dp=2, tp=2), cpu_mesh_devices[:4])
        step, init, shard = make_vit_train_step(
            cfg, mesh, optimizer=optax.adam(1e-3), attn_impl="blockwise")
        state = init()
        rng = np.random.default_rng(0)
        # Label = which quadrant is brightest; linearly separable from
        # patch features.
        imgs = rng.uniform(0, 0.3, (16, 16, 16, 3)).astype(np.float32)
        labels = rng.integers(0, 4, 16).astype(np.int32)
        for n, lab in enumerate(labels):
            r0, c0 = (lab // 2) * 8, (lab % 2) * 8
            imgs[n, r0:r0 + 8, c0:c0 + 8] += 0.6
        losses = []
        for _ in range(20):
            state, m = step(state, shard(imgs), shard(labels))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]

    def test_vit_consumes_read_images(self, tmp_path):
        """Multimodal loop closed: data.read_images feeds the ViT train
        step directly (decoded uint8 batches -> float images -> loss)."""
        import numpy as np
        import optax
        from PIL import Image

        import jax
        import ray_tpu
        import ray_tpu.data as rdata
        from ray_tpu.models.vit import ViTConfig, make_vit_train_step
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh

        for i in range(8):
            arr = np.full((20, 20, 3), 20 * i, dtype=np.uint8)
            Image.fromarray(arr).save(tmp_path / f"c{i % 2}_{i}.png")
        ray_tpu.init()
        try:
            ds = rdata.read_images(str(tmp_path), size=(16, 16))
            batch = next(iter(ds.iter_batches(batch_size=8)))
        finally:
            ray_tpu.shutdown()
        imgs = batch["image"].astype(np.float32) / 255.0
        labels = np.asarray(
            [int(p.split("/")[-1][1]) for p in batch["path"]], np.int32)
        cfg = ViTConfig.tiny()
        mesh = build_mesh(MeshSpec(dp=1), jax.devices("cpu")[:1])
        step, init, shard = make_vit_train_step(
            cfg, mesh, optimizer=optax.adam(1e-3), attn_impl="blockwise")
        state = init()
        state, m = step(state, shard(imgs), shard(labels))
        assert np.isfinite(float(m["loss"]))
