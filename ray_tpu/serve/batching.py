"""@serve.batch: transparent request batching inside a replica.

Capability parity with the reference's batching (reference:
python/ray/serve/batching.py — concurrent calls to a decorated method are
queued and executed as one underlying call on a list, results fanned back
out). Thread-based: replicas run requests on a thread pool
(max_concurrency), so concurrent callers park on futures while one batcher
thread drains the queue.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from functools import wraps
from typing import Any, Callable

from ray_tpu.util import tracing


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def submit(self, instance: Any, item: Any) -> Future:
        # The request's deadline rides along (thread-local, stamped by the
        # replica before the user method ran): the batch loop sheds items
        # that expire while queued instead of spending a batch slot on
        # them. The trace context is captured HERE too — batching fans
        # many requests into ONE execution, so each item's batch span must
        # parent to its own request's trace, not to whichever request
        # happened to trigger the batch (captured per-item while the
        # caller's thread-local context is still live).
        from ray_tpu.serve.resilience import current_deadline, current_deployment

        fut: Future = Future()
        ctx = tracing.inject() if tracing.current_context() else None
        self.q.put((instance, item, fut, current_deadline(),
                    current_deployment(), ctx, time.time()))
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop, daemon=True)
                self._thread.start()
        return fut

    @staticmethod
    def _drop_expired(batch: list) -> list:
        """Fail expired entries (DeadlineExceeded) and return the live
        rest — run just before the batch executes, where queue wait has
        already been paid and compute is about to be."""
        from ray_tpu.serve.resilience import (
            DeadlineExceeded,
            expired,
            shed_metrics,
        )

        live = []
        for entry in batch:
            if expired(entry[3]):
                entry[2].set_exception(DeadlineExceeded(
                    "request expired while queued for a batch"))
                try:
                    shed_metrics()["expired"].inc(
                        tags={"deployment": entry[4], "where": "batcher"})
                except Exception:
                    pass
            else:
                live.append(entry)
        return live

    def _loop(self) -> None:
        while True:
            try:
                first = self.q.get(timeout=5.0)
            except queue.Empty:
                return  # idle; a new submit restarts the thread
            batch = [first]
            deadline = self.timeout
            while len(batch) < self.max_batch_size:
                try:
                    batch.append(self.q.get(timeout=deadline))
                except queue.Empty:
                    break
            batch = self._drop_expired(batch)
            if not batch:
                continue
            instance = batch[0][0]
            items = [b[1] for b in batch]
            futs = [b[2] for b in batch]
            status = "OK"
            try:
                results = (self.fn(instance, items) if instance is not None
                           else self.fn(items))
                if len(results) != len(items):
                    raise RuntimeError(
                        f"@serve.batch function returned {len(results)} results "
                        f"for a batch of {len(items)}")
                for f, r in zip(futs, results):
                    f.set_result(r)
            except BaseException as e:  # noqa: BLE001
                status = f"ERROR: {type(e).__name__}"
                for f in futs:
                    if not f.done():
                        f.set_exception(e)
            # One batch execution, many requests: each item with a
            # propagated context gets its own span (queue wait + execute)
            # parented under ITS request's trace — the batch loop thread
            # never entered any of them, so the context rides explicitly.
            end = time.time()
            for entry in batch:
                if entry[5] is not None:
                    tracing.record_span(
                        "serve.batch_item", entry[6], end,
                        attributes={"batch_size": len(items),
                                    "status": status},
                        ctx=entry[5])


def batch(_fn: Callable | None = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: ``@serve.batch`` on a method taking a list of inputs."""

    def deco(fn: Callable):
        # Queues hold locks/threads, so they are created lazily per replica
        # instance (keeps the decorated class picklable for shipping to the
        # replica actor) and batching state is per-replica, as in the
        # reference.
        # Lazy queue creation keeps the decorated class picklable (queues
        # hold locks/threads) and makes batching state per-replica. No lock:
        # dict.setdefault is atomic under the GIL, so a racing duplicate
        # queue is simply discarded in favor of the winner.
        attr = f"_serve_batch_queue_{fn.__name__}"
        unbound_holder: dict = {}

        @wraps(fn)
        def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                instance, item = args
                holder = instance.__dict__
            else:
                instance, item = None, args[0]
                holder = unbound_holder
            bq = holder.get(attr)
            if bq is None:
                bq = holder.setdefault(
                    attr, _BatchQueue(fn, max_batch_size,
                                      batch_wait_timeout_s))
            return bq.submit(instance, item).result()

        wrapper._is_serve_batch = True
        return wrapper

    return deco(_fn) if _fn is not None else deco
