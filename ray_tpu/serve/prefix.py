"""Chained block hashes for KV-block-aware prefix routing.

Capability parity with the reference's prefix-aware request router
(reference: serve request_router routing_policies/prefix_aware + vLLM's
block-hash prefix caching): a prompt is hashed in fixed-size blocks where
block ``i``'s hash chains over block ``i-1``'s — so hash ``h_i`` identifies
the ENTIRE prefix through block ``i``, not just its own tokens. A replica
publishes the chain hashes of every prefix its engine holds; a router
scores candidates by how many leading request hashes the replica's set
contains. Membership of ``h_i`` implies the whole prefix is cached, so the
match length is exactly the reusable KV span in blocks.

Two domains share one implementation:

- token domain (``block_hashes``): sequences of token ids — what the
  engine's KV cache is actually keyed by. Callers that tokenize
  (the P/D orchestrator, engine-direct handle users, benches) compute
  request hashes here and MUST use the replica's published block size.
- text domain (``text_block_hashes``): UTF-8 bytes in fixed char blocks —
  for deployments that key their cache on raw text (the serve HTTP proxy
  cannot tokenize, so text-keyed deployments let proxy-side hints stay
  precise). The two domains never mix: a deployment publishes in one
  domain and its clients hash in the same one.

Hashes are crc32-chained over the little-endian uint32 encoding of the
ids: stable across processes and Python versions (no PYTHONHASHSEED), and
cheap enough to run per request on the router hot path. 32-bit collisions
only cost a misrouted request (the engine re-checks real token LCP before
reusing KV), never correctness.
"""

from __future__ import annotations

import zlib
from typing import Iterable, Sequence

import numpy as np

# Hashing more than this many blocks per prefix buys nothing: routing only
# needs enough resolution to separate hot system prompts, and the publish
# payload must stay small enough to piggyback on every snapshot.
MAX_BLOCKS = 64


def block_hashes(ids: Sequence[int], block: int,
                 max_blocks: int = MAX_BLOCKS) -> tuple[int, ...]:
    """Chain hashes of ``ids`` in blocks of ``block`` tokens.

    Only FULL blocks are hashed — a partial tail block can't be reused as
    cached KV by a different prompt anyway (the engine always recomputes
    at least the final prompt token). Returns () for prompts shorter than
    one block."""
    if block <= 0:
        return ()
    n = (min(len(ids), block * max_blocks) // block) * block
    if n <= 0:
        return ()
    buf = np.asarray(list(ids[:n]), dtype=np.int64).astype(
        np.uint32).tobytes()
    out = []
    h = 0
    step = block * 4
    for i in range(0, n * 4, step):
        h = zlib.crc32(buf[i:i + step], h)
        out.append(h)
    return tuple(out)


def text_block_hashes(text: str, block_chars: int = 128,
                      max_blocks: int = MAX_BLOCKS) -> tuple[int, ...]:
    """Text-domain chain hashes: UTF-8 bytes in ``block_chars``-byte
    blocks (for deployments whose cache is keyed on raw text)."""
    return block_hashes(text.encode("utf-8", "ignore"), block_chars,
                        max_blocks)


def match_len(hashes: Sequence[int], held: "set[int] | frozenset[int]"
              ) -> int:
    """Leading blocks of ``hashes`` present in ``held``. Chaining makes a
    gap impossible in an honest publication, so stop at the first miss."""
    n = 0
    for h in hashes:
        if h not in held:
            break
        n += 1
    return n


def union_hashes(prefixes: Iterable[Sequence[int]], block: int,
                 max_blocks: int = MAX_BLOCKS) -> tuple[int, ...]:
    """Union of chain hashes over several cached prefixes (what a replica
    publishes), sorted for a deterministic snapshot."""
    out: set[int] = set()
    for p in prefixes:
        out.update(block_hashes(p, block, max_blocks))
    return tuple(sorted(out))
