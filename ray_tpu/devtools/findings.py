"""Finding record shared by the rtlint rules, engine, and allowlist."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    rule: str      # R0..R5
    relpath: str   # repo-relative posix path
    line: int
    symbol: str    # stable key: Class.attr / metric name / env var / ...
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        """Allowlist match key — line numbers drift, symbols don't."""
        return (self.rule, self.relpath, self.symbol)

    def render(self) -> str:
        return f"{self.relpath}:{self.line} {self.rule} {self.message}"
