"""Batch LLM inference over Datasets.

Capability parity with the reference's ray.data.llm (reference:
python/ray/data/llm.py:28 ProcessorConfig → ray.llm._internal.batch
processor.base:293 Processor — a map_batches pipeline of chat-template →
tokenize → engine → detokenize stages over an actor pool): here one stage
holds the JAX continuous-batching engine; tokenize/detokenize ride inside
it (the engine's tokenizer), and the actor pool gives each worker a
long-lived compiled engine.

Usage:
    processor = build_llm_processor(LLMConfig(model=...), concurrency=1)
    ds = ray_tpu.data.from_items([{"prompt": "..."}, ...])
    out = processor(ds)            # adds "generated_text" (+ token counts)
    out.take_all()
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np


@dataclass
class ProcessorConfig:
    """Batch-inference knobs (reference: ProcessorConfig, data/llm.py:28)."""

    batch_size: int = 16
    concurrency: int = 1
    prompt_column: str = "prompt"
    output_column: str = "generated_text"
    sampling: dict = field(default_factory=dict)  # max_tokens/temperature/…
    apply_chat_template: bool = False


class _EngineStage:
    """map_batches callable class: one LLMEngine per actor, reused across
    batches (reference: vllm_engine_stage.py — the engine outlives blocks)."""

    def __init__(self, llm_config, proc: ProcessorConfig):
        from ray_tpu.llm import LLMEngine, SamplingParams

        self.engine = LLMEngine(llm_config)
        self.proc = proc
        self.sampling = SamplingParams(**proc.sampling)

    def __call__(self, batch: dict) -> dict:
        prompts = [str(p) for p in batch[self.proc.prompt_column]]
        if self.proc.apply_chat_template:
            prompts = [self.engine.tokenizer.apply_chat_template(
                [{"role": "user", "content": p}]) for p in prompts]
        # Submit the whole batch; the engine's continuous batching fills its
        # slots and interleaves decodes.
        reqs = [self.engine.submit(p, self.sampling) for p in prompts]
        texts, ntok = [], []
        for req in reqs:
            if not req.done.wait(timeout=600):
                raise TimeoutError(
                    f"generation {req.request_id} did not finish in 600s")
            if req.error:
                raise RuntimeError(req.error)
            res = self.engine._result(req)
            texts.append(res.text)
            ntok.append(len(res.token_ids))
        out = dict(batch)
        out[self.proc.output_column] = np.asarray(texts, dtype=object)
        out["num_generated_tokens"] = np.asarray(ntok)
        return out


def build_llm_processor(llm_config, *, config: ProcessorConfig | None = None,
                        **overrides) -> Any:
    """Returns processor(dataset) -> dataset with generations appended."""
    from ray_tpu.data.executor import ActorPoolStrategy

    proc = config or ProcessorConfig(**overrides)

    def processor(ds):
        return ds.map_batches(
            _EngineStage,
            fn_constructor_args=(llm_config, proc),
            batch_size=proc.batch_size,
            compute=ActorPoolStrategy(size=proc.concurrency),
        )

    return processor
