"""Test configuration.

Mirrors the reference's test strategy (reference: python/ray/tests/conftest.py
ray_start_regular :602 / ray_start_cluster :647): fixtures that start/stop the
runtime around each test, plus a virtual 8-device CPU mesh so every sharding/
collective test exercises real multi-device SPMD without TPU hardware.
"""

import os

# Force an 8-virtual-device CPU mesh. The environment pre-imports jax with the
# remote-TPU tunnel platform enabled (slow/flaky to init, single chip), so the
# env var alone is ignored — jax.config.update must be used before any backend
# initialization.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """``multidevice`` tests need the 8-virtual-device mesh this conftest
    forces; when the env overrides XLA_FLAGS (or jax was initialized before
    us) skip them instead of failing on mesh construction. Registered in
    pyproject so `-m multidevice` can select them in isolation too."""
    try:
        n = len(jax.devices("cpu"))
    except Exception:
        n = 0
    if n >= 8:
        return
    skip = pytest.mark.skip(reason=f"needs 8 virtual cpu devices, have {n}")
    for item in items:
        if "multidevice" in item.keywords:
            item.add_marker(skip)


def poll_until(predicate, timeout: float = 15.0, interval: float = 0.05,
               desc: str = "condition"):
    """Event-polling helper: spin on ``predicate`` with short sleeps until
    it returns something truthy (returned) or the deadline passes
    (AssertionError). Keeps observability tests deterministic without
    sleep(>0.1) calls — poll fast, bound long."""
    import time as _time

    deadline = _time.monotonic() + timeout
    while True:
        value = predicate()
        if value:
            return value
        if _time.monotonic() >= deadline:
            raise AssertionError(f"timed out after {timeout}s waiting for {desc}")
        _time.sleep(interval)


@pytest.fixture
def wait_for():
    """Fixture handle for poll_until (conftest isn't importable as a module
    from test files under rootdir-relative invocation)."""
    return poll_until


@pytest.fixture
def rt_start():
    """In-process runtime with 8 fake CPUs and a fake 4-chip TPU host."""
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8, resources={"TPU": 4.0})
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {len(devs)}"
    return devs
