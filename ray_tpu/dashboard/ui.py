"""Dashboard web UI: a single-file HTML client over the JSON API.

Capability parity with the reference's React dashboard client (reference:
python/ray/dashboard/client/ — overview, nodes, actors, tasks, jobs views
over the same JSON API). Here the client is one dependency-free page that
polls /api/* and renders tables; it is served at "/" by the dashboard
HTTP server (http_server.py).
"""

from __future__ import annotations

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
  :root {
    --bg: #ffffff; --fg: #1a1a22; --muted: #667085; --line: #e4e7ec;
    --card: #f8fafc; --accent: #4355f9; --ok: #16a34a; --bad: #dc2626;
  }
  @media (prefers-color-scheme: dark) {
    :root { --bg:#101318; --fg:#e6e8ee; --muted:#98a2b3; --line:#2a2f3a;
            --card:#181c24; --accent:#8ba3ff; --ok:#4ade80; --bad:#f87171; }
  }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.45 system-ui, sans-serif; }
  header { display:flex; align-items:baseline; gap:12px; padding:14px 20px;
           border-bottom:1px solid var(--line); }
  header h1 { font-size:16px; margin:0; }
  header .ver { color:var(--muted); font-size:12px; }
  header .upd { margin-left:auto; color:var(--muted); font-size:12px; }
  main { padding:16px 20px; max-width:1200px; margin:0 auto; }
  .tiles { display:grid; grid-template-columns:repeat(auto-fit,minmax(150px,1fr));
           gap:10px; margin-bottom:18px; }
  .tile { background:var(--card); border:1px solid var(--line);
          border-radius:8px; padding:10px 12px; }
  .tile .k { color:var(--muted); font-size:12px; }
  .tile .v { font-size:20px; font-weight:600; margin-top:2px; }
  section { margin-bottom:22px; }
  section h2 { font-size:13px; text-transform:uppercase; letter-spacing:.04em;
               color:var(--muted); margin:0 0 8px; }
  table { width:100%; border-collapse:collapse; background:var(--card);
          border:1px solid var(--line); border-radius:8px; overflow:hidden; }
  th, td { text-align:left; padding:6px 10px; border-bottom:1px solid var(--line);
           font-size:13px; white-space:nowrap; overflow:hidden;
           text-overflow:ellipsis; max-width:320px; }
  th { color:var(--muted); font-weight:500; font-size:12px; }
  tr:last-child td { border-bottom:none; }
  .s-ok { color:var(--ok); } .s-bad { color:var(--bad); }
  .empty { color:var(--muted); padding:8px 10px; font-size:13px; }
  a { color:var(--accent); }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1><span class="ver" id="version"></span>
  <span class="upd" id="updated"></span>
</header>
<main>
  <div class="tiles" id="tiles"></div>
  <section><h2>Nodes</h2><div id="nodes"></div></section>
  <section><h2>Actors</h2><div id="actors"></div></section>
  <section><h2>Task summary</h2><div id="tasksum"></div></section>
  <section><h2>Placement groups</h2><div id="pgs"></div></section>
  <section><h2>Jobs</h2><div id="jobs"></div></section>
  <section><h2>Links</h2>
    <div class="empty"><a href="/metrics">/metrics</a> (Prometheus) ·
      <a href="/api/timeline">/api/timeline</a> ·
      <a href="/api/tasks">/api/tasks</a> ·
      <a href="/api/traces">/api/traces</a></div>
  </section>
</main>
<script>
const fmt = (x) => typeof x === "number" && !Number.isInteger(x)
    ? x.toFixed(2) : String(x);
// Cluster-supplied strings (actor names, job entrypoints, labels) are
// untrusted: escape before any innerHTML insertion (stored-XSS guard).
const esc = (s) => String(s).replace(/[&<>"']/g, (c) => ({
  "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));
function table(el, rows, cols) {
  const div = document.getElementById(el);
  if (!rows || !rows.length) { div.innerHTML = '<div class="empty">none</div>'; return; }
  let h = "<table><tr>" + cols.map(c => `<th>${esc(c[0])}</th>`).join("") + "</tr>";
  for (const r of rows.slice(0, 50)) {
    h += "<tr>" + cols.map(c => {
      let v = typeof c[1] === "function" ? c[1](r) : r[c[1]];
      if (v === undefined || v === null) v = "";
      if (typeof v === "object") v = JSON.stringify(v);
      const cls = /ALIVE|RUNNING|SUCCEEDED|FINISHED|true/.test(String(v)) ? "s-ok"
                : /DEAD|FAILED|ERROR/.test(String(v)) ? "s-bad" : "";
      return `<td class="${cls}">${esc(fmt(v))}</td>`;
    }).join("") + "</tr>";
  }
  div.innerHTML = h + "</table>";
}
async function j(url) {
  try { const r = await fetch(url); return r.ok ? await r.json() : null; }
  catch (e) { return null; }
}
async function refresh() {
  const [ver, status, nodes, actors, tasksum, pgs, jobs] = await Promise.all([
    j("/api/version"), j("/api/cluster_status"), j("/api/nodes"),
    j("/api/actors"), j("/api/task_summary"), j("/api/placement_groups"),
    j("/api/jobs/list"),
  ]);
  if (ver) document.getElementById("version").textContent = "v" + ver.version;
  const tiles = [];
  if (status) {
    const total = status.cluster_resources || {}, avail = status.available_resources || {};
    for (const k of Object.keys(total)) {
      if (k.includes("node:") || k.includes("-head")) continue;
      tiles.push([k, `${fmt(avail[k] ?? 0)} / ${fmt(total[k])}`]);
    }
  }
  if (nodes) tiles.push(["nodes", nodes.length]);
  if (actors) tiles.push(["actors", actors.length]);
  document.getElementById("tiles").innerHTML = tiles.map(
    ([k, v]) => `<div class="tile"><div class="k">${esc(k)}</div><div class="v">${esc(v)}</div></div>`
  ).join("");
  table("nodes", nodes, [["id", "node_id"], ["state", r => r.alive ? "ALIVE" : "DEAD"],
    ["address", r => (r.addr || []).join ? r.addr.join(":") : r.addr],
    ["resources", "resources"], ["available", "available"], ["labels", "labels"]]);
  table("actors", actors, [["id", "actor_id"], ["class", "class_name"],
    ["name", "name"], ["state", "state"], ["node", "node_id"],
    ["restarts", "num_restarts"]]);
  const ts = tasksum ? Object.entries(tasksum).map(([k, v]) => ({state: k, count: v})) : [];
  table("tasksum", ts, [["state", "state"], ["count", "count"]]);
  table("pgs", pgs, [["id", "pg_id"], ["state", "state"], ["strategy", "strategy"],
    ["bundles", "bundles"]]);
  table("jobs", jobs, [["id", r => r.job_id || r.submission_id], ["status", "status"],
    ["entrypoint", "entrypoint"], ["start", "start_time"], ["end", "end_time"]]);
  document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
}
refresh(); setInterval(refresh, 3000);
</script>
</body>
</html>
"""
