from ray_tpu.job_submission.job_manager import JobManager, JobStatus
from ray_tpu.job_submission.sdk import JobSubmissionClient

__all__ = ["JobManager", "JobStatus", "JobSubmissionClient"]
