"""Autoscaler control loop: demand in, launch/terminate decisions out.

Capability parity with the reference's autoscaler v2 (reference:
python/ray/autoscaler/v2/autoscaler.py:51 Autoscaler + monitor.py — each
round reads cluster resource state from the GCS
(GcsAutoscalerStateManager), bin-packs pending demands onto node types,
launches through the provider, and terminates idle nodes): ``update()`` is
one reconciliation round; run it from a monitor loop or tests.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ray_tpu.autoscaler.instance_manager import (
    InstanceManager,
    InstanceStatus,
)
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.scheduler import bin_pack_demands


@dataclass
class NodeTypeConfig:
    resources: dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: dict[str, str] = field(default_factory=dict)


@dataclass
class AutoscalingConfig:
    node_types: dict[str, NodeTypeConfig]
    idle_timeout_s: float = 60.0
    max_launches_per_round: int = 8


class Autoscaler:
    def __init__(self, config: AutoscalingConfig, provider: NodeProvider,
                 head_client):
        """``head_client`` is an RpcClient to the head (for cluster_load)."""
        self.config = config
        self.provider = provider
        self.head = head_client
        self.instances = InstanceManager()
        self._idle_since: dict[str, float] = {}  # node_id -> first idle ts

    # ---------------------------------------------------------------- rounds
    def update(self) -> dict:
        """One reconciliation round; returns a summary for observability."""
        load = self.head.call("cluster_load")
        self._reconcile_allocated(load)
        launches = self._scale_up(load)
        terminated = self._scale_down(load)
        return {"launched": launches, "terminated": terminated,
                "pending_demands": len(load.get("pending_demands", []))}

    # ---------------------------------------------------------------- helpers
    def _counts_by_type(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for inst in self.instances.active():
            counts[inst.node_type] = counts.get(inst.node_type, 0) + 1
        return counts

    def _reconcile_allocated(self, load: dict) -> None:
        """Move REQUESTED/ALLOCATED instances forward as their nodes join."""
        alive_nodes = {nid for nid, n in load["nodes"].items() if n["alive"]}
        for inst in self.instances.instances(
                (InstanceStatus.REQUESTED, InstanceStatus.ALLOCATED)):
            status = self.provider.node_status(inst.cloud_id)
            if status == "failed":
                # REQUESTED never materialized -> ALLOCATION_FAILED; an
                # ALLOCATED node that failed after create (e.g. TPU slice
                # preempted) is simply gone -> TERMINATED. The FSM only
                # permits ALLOCATION_FAILED from REQUESTED.
                self.instances.transition(
                    inst.instance_id,
                    InstanceStatus.ALLOCATION_FAILED
                    if inst.status == InstanceStatus.REQUESTED
                    else InstanceStatus.TERMINATED)
                continue
            if inst.status == InstanceStatus.REQUESTED and status == "running":
                self.instances.transition(
                    inst.instance_id, InstanceStatus.ALLOCATED)
            node_id = self.provider.runtime_node_id(inst.cloud_id)
            if (inst.status == InstanceStatus.ALLOCATED
                    and node_id and node_id in alive_nodes):
                self.instances.transition(
                    inst.instance_id, InstanceStatus.RAY_RUNNING,
                    node_id=node_id)

    def _scale_up(self, load: dict) -> dict[str, int]:
        demands = list(load.get("pending_demands", []))
        demands += list(load.get("pending_pg_bundles", []))
        counts = self._counts_by_type()

        # Min-worker floors count as demands of a full node.
        for name, cfg in self.config.node_types.items():
            for _ in range(max(0, cfg.min_workers - counts.get(name, 0))):
                demands.append(dict(cfg.resources))

        if not demands:
            return {}
        free = [dict(n["available"]) for n in load["nodes"].values()
                if n["alive"]]
        # Capacity already on the way absorbs demand too.
        for inst in self.instances.instances(
                (InstanceStatus.QUEUED, InstanceStatus.REQUESTED,
                 InstanceStatus.ALLOCATED)):
            free.append(dict(self.config.node_types[inst.node_type].resources))
        max_new = {
            name: min(cfg.max_workers - counts.get(name, 0),
                      self.config.max_launches_per_round)
            for name, cfg in self.config.node_types.items()
        }
        launches, _infeasible = bin_pack_demands(
            demands, free,
            {n: c.resources for n, c in self.config.node_types.items()},
            max_new_per_type=max_new,
        )
        for node_type, count in launches.items():
            cfg = self.config.node_types[node_type]
            for _ in range(count):
                inst = self.instances.create(node_type)
                self.instances.transition(inst.instance_id,
                                          InstanceStatus.REQUESTED)
                try:
                    cloud_id = self.provider.launch_node(
                        node_type, dict(cfg.resources), dict(cfg.labels))
                except Exception:
                    self.instances.transition(
                        inst.instance_id, InstanceStatus.ALLOCATION_FAILED)
                    continue
                inst.cloud_id = cloud_id
        return launches

    def _scale_down(self, load: dict) -> list[str]:
        """Terminate RAY_RUNNING nodes idle past the timeout, above floors."""
        now = time.monotonic()
        counts = self._counts_by_type()
        terminated: list[str] = []
        for inst in self.instances.instances((InstanceStatus.RAY_RUNNING,)):
            node = load["nodes"].get(inst.node_id)
            if node is None or not node["alive"]:
                self.instances.transition(inst.instance_id,
                                          InstanceStatus.TERMINATED)
                continue
            idle = (node["available"] == node["resources"]
                    and not node.get("pending", 0))
            if not idle:
                self._idle_since.pop(inst.node_id, None)
                continue
            first = self._idle_since.setdefault(inst.node_id, now)
            floor = self.config.node_types[inst.node_type].min_workers
            if (now - first >= self.config.idle_timeout_s
                    and counts.get(inst.node_type, 0) > floor):
                self.instances.transition(inst.instance_id,
                                          InstanceStatus.RAY_STOPPING)
                try:
                    self.provider.terminate_node(inst.cloud_id)
                finally:
                    self.instances.transition(inst.instance_id,
                                              InstanceStatus.TERMINATED)
                counts[inst.node_type] -= 1
                terminated.append(inst.node_id)
                self._idle_since.pop(inst.node_id, None)
        return terminated

    # ---------------------------------------------------------------- monitor
    def run_monitor(self, interval_s: float = 5.0, stop_event=None) -> None:
        """Blocking reconcile loop (reference: monitor.py)."""
        import threading

        stop_event = stop_event or threading.Event()
        while not stop_event.is_set():
            try:
                self.update()
            except Exception:
                pass
            stop_event.wait(interval_s)
