"""Attention / norm / rope kernels vs reference implementations.

Pallas kernels run in interpret mode on CPU via pltpu force_tpu_interpret_mode
where exercised; numerical ground truth is the O(S²) reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import (
    attention_reference,
    blockwise_attention,
    flash_attention,
)
from ray_tpu.ops.norms import rms_norm_reference
from ray_tpu.ops.ring_attention import ring_attention_sharded
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.parallel.mesh import MeshSpec, build_mesh


def _qkv(b=2, h=4, hkv=None, s=128, d=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype)
    k = jax.random.normal(ks[1], (b, hkv or h, s, d), dtype)
    v = jax.random.normal(ks[2], (b, hkv or h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_reference(causal):
    q, k, v = _qkv()
    ref = attention_reference(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, kv_block=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_blockwise_unaligned_kv_block():
    q, k, v = _qkv(s=96)
    ref = attention_reference(q, k, v)
    out = blockwise_attention(q, k, v, kv_block=40)  # 96 = 2*40 + 16 pad
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gqa_heads():
    q, k, v = _qkv(h=8, hkv=2)
    ref = attention_reference(q, k, v)
    out = blockwise_attention(q, k, v, kv_block=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_cpu_fallback_and_grad():
    q, k, v = _qkv(s=64)

    def loss(q, k, v):
        return flash_attention(q, k, v, True, None, False).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def _force_interpret_mode():
    """pltpu.force_tpu_interpret_mode appeared after jax 0.4.37 — skip
    with the reason instead of erroring (same compat policy as the
    shard_map shim in parallel/): the kernel code paths are still covered
    by the attn_mod.INTERPRET tests below on old releases."""
    import jax
    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "force_tpu_interpret_mode"):
        pytest.skip("pltpu.force_tpu_interpret_mode unavailable on jax "
                    f"{jax.__version__} (added in later releases)")
    return pltpu.force_tpu_interpret_mode()


def test_flash_pallas_interpret_matches_reference():
    q, k, v = _qkv(b=1, h=2, s=256, d=64)
    with _force_interpret_mode():
        from ray_tpu.ops.attention import _flash_fwd_pallas

        out, lse = _flash_fwd_pallas(q, k, v, causal=True, sm_scale=1.0 / 8.0,
                                     block_q=128, block_k=128)
    ref = attention_reference(q, k, v, causal=True, sm_scale=1.0 / 8.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)
    # lse must reproduce softmax normalizers: exp(s - lse) rows sum to 1.
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) / 8.0
    mask = np.tril(np.ones((256, 256), bool))
    s = np.where(mask, s, -np.inf)
    ref_lse = np.log(np.exp(s).sum(-1))
    np.testing.assert_allclose(np.asarray(lse), ref_lse, atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("fused", [True, False])
@pytest.mark.parametrize("h,hkv,causal", [(2, 2, True), (4, 2, True),
                                          (2, 2, False),
                                          # rep=4: pack=4 kernel path + the
                                          # kv_div>1 remainder fold — the
                                          # geometry production Llama uses.
                                          (8, 2, True), (8, 1, False)])
def test_flash_pallas_backward_matches_reference(h, hkv, causal, fused):
    """Gradient equivalence of the Pallas backward kernels (interpret mode)
    against autodiff through attention_reference — incl. the GQA fold —
    for BOTH the fused dq+dkv kernel and the split-kernel fallback."""
    import ray_tpu.ops.attention as attn_mod

    q, k, v = _qkv(b=1, h=h, hkv=hkv, s=256, d=64)
    w = jnp.asarray(
        np.linspace(0.5, 1.5, q.size).reshape(q.shape), jnp.float32)

    def loss(f):
        return lambda q, k, v: (f(q, k, v).astype(jnp.float32) * w).sum()

    attn_mod.INTERPRET = True
    old_fused = attn_mod.FUSED_BWD
    attn_mod.FUSED_BWD = fused
    try:
        g = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal, None, True)), argnums=(0, 1, 2))(q, k, v)
    finally:
        attn_mod.INTERPRET = False
        attn_mod.FUSED_BWD = old_fused
    g_ref = jax.grad(loss(lambda q, k, v: attention_reference(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g, g_ref):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        denom = max(np.abs(b).max(), 1e-9)
        assert np.abs(a - b).max() / denom < 2e-2, name


def test_ring_attention_matches_reference(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(sp=8), cpu_mesh_devices)
    q, k, v = _qkv(b=1, h=2, s=256, d=32)
    ref = attention_reference(q, k, v, causal=True)
    out = ring_attention_sharded(q, k, v, mesh, axis="sp", causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_ring_attention_noncausal(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(sp=4), cpu_mesh_devices[:4])
    q, k, v = _qkv(b=1, h=2, s=64, d=16)
    ref = attention_reference(q, k, v, causal=False)
    out = ring_attention_sharded(q, k, v, mesh, axis="sp", causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_ring_attention_differentiable(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(sp=4), cpu_mesh_devices[:4])
    q, k, v = _qkv(b=1, h=1, s=64, d=16)

    def ring_loss(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, axis="sp").sum()

    def ref_loss(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_rms_norm_reference_properties():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 64)) * 5 + 1
    w = jnp.ones(64)
    y = rms_norm_reference(x, w)
    rms = jnp.sqrt(jnp.mean(y * y, axis=-1))
    np.testing.assert_allclose(np.asarray(rms), 1.0, atol=1e-3)


def test_rms_norm_pallas_interpret():
    from ray_tpu.ops.norms import rms_norm_pallas

    x = jax.random.normal(jax.random.PRNGKey(1), (256, 128))
    w = jax.random.normal(jax.random.PRNGKey(2), (128,))
    with _force_interpret_mode():
        out = rms_norm_pallas(x, w)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(rms_norm_reference(x, w)), atol=1e-5)


def test_rope_rotation_preserves_norm():
    inv = rope_frequencies(64)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 16, 64))
    out = apply_rope(x, jnp.arange(16), inv)
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(out, axis=-1)),
        np.asarray(jnp.linalg.norm(x, axis=-1)), rtol=1e-5,
    )


def test_rope_relative_property():
    # <rope(q, m), rope(k, n)> depends only on m - n
    inv = rope_frequencies(32)
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 1, 32))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1, 32))

    def dot_at(m, n):
        qm = apply_rope(jnp.broadcast_to(q, (1, 1, 1, 32)), jnp.array([m]), inv)
        kn = apply_rope(jnp.broadcast_to(k, (1, 1, 1, 32)), jnp.array([n]), inv)
        return float(jnp.sum(qm * kn))

    assert abs(dot_at(5, 3) - dot_at(12, 10)) < 1e-3


def test_rope_llama3_scaling():
    inv_plain = rope_frequencies(64)
    inv_scaled = rope_frequencies(64, scaling={
        "factor": 8.0, "low_freq_factor": 1.0, "high_freq_factor": 4.0,
        "original_max_position": 8192,
    })
    # low-frequency components shrink; highest frequencies unchanged
    assert np.asarray(inv_scaled)[-1] < np.asarray(inv_plain)[-1]
    np.testing.assert_allclose(np.asarray(inv_scaled)[0],
                               np.asarray(inv_plain)[0])


# ---------------------------------------------------------------------------
# fused cross-entropy (ops/loss.py)
# ---------------------------------------------------------------------------

def _ce_reference(x, head, targets, mask):
    logits = (x.astype(jnp.float32) @ head.astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    m = (jnp.ones_like(nll) if mask is None else mask).astype(jnp.float32)
    return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)


@pytest.mark.parametrize("chunk", [4, 8, 32])
def test_fused_cross_entropy_matches_reference(chunk):
    from ray_tpu.ops.loss import fused_cross_entropy

    key = jax.random.PRNGKey(0)
    b, s, h, v = 2, 16, 8, 32
    x = jax.random.normal(key, (b, s, h), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(1), (h, v), jnp.float32) * 0.2
    targets = jax.random.randint(jax.random.PRNGKey(2), (b, s), 0, v)

    got = fused_cross_entropy(x, head, targets, None, chunk)
    want = _ce_reference(x, head, targets, None)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_cross_entropy_grads_match():
    from ray_tpu.ops.loss import fused_cross_entropy

    key = jax.random.PRNGKey(3)
    b, s, h, v = 2, 8, 8, 24
    x = jax.random.normal(key, (b, s, h), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(4), (h, v), jnp.float32) * 0.2
    targets = jax.random.randint(jax.random.PRNGKey(5), (b, s), 0, v)
    mask = (jax.random.uniform(jax.random.PRNGKey(6), (b, s)) > 0.3)

    gx, gh = jax.grad(
        lambda x_, h_: fused_cross_entropy(x_, h_, targets, mask, 4),
        argnums=(0, 1))(x, head)
    rx, rh = jax.grad(
        lambda x_, h_: _ce_reference(x_, h_, targets, mask),
        argnums=(0, 1))(x, head)
    np.testing.assert_allclose(gx, rx, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gh, rh, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("s,chunk", [(13, 4), (7, 512), (24, 7), (17, 17)])
def test_fused_cross_entropy_odd_seq_nondivisible_chunk(s, chunk):
    """s % chunk != 0 falls back to a single chunk (ops/loss.py): the
    forward AND the custom-vjp backward must both take the fallback and
    agree with the reference — the backward recomputes chunk geometry
    independently, so a fwd/bwd disagreement would silently corrupt
    gradients rather than error."""
    from ray_tpu.ops.loss import fused_cross_entropy

    b, h, v = 2, 8, 24
    x = jax.random.normal(jax.random.PRNGKey(7), (b, s, h), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(8), (h, v), jnp.float32) * 0.2
    targets = jax.random.randint(jax.random.PRNGKey(9), (b, s), 0, v)
    mask = (jax.random.uniform(jax.random.PRNGKey(10), (b, s)) > 0.25)

    got = fused_cross_entropy(x, head, targets, mask, chunk)
    want = _ce_reference(x, head, targets, mask)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    gx, gh = jax.grad(
        lambda x_, h_: fused_cross_entropy(x_, h_, targets, mask, chunk),
        argnums=(0, 1))(x, head)
    rx, rh = jax.grad(
        lambda x_, h_: _ce_reference(x_, h_, targets, mask),
        argnums=(0, 1))(x, head)
    np.testing.assert_allclose(gx, rx, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gh, rh, rtol=1e-4, atol=1e-5)


def test_fused_cross_entropy_divisible_multichunk_grads():
    """Companion boundary case: s % chunk == 0 with several chunks (the
    scan path, not the fallback) at an odd chunk count."""
    from ray_tpu.ops.loss import fused_cross_entropy

    b, s, h, v, chunk = 2, 15, 8, 24, 5
    x = jax.random.normal(jax.random.PRNGKey(11), (b, s, h), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(12), (h, v),
                             jnp.float32) * 0.2
    targets = jax.random.randint(jax.random.PRNGKey(13), (b, s), 0, v)

    gx, gh = jax.grad(
        lambda x_, h_: fused_cross_entropy(x_, h_, targets, None, chunk),
        argnums=(0, 1))(x, head)
    rx, rh = jax.grad(
        lambda x_, h_: _ce_reference(x_, h_, targets, None),
        argnums=(0, 1))(x, head)
    np.testing.assert_allclose(gx, rx, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gh, rh, rtol=1e-4, atol=1e-5)


def test_llama_loss_fused_matches_unfused():
    from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    fused = loss_fn(cfg, params, tokens, targets, attn_impl="blockwise",
                    remat=False, fused_ce=True)
    plain = loss_fn(cfg, params, tokens, targets, attn_impl="blockwise",
                    remat=False, fused_ce=False)
    np.testing.assert_allclose(fused, plain, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_fused_backward_multiblock(causal):
    """Multi-q-block case (s > block_q): exercises the fused kernel's
    dk/dv revisiting accumulation across the sequential grid dimension
    (the s=256 cases above fit one block and never re-enter)."""
    import ray_tpu.ops.attention as attn_mod

    q, k, v = _qkv(b=1, h=1, hkv=1, s=1024, d=64)

    def loss(f):
        return lambda q, k, v: (f(q, k, v).astype(jnp.float32) ** 2).sum()

    attn_mod.INTERPRET = True
    old = attn_mod.FUSED_BWD
    attn_mod.FUSED_BWD = True
    try:
        g = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal, None, True)), argnums=(0, 1, 2))(q, k, v)
    finally:
        attn_mod.INTERPRET = False
        attn_mod.FUSED_BWD = old
    g_ref = jax.grad(loss(lambda q, k, v: attention_reference(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), g, g_ref):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        denom = max(np.abs(b).max(), 1e-9)
        assert np.abs(a - b).max() / denom < 2e-2, name


class TestRingFlashChunk:
    """Ring attention over the Pallas chunk kernel (flash_attention_chunk:
    data-driven causal positions, differentiable lse) must match the
    reference exactly like the einsum path does. INTERPRET runs the real
    kernel code on CPU."""

    def _with_interpret(self, fn):
        import ray_tpu.ops.attention as attn_mod

        attn_mod.INTERPRET = True
        try:
            return fn()
        finally:
            attn_mod.INTERPRET = False

    def test_forward_matches_reference(self, cpu_mesh_devices):
        mesh = build_mesh(MeshSpec(sp=4), cpu_mesh_devices[:4])
        q, k, v = _qkv(b=1, h=2, s=256, d=32)
        ref = attention_reference(q, k, v, causal=True)
        out = self._with_interpret(lambda: ring_attention_sharded(
            q, k, v, mesh, axis="sp", causal=True, impl="flash"))
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32), atol=3e-2)

    def test_forward_gqa_noncausal(self, cpu_mesh_devices):
        mesh = build_mesh(MeshSpec(sp=4), cpu_mesh_devices[:4])
        q, k, v = _qkv(b=1, h=4, hkv=2, s=128, d=32)
        ref = attention_reference(q, k, v, causal=False)
        out = self._with_interpret(lambda: ring_attention_sharded(
            q, k, v, mesh, axis="sp", causal=False, impl="flash"))
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32), atol=3e-2)

    def test_gradients_match_reference(self, cpu_mesh_devices):
        """The cross-chunk (out, lse) combiner backprops through the
        chunk kernel's lse cotangent (ds = p(dp - delta + g_lse))."""
        mesh = build_mesh(MeshSpec(sp=4), cpu_mesh_devices[:4])
        q, k, v = _qkv(b=1, h=2, s=128, d=32)
        w = jnp.asarray(
            np.linspace(0.5, 1.5, q.size).reshape(q.shape), jnp.float32)

        def ring_loss(q, k, v):
            out = ring_attention_sharded(q, k, v, mesh, axis="sp",
                                         causal=True, impl="flash")
            return (out.astype(jnp.float32) * w).sum()

        def ref_loss(q, k, v):
            return (attention_reference(q, k, v, causal=True)
                    .astype(jnp.float32) * w).sum()

        g = self._with_interpret(
            lambda: jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v))
        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g, g_ref):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            denom = max(np.abs(b).max(), 1e-9)
            assert np.abs(a - b).max() / denom < 3e-2, name
