"""Measure achievable bf16 matmul FLOP/s on this chip (MFU ceiling probe).

Chains iterations through a data dependency AND fetches a scalar to host
each timing — on the axon tunnel, block_until_ready alone does not appear
to wait for execution.
"""
import time

import jax
import jax.numpy as jnp
from jax import lax


def bench(n, iters=20):
    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16) * 0.01
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16) * 0.01

    @jax.jit
    def chain(a, b):
        def body(x, _):
            return lax.dot(x, b, preferred_element_type=jnp.bfloat16) * 0.01, None
        out, _ = lax.scan(body, a, None, length=iters)
        return out.astype(jnp.float32).sum()

    float(chain(a, b))  # warmup + compile
    t0 = time.perf_counter()
    s = float(chain(a, b))
    dt = (time.perf_counter() - t0) / iters
    flops = 2 * n * n * n
    print(f"{n}^3 chained+fetch: {dt*1e3:.3f} ms/matmul  "
          f"{flops/dt/1e12:.1f} TFLOP/s (sum={s:.3g})", flush=True)


for n in [2048, 4096, 8192]:
    bench(n)

# asymptote probes: vary iters to separate fixed fetch latency, bigger n
def bench2(n, iters):
    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16) * 0.01
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16) * 0.01

    @jax.jit
    def chain(a, b):
        def body(x, _):
            return lax.dot(x, b, preferred_element_type=jnp.bfloat16) * 0.01, None
        out, _ = lax.scan(body, a, None, length=iters)
        return out.astype(jnp.float32).sum()

    float(chain(a, b))
    t0 = time.perf_counter()
    float(chain(a, b))
    tot = time.perf_counter() - t0
    flops = 2 * n * n * n * iters
    print(f"n={n} iters={iters}: total {tot*1e3:.1f} ms  {flops/tot/1e12:.1f} TFLOP/s",
          flush=True)

for it in [5, 20, 80]:
    bench2(8192, it)
