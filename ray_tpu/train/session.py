"""Per-worker training session: context + report API.

Capability parity with the reference's session (reference:
ray.train.get_context / ray.train.report — python/ray/train/v2/_internal/
execution/context.py shapes; report flows to the controller's checkpoint
manager, SURVEY.md §3.4 step 4).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = "train"
    storage_path: str | None = None
    trial_dir: str | None = None
    coordinator_addr: str | None = None
    restart_count: int = 0
    latest_checkpoint: str | None = None  # dir path, set on restore
    # Multi-slice topology (from JaxBackendConfig.num_slices): lets a
    # train_fn build its hybrid mesh / pick dcn_axes for the spmd step
    # without re-deriving the slice count from MEGASCALE env.
    num_slices: int = 1

    # filled by the worker harness
    dataset_shards: dict = field(default_factory=dict)  # name -> DataIterator
    _reports: list[dict] = field(default_factory=list)
    _report_lock: threading.Lock = field(default_factory=threading.Lock)
    _last_report_ts: float = 0.0  # monotonic ts of the previous report()

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_num_slices(self) -> int:
        return self.num_slices

    def get_checkpoint(self) -> str | None:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        """This worker's streaming split of a Trainer dataset (reference:
        ray.train.get_dataset_shard — v2 DataParallelTrainer datasets= are
        streaming_split across the worker group)."""
        if name not in self.dataset_shards:
            raise KeyError(
                f"no dataset {name!r}; Trainer(datasets={{...}}) keys: "
                f"{sorted(self.dataset_shards)}")
        return self.dataset_shards[name]


_local = threading.local()


def set_context(ctx: TrainContext | None) -> None:
    _local.ctx = ctx


def get_context() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError("ray_tpu.train.get_context() called outside a train worker")
    return ctx


_train_metrics = None
_train_metrics_lock = threading.Lock()


def _get_train_metrics():
    """Lazy singletons: the gauges every report() updates. Created on the
    worker that actually trains, so the federated /metrics shows them under
    that worker's node_id (reference capability: the per-chip tokens/sec and
    MFU numbers papers headline — PAPERS.md Gemma-on-TPU — readable off one
    endpoint instead of living in code comments)."""
    global _train_metrics
    with _train_metrics_lock:
        if _train_metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _train_metrics = {
                "step_time": Gauge(
                    "train_step_time_s",
                    "seconds between consecutive session.report() calls "
                    "(the per-step wall time when reporting per step)",
                    tag_keys=("rank",)),
                "tokens_per_s": Gauge(
                    "train_tokens_per_s",
                    "training throughput: reported tokens / step time",
                    tag_keys=("rank",)),
                "mfu": Gauge(
                    "train_mfu",
                    "achieved model FLOPs utilization (0..1): reported "
                    "flops / step time / peak_flops",
                    tag_keys=("rank",)),
                "reports": Counter(
                    "train_reports_total", "session.report() calls",
                    tag_keys=("rank",)),
            }
        return _train_metrics


def _instrument_report(ctx: TrainContext, metrics: dict[str, Any]) -> None:
    """Derive step-time / tokens-per-sec / MFU gauges from a report.
    Recognized keys: ``tokens`` (or ``tokens_per_step``) per step, ``flops``
    (or ``flops_per_step``) per step, ``peak_flops`` (else RTPU_PEAK_FLOPS
    env), and direct ``tokens_per_s`` / ``mfu`` passthroughs."""
    import os
    import time

    m = _get_train_metrics()
    rank = {"rank": str(ctx.world_rank)}
    m["reports"].inc(tags=rank)
    now = time.monotonic()
    last, ctx._last_report_ts = ctx._last_report_ts, now
    step_time = (now - last) if last else 0.0
    if step_time > 0:
        m["step_time"].set(step_time, tags=rank)
    if "tokens_per_s" in metrics:
        m["tokens_per_s"].set(float(metrics["tokens_per_s"]), tags=rank)
    elif step_time > 0:
        tokens = metrics.get("tokens", metrics.get("tokens_per_step"))
        if tokens:
            m["tokens_per_s"].set(float(tokens) / step_time, tags=rank)
    if "mfu" in metrics:
        m["mfu"].set(float(metrics["mfu"]), tags=rank)
    elif step_time > 0:
        flops = metrics.get("flops", metrics.get("flops_per_step"))
        peak = metrics.get("peak_flops") or \
            float(os.environ.get("RTPU_PEAK_FLOPS", 0) or 0)
        if flops and peak:
            m["mfu"].set(float(flops) / step_time / float(peak), tags=rank)


def report(metrics: dict[str, Any], checkpoint: str | None = None) -> None:
    """Report metrics (and optionally a checkpoint directory the worker has
    already written) to the controller. Non-blocking; the controller collects
    reports when it polls. Also feeds the train gauges
    (train_step_time_s / train_tokens_per_s / train_mfu) so throughput is
    readable off /metrics, not just the report stream."""
    ctx = get_context()
    try:
        _instrument_report(ctx, metrics)
    except Exception:
        pass  # metrics must never fail a training step
    with ctx._report_lock:
        ctx._reports.append({"metrics": dict(metrics), "checkpoint": checkpoint})


def drain_reports(ctx: TrainContext) -> list[dict]:
    with ctx._report_lock:
        out, ctx._reports = ctx._reports, []
    return out


def get_dataset_shard(name: str = "train"):
    """Module-level alias (reference: ray.train.get_dataset_shard)."""
    return get_context().get_dataset_shard(name)
