"""Multi-agent RL: MultiAgentEnv protocol, env runner, and multi-policy PPO.

Capability parity with the reference's multi-agent stack (reference:
rllib/env/multi_agent_env.py MultiAgentEnv — dict-keyed obs/reward/done per
agent with the "__all__" episode terminator; rllib/env/
multi_agent_env_runner.py collects per-agent trajectories and a
policy_mapping_fn routes each agent to the policy that acts for (and trains
on) its experience; algorithms then update every policy on its own batch).
TPU-native shape: per-policy updates are the existing jitted PPO update —
multi-agency is pure batch routing around it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.ppo import (_act, compute_gae_jit, init_policy,
                            ppo_update)
from ray_tpu.tune.trainable import Trainable


class MultiAgentEnv:
    """Dict-keyed multi-agent episode protocol (reference:
    multi_agent_env.py): reset() -> {agent: obs}; step({agent: action}) ->
    (obs, rewards, dones) dicts, with dones["__all__"] ending the episode."""

    agent_ids: tuple[str, ...] = ()
    observation_size: int = 0
    num_actions: int = 0

    def reset(self) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: dict[str, int]):
        raise NotImplementedError


class CoordinationGame(MultiAgentEnv):
    """Two agents earn +1 each step their actions MATCH; episodes last
    ``horizon`` steps. Observations: one-hot of the previous joint action
    plus the step fraction — enough signal for independent policies to
    lock onto one equilibrium. Optimal per-agent episode return ==
    horizon."""

    agent_ids = ("a0", "a1")
    observation_size = 5
    num_actions = 2

    def __init__(self, horizon: int = 16, seed: int = 0):
        self.horizon = horizon
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._last = (0, 0)

    def _obs(self) -> dict[str, np.ndarray]:
        joint = np.zeros(4, np.float32)
        joint[self._last[0] * 2 + self._last[1]] = 1.0
        frac = np.array([self._t / self.horizon], np.float32)
        o = np.concatenate([joint, frac])
        return {a: o.copy() for a in self.agent_ids}

    def reset(self) -> dict[str, np.ndarray]:
        self._t = 0
        self._last = (int(self._rng.integers(2)), int(self._rng.integers(2)))
        return self._obs()

    def step(self, actions: dict[str, int]):
        self._t += 1
        a0, a1 = int(actions["a0"]), int(actions["a1"])
        self._last = (a0, a1)
        r = 1.0 if a0 == a1 else 0.0
        rewards = {a: r for a in self.agent_ids}
        done = self._t >= self.horizon
        dones = {a: done for a in self.agent_ids}
        dones["__all__"] = done
        return self._obs(), rewards, dones


class ChaseGame(MultiAgentEnv):
    """Mixed cooperative-competitive pursuit on a ring (the predator-prey
    shape of rllib's multi-agent examples): two predators share a team
    objective — corner the prey — while the prey's reward is zero-sum
    against them. Exercises heterogeneous policies (predator vs prey
    objectives), one policy serving MULTIPLE agent slots, and true
    terminations (capture) alongside time-limit truncation.

    Ring of ``size`` cells; actions {left, stay, right}. Capture (any
    predator on the prey's cell): predators +5, prey -5, episode ends.
    Per step: predators -0.05 (time pressure), prey +0.05 (survival).

    The ring must be large enough that random predators DON'T stumble
    into captures within a few steps — on size 12 a random-policy
    predator already returned ~4.6 of the ~4.95 ceiling, leaving no
    learnable headroom (the root cause of the long-skipped predator-gain
    test); at 20 cells random play mostly times out (~1.7 return) and
    directed pursuit is something the policy has to learn."""

    agent_ids = ("pred0", "pred1", "prey")
    observation_size = 5
    num_actions = 3

    def __init__(self, size: int = 20, horizon: int = 64, seed: int = 0):
        self.size = size
        self.horizon = horizon
        self._rng = np.random.default_rng(seed)
        self._pos = {a: 0 for a in self.agent_ids}
        self._t = 0
        self.captures = 0
        self.episodes = 0

    def _rel(self, a: str, b: str) -> tuple[float, float]:
        ang = 2 * np.pi * (self._pos[b] - self._pos[a]) / self.size
        return np.sin(ang), np.cos(ang)

    def _obs(self) -> dict[str, np.ndarray]:
        frac = self._t / self.horizon
        out = {}
        for a in self.agent_ids:
            others = [x for x in self.agent_ids if x != a]
            feats = []
            for o in others:
                feats.extend(self._rel(a, o))
            feats.append(frac)
            out[a] = np.asarray(feats, np.float32)
        return out

    def reset(self) -> dict[str, np.ndarray]:
        self._t = 0
        cells = self._rng.choice(self.size, size=3, replace=False)
        for a, c in zip(self.agent_ids, cells):
            self._pos[a] = int(c)
        return self._obs()

    def step(self, actions: dict[str, int]):
        self._t += 1
        for a in self.agent_ids:
            self._pos[a] = (self._pos[a] + int(actions[a]) - 1) % self.size
        caught = (self._pos["prey"] == self._pos["pred0"]
                  or self._pos["prey"] == self._pos["pred1"])
        if caught:
            rewards = {"pred0": 5.0, "pred1": 5.0, "prey": -5.0}
        else:
            rewards = {"pred0": -0.05, "pred1": -0.05, "prey": 0.05}
        done = caught or self._t >= self.horizon
        if done:
            self.episodes += 1
            if caught:
                self.captures += 1
        dones = {a: done for a in self.agent_ids}
        dones["__all__"] = done
        return self._obs(), rewards, dones


def make_multi_agent_env(name: str, seed: int = 0,
                         **kwargs) -> MultiAgentEnv:
    if name == "CoordinationGame":
        return CoordinationGame(seed=seed, **kwargs)
    if name == "ChaseGame":
        return ChaseGame(seed=seed, **kwargs)
    raise ValueError(f"unknown multi-agent env {name!r}")


class MultiAgentEnvRunner:
    """Per-agent trajectory collection with policy routing (reference:
    multi_agent_env_runner.py): each step, every live agent's observation
    goes to the policy policy_mapping_fn assigns it; experience lands in
    that POLICY's batch. sample() returns {policy_id: [T, K, ...]} where K
    is the number of agent slots mapped to the policy."""

    def __init__(self, env_name: str, rollout_len: int,
                 policy_mapping_fn: Callable[[str], str],
                 act_fns: dict[str, Callable], seed: int = 0,
                 env_kwargs: dict | None = None):
        self.env = make_multi_agent_env(env_name, seed=seed,
                                        **(env_kwargs or {}))
        self.rollout_len = rollout_len
        self.policy_mapping_fn = policy_mapping_fn
        self.act_fns = act_fns
        self.params: dict[str, Any] = {}
        self._seed = seed
        self._step = 0
        self._obs = self.env.reset()
        self._episode_return = 0.0
        self._episode_returns: list[float] = []
        self._agent_return = {a: 0.0 for a in self.env.agent_ids}
        self._agent_returns: list[dict[str, float]] = []
        # Fixed slot order per policy: [T, K] batches need stable columns.
        self._slots: dict[str, list[str]] = {}
        for agent in self.env.agent_ids:
            pid = self.policy_mapping_fn(agent)
            self._slots.setdefault(pid, []).append(agent)

    def set_weights(self, params: dict[str, Any]) -> None:
        self.params = params

    def sample(self) -> dict[str, dict]:
        T = self.rollout_len
        env = self.env
        out: dict[str, dict] = {}
        for pid, agents in self._slots.items():
            K = len(agents)
            out[pid] = {
                "obs": np.zeros((T, K, env.observation_size), np.float32),
                "actions": np.zeros((T, K), np.int32),
                "logp": np.zeros((T, K), np.float32),
                "values": np.zeros((T, K), np.float32),
                "rewards": np.zeros((T, K), np.float32),
                "dones": np.zeros((T, K), np.bool_),
            }
        for t in range(T):
            self._step += 1
            actions: dict[str, int] = {}
            for pid, agents in self._slots.items():
                obs = np.stack([self._obs[a] for a in agents])
                a, lp, v = self.act_fns[pid](
                    self.params[pid], obs,
                    self._seed * 100_003 + self._step)
                b = out[pid]
                b["obs"][t] = obs
                b["actions"][t], b["logp"][t], b["values"][t] = a, lp, v
                for k, agent in enumerate(agents):
                    actions[agent] = int(a[k])
            self._obs, rewards, dones = env.step(actions)
            self._episode_return += float(np.mean(list(rewards.values())))
            for a, r in rewards.items():
                self._agent_return[a] += float(r)
            for pid, agents in self._slots.items():
                b = out[pid]
                b["rewards"][t] = [rewards[a] for a in agents]
                b["dones"][t] = [dones[a] for a in agents]
            if dones.get("__all__"):
                self._episode_returns.append(self._episode_return)
                self._episode_return = 0.0
                self._agent_returns.append(dict(self._agent_return))
                self._agent_return = {a: 0.0 for a in env.agent_ids}
                self._obs = env.reset()
        # Bootstrap values from the current obs under each policy.
        for pid, agents in self._slots.items():
            obs = np.stack([self._obs[a] for a in agents])
            _, _, last_v = self.act_fns[pid](
                self.params[pid], obs, self._seed * 100_003 + self._step + 1)
            out[pid]["last_values"] = np.asarray(last_v, np.float32)
        out["__episode_returns__"] = self._episode_returns
        self._episode_returns = []
        out["__agent_episode_returns__"] = self._agent_returns
        self._agent_returns = []
        return out


@dataclass
class MultiAgentPPOConfig:
    env: str = "CoordinationGame"
    env_kwargs: dict = field(default_factory=dict)
    # policy_ids + mapping: default = one shared policy for every agent
    # (reference: the shared-policy default of multi-agent configs).
    policies: tuple[str, ...] = ("shared",)
    policy_mapping: dict = field(default_factory=dict)  # agent -> policy
    num_env_runners: int = 0
    rollout_len: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip: float = 0.2
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    num_minibatches: int = 4
    num_epochs: int = 4
    hidden: int = 32
    seed: int = 0

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO({"ma_config": self})


class MultiAgentPPO(Trainable):
    """Independent/shared-policy PPO over a MultiAgentEnv (reference:
    rllib multi-agent training — each policy updates on the batch its
    agents produced)."""

    def setup(self, config: dict) -> None:
        cfg = config.get("ma_config") or MultiAgentPPOConfig(
            **{k: v for k, v in config.items()
               if k in MultiAgentPPOConfig.__dataclass_fields__})
        self.cfg = cfg
        probe = make_multi_agent_env(cfg.env, seed=cfg.seed,
                                     **cfg.env_kwargs)

        def mapping(agent: str) -> str:
            return cfg.policy_mapping.get(agent, cfg.policies[0])

        self.mapping = mapping
        self.policies: dict[str, Any] = {}
        self.opt_states: dict[str, Any] = {}
        self.optimizer = optax.adam(cfg.lr)
        for i, pid in enumerate(cfg.policies):
            self.policies[pid] = init_policy(
                jax.random.PRNGKey(cfg.seed + i), probe.observation_size,
                probe.num_actions, cfg.hidden)
            self.opt_states[pid] = self.optimizer.init(self.policies[pid])

        def act(p, obs, seed):
            a, lp, v = _act(p, jnp.asarray(obs), seed)
            return np.asarray(a), np.asarray(lp), np.asarray(v)

        self._runner = MultiAgentEnvRunner(
            cfg.env, cfg.rollout_len, mapping,
            {pid: act for pid in cfg.policies}, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs)
        self._return_window: list[float] = []
        self._policy_returns: dict[str, list[float]] = {}

    def step(self) -> dict:
        cfg = self.cfg
        self._runner.set_weights(self.policies)
        sample = self._runner.sample()
        self._return_window.extend(sample.pop("__episode_returns__"))
        stats: dict = {}
        # Per-POLICY mean episode return: in mixed-sum envs the all-agent
        # mean washes out (predator gains cancel prey losses).
        for ep in sample.pop("__agent_episode_returns__", []):
            by_pid: dict[str, list[float]] = {}
            for agent, ret in ep.items():
                by_pid.setdefault(self.mapping(agent), []).append(ret)
            for pid, rets in by_pid.items():
                self._policy_returns.setdefault(pid, []).append(
                    float(np.mean(rets)))
        for pid, window in self._policy_returns.items():
            self._policy_returns[pid] = window[-100:]
            stats[f"{pid}/episode_return_mean"] = float(np.mean(window))
        static = (cfg.clip, cfg.vf_coef, cfg.ent_coef, cfg.num_minibatches,
                  cfg.num_epochs)
        for pid, s in sample.items():
            adv, ret = compute_gae_jit(
                jnp.asarray(s["rewards"]), jnp.asarray(s["values"]),
                jnp.asarray(s["dones"]), jnp.asarray(s["last_values"]),
                cfg.gamma, cfg.gae_lambda)
            batch = {
                "obs": jnp.asarray(
                    s["obs"].reshape(-1, s["obs"].shape[-1])),
                "actions": jnp.asarray(s["actions"].reshape(-1)),
                "logp": jnp.asarray(s["logp"].reshape(-1)),
                "advantages": jnp.asarray(np.asarray(adv).reshape(-1)),
                "returns": jnp.asarray(np.asarray(ret).reshape(-1)),
            }
            self.policies[pid], self.opt_states[pid], pstats = ppo_update(
                self.optimizer, static, self.policies[pid],
                self.opt_states[pid], batch, cfg.seed + self.iteration)
            stats.update({f"{pid}/{k}": float(v) for k, v in pstats.items()})
        self._return_window = self._return_window[-100:]
        mean_ret = (float(np.mean(self._return_window))
                    if self._return_window else 0.0)
        return {"episode_return_mean": mean_ret,
                "policies": list(self.policies), **stats}

    def save_checkpoint(self) -> Any:
        return {"policies": jax.tree.map(np.asarray, self.policies),
                "iteration": self.iteration}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.policies = jax.tree.map(jnp.asarray, checkpoint["policies"])
        self.iteration = checkpoint["iteration"]
