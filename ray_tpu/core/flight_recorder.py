"""Failure flight recorder: timestamped debug bundles on task/worker/actor
failure.

When a task fails terminally, a worker dies, or an actor is declared dead,
the runtime dumps the last-N task events, the finished spans, and a metrics
snapshot for this process into a JSON bundle under
``<temp_dir>/flight_records/`` (reference capability: the post-mortem slice
of the reference's dashboard — GcsTaskManager's retained failed-task events
plus the metrics agent's last scrape — condensed into one artifact that
survives the process). Bundles are bounded (oldest deleted) and recording is
rate-limited so a failure storm can't turn the error path into a disk
benchmark. Retrieval: ``ray_tpu.util.state.list_flight_records()`` /
``get_flight_record()`` and ``python -m ray_tpu flight-records``.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ray_tpu.utils.config import get_config

_lock = threading.Lock()
_last_record_ts = 0.0
# Floor between dumps: failure bundles include the last-N events anyway, so
# a suppressed dump's context lands in the next one.
MIN_INTERVAL_S = 0.05
EVENTS_TAIL = 500
SPANS_TAIL = 500


def records_dir() -> str:
    return os.path.join(get_config().temp_dir, "flight_records")


def record(kind: str, reason: str = "", task_id: str = "",
           actor_id: str = "", node_id: str = "",
           extra: dict | None = None,
           local_only: bool = False) -> str | None:
    """Dump a debug bundle; returns its path, or None when disabled,
    rate-limited, or anything at all goes wrong (the failure path being
    instrumented must never fail harder because of the recorder).

    ``local_only`` skips every cluster RPC while building the bundle —
    required from signal handlers and kill-grace windows, where a blocking
    head round-trip could hang past the SIGKILL (or forever, when the RPC
    plane being wedged is exactly why the dump was requested)."""
    global _last_record_ts
    try:
        cfg = get_config()
        if not cfg.flight_recorder_enabled:
            return None
        now = time.monotonic()
        with _lock:
            if now - _last_record_ts < MIN_INTERVAL_S:
                return None
            _last_record_ts = now
        bundle = _build_bundle(kind, reason, task_id, actor_id, node_id,
                               extra, local_only)
        d = records_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"fr-{time.time_ns()}-{kind}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)
        _prune(d, cfg.flight_recorder_max_bundles)
        return path
    except Exception:
        return None


def _build_bundle(kind, reason, task_id, actor_id, node_id, extra,
                  local_only: bool = False) -> dict:
    from ray_tpu.core import events as _events
    from ray_tpu.util import metrics as _metrics
    from ray_tpu.util import tracing as _tracing

    import asyncio

    try:
        asyncio.get_running_loop()
        on_io_loop = True
    except RuntimeError:
        on_io_loop = False
    # Slice BEFORE converting: the rings hold up to 100k entries and some
    # record() callers run on a node's control-plane event loop — asdict
    # over the full ring there would stall heartbeats/lease handling.
    try:
        if on_io_loop or local_only:
            # record() from an event-loop coroutine (actor-death paths) or
            # a caller that cannot block (signal handlers): an RPC would
            # deadlock / hang, so settle for the local buffer + the
            # already-fetched cluster cache.
            raw = _events.global_event_buffer().events()
            raw.extend(_events._cluster_cache)
        else:
            # Include the head-collected cluster events so the bundle shows
            # the failing task's full lifecycle even when its
            # SUBMITTED/RUNNING halves live in other processes.
            raw = _events.all_events()
    except Exception:
        raw = _events.global_event_buffer().events()
    evs = [e if isinstance(e, dict) else _event_dict(e)
           for e in raw[-EVENTS_TAIL:]]
    from dataclasses import asdict as _asdict

    spans = [_asdict(s) for s in _tracing.spans()[-SPANS_TAIL:]]
    if not on_io_loop and not local_only:
        # Cluster mode: local spans alone miss the submitter's client span
        # (it lives in the driver process and reaches the head via its
        # telemetry flusher) — merge the head's view so a worker-side
        # bundle still shows the whole trace.
        try:
            from ray_tpu.core.worker import global_worker

            rt = global_worker.runtime
            if rt is not None and hasattr(rt, "cluster_spans"):
                have = {s["span_id"] for s in spans}
                spans.extend(s for s in rt.cluster_spans()[-SPANS_TAIL:]
                             if s.get("span_id") not in have)
        except Exception:
            pass  # head unreachable: local spans still useful
    return {
        "ts": time.time(),
        "kind": kind,
        "reason": reason,
        "task_id": task_id,
        "actor_id": actor_id,
        "node_id": node_id,
        "pid": os.getpid(),
        "events": evs,
        # Bounded already: ≤ SPANS_TAIL local + ≤ SPANS_TAIL head-merged
        # (slicing the merged list would cut the local worker spans — the
        # ones the bundle exists for — in favor of later-appended ones).
        "spans": spans,
        "metrics": _metrics.registry().snapshot(),
        "extra": dict(extra or {}),
    }


def _event_dict(e) -> dict:
    return {
        "task_id": e.task_id, "name": e.name, "state": e.state, "ts": e.ts,
        "worker_id": e.worker_id, "node_id": e.node_id,
        "actor_id": e.actor_id, "job_id": e.job_id, "extra": e.extra,
    }


def _prune(d: str, keep: int) -> None:
    names = sorted(n for n in os.listdir(d)
                   if n.startswith("fr-") and n.endswith(".json"))
    for n in names[:-keep] if keep > 0 else names:
        try:
            os.remove(os.path.join(d, n))
        except OSError:
            pass


def list_records() -> list[dict]:
    """Bundle index, newest last (name encodes the nanosecond timestamp)."""
    d = records_dir()
    out: list[dict] = []
    try:
        names = sorted(n for n in os.listdir(d)
                       if n.startswith("fr-") and n.endswith(".json"))
    except FileNotFoundError:
        return out
    for n in names:
        parts = n[:-len(".json")].split("-", 2)
        out.append({
            "name": n,
            "path": os.path.join(d, n),
            "ts_ns": int(parts[1]) if len(parts) > 2 and
            parts[1].isdigit() else 0,
            "kind": parts[2] if len(parts) > 2 else "",
        })
    return out


def get_record(name: str) -> dict:
    path = os.path.join(records_dir(), os.path.basename(name))
    with open(path) as f:
        return json.load(f)
