"""EnvRunner: rollout collection, locally or as a fleet of actors.

Capability parity with the reference's runner group (reference:
rllib/env/env_runner.py:36 EnvRunner ABC, single_agent_env_runner.py:67
sample(); env_runner_group.py fans out sampling and syncs weights; the
fault-aware group tolerates dead runners via utils/actor_manager.py
FaultAwareApply): runners hold vectorized envs + the current policy params
and return fixed-length trajectory batches; the group broadcasts weights,
samples in parallel, and replaces dead runners.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np


def _frozen_apply(pipeline, x):
    """Apply a pipeline without updating stateful connectors."""
    if hasattr(pipeline, "frozen_apply"):
        return pipeline.frozen_apply(x)
    prior = getattr(pipeline, "frozen", False)
    pipeline.frozen = True
    try:
        return pipeline(x)
    finally:
        pipeline.frozen = prior


class EnvRunner:
    """One runner = N vectorized envs + a policy-apply function."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 policy_factory: Callable, seed: int = 0,
                 env_to_module=None, module_to_env=None):
        from ray_tpu.rl.env import VectorEnv

        self.vec = VectorEnv(env_name, num_envs, seed=seed)
        self.rollout_len = rollout_len
        # policy_factory() -> (act_fn, initial_params); act_fn(params, obs,
        # rng_seed) -> (actions, logp, value) as numpy.
        self.act_fn, self.params = policy_factory()
        # Connector pipelines (reference: rllib/connectors/): observations
        # flow through env_to_module before the policy; actions flow
        # through module_to_env before the environment. Batches store the
        # TRANSFORMED obs (what the model consumed) and the MODEL-space
        # actions, so the learner trains in the model's space.
        self.env_to_module = env_to_module
        self.module_to_env = module_to_env
        raw = self.vec.reset()
        self.obs = (self.env_to_module(raw) if self.env_to_module
                    else raw)
        self._seed = seed
        self._step = 0

    def set_weights(self, params: Any) -> None:
        self.params = params

    def sample(self) -> dict:
        """Collect rollout_len steps per env: a [T, N, ...] batch plus the
        bootstrap values the learner's GAE needs."""
        T, N = self.rollout_len, self.vec.num_envs
        obs_b = np.zeros((T, N, self.obs.shape[-1]), np.float32)
        act_b = None  # allocated from the first action batch: discrete
        # policies emit [N] ints, continuous ones [N, act_dim] floats
        logp_b = np.zeros((T, N), np.float32)
        val_b = np.zeros((T, N), np.float32)
        rew_b = np.zeros((T, N), np.float32)
        done_b = np.zeros((T, N), np.bool_)
        term_b = np.zeros((T, N), np.bool_)
        next_obs_b = np.zeros((T, N, self.obs.shape[-1]), np.float32)

        for t in range(T):
            self._step += 1
            actions, logp, value = self.act_fn(self.params, self.obs,
                                               self._seed * 100_003 + self._step)
            if act_b is None:
                act_b = np.zeros((T,) + np.shape(actions),
                                 np.asarray(actions).dtype)
            obs_b[t] = self.obs
            act_b[t], logp_b[t], val_b[t] = actions, logp, value
            env_actions = (self.module_to_env(actions)
                           if self.module_to_env else actions)
            raw_obs, rew_b[t], done_b[t] = self.vec.step(env_actions)
            term_b[t] = self.vec.last_terminals
            raw_next = self.vec.last_final_obs  # pre-reset successors
            if self.env_to_module is not None:
                # next_obs passes through the pipeline WITHOUT mutating
                # stateful connectors (it is a bootstrap input, not a
                # policy step); episode boundaries reset per-env state.
                next_obs_b[t] = _frozen_apply(self.env_to_module, raw_next)
                for i in np.nonzero(done_b[t])[0]:
                    self.env_to_module.reset(int(i))
                self.obs = self.env_to_module(raw_obs)
            else:
                next_obs_b[t] = raw_next
                self.obs = raw_obs
        _, _, last_value = self.act_fn(self.params, self.obs,
                                       self._seed * 100_003 + self._step + 1)
        return {
            "obs": obs_b, "actions": act_b, "logp": logp_b, "values": val_b,
            "rewards": rew_b, "dones": done_b, "terminals": term_b,
            "next_obs": next_obs_b, "last_values": last_value,
            "last_obs": np.asarray(self.obs, np.float32),  # for 1-step targets
            "episode_returns": self.vec.drain_episode_returns(),
        }

    def ping(self) -> bool:
        return True

    def connector_state(self) -> dict:
        out = {}
        if self.env_to_module is not None:
            out["env_to_module"] = self.env_to_module.state_dict()
        if self.module_to_env is not None:
            out["module_to_env"] = self.module_to_env.state_dict()
        return out

    def set_connector_state(self, state: dict) -> None:
        if self.env_to_module is not None and "env_to_module" in state:
            self.env_to_module.set_state(state["env_to_module"])
        if self.module_to_env is not None and "module_to_env" in state:
            self.module_to_env.set_state(state["module_to_env"])


class EnvRunnerGroup:
    """Fan-out sampling over runner actors; num_runners=0 runs inline
    (reference: num_env_runners=0 -> local EnvRunner)."""

    def __init__(self, env_name: str, *, num_runners: int = 0,
                 num_envs_per_runner: int = 8, rollout_len: int = 64,
                 policy_factory: Callable, seed: int = 0,
                 connector_factory: Callable | None = None):
        """connector_factory() -> (env_to_module, module_to_env) pipelines,
        built PER RUNNER (stateful connectors are runner-local)."""
        self._args = (env_name, num_envs_per_runner, rollout_len,
                      policy_factory)
        self._connector_factory = connector_factory
        self._seed = seed
        self.num_runners = num_runners
        if num_runners == 0:
            e2m, m2e = (connector_factory() if connector_factory
                        else (None, None))
            self._local = EnvRunner(env_name, num_envs_per_runner,
                                    rollout_len, policy_factory, seed=seed,
                                    env_to_module=e2m, module_to_env=m2e)
            self.actors = []
        else:
            self._local = None
            self.actors = [self._spawn(i) for i in range(num_runners)]

    def _spawn(self, idx: int):
        import ray_tpu

        RunnerActor = ray_tpu.remote(EnvRunner)
        e2m, m2e = (self._connector_factory()
                    if self._connector_factory else (None, None))
        return RunnerActor.options(num_cpus=0).remote(
            *self._args, seed=self._seed + idx * 1000,
            env_to_module=e2m, module_to_env=m2e)

    def sample(self, params) -> list[dict]:
        import ray_tpu

        if self._local is not None:
            self._local.set_weights(params)
            return [self._local.sample()]
        ref = ray_tpu.put(params)  # one broadcast object, not N copies
        out, dead = [], []
        live = []
        # Submit-then-gather: every RPC is in flight before the first
        # get, so N runners cost one round-trip latency, not N (the
        # serialized per-actor get was pure Python overhead in the A/B
        # against the vectorized paths). Gets stay per-actor so a dead
        # runner still doesn't sink the whole step.
        weight_refs = [(i, a, a.set_weights.remote(ref))
                       for i, a in enumerate(self.actors)]
        for i, a, r in weight_refs:
            try:
                ray_tpu.get(r, timeout=120)
                live.append((i, a))
            except ray_tpu.ActorDiedError:
                dead.append(i)
        sample_refs = [(i, a.sample.remote()) for i, a in live]
        for i, r in sample_refs:
            try:
                out.append(ray_tpu.get(r, timeout=120))
            except ray_tpu.ActorDiedError:
                dead.append(i)
        # Fault tolerance: replace dead runners; the surviving sample set
        # still trains this iteration (reference: FaultAwareApply).
        for i in dead:
            self.actors[i] = self._spawn(i + self._seed + 17)
        return out

    def connector_state(self) -> dict:
        """Rank-0 runner's connector state (checkpointing)."""
        if self._local is not None:
            return self._local.connector_state()
        import ray_tpu

        for a in self.actors:
            try:
                return ray_tpu.get(a.connector_state.remote(), timeout=60)
            except ray_tpu.ActorDiedError:
                continue
        return {}

    def set_connector_state(self, state: dict) -> None:
        if not state:
            return
        if self._local is not None:
            self._local.set_connector_state(state)
            return
        import ray_tpu

        for a in self.actors:
            try:
                ray_tpu.get(a.set_connector_state.remote(state),
                            timeout=60)
            except ray_tpu.ActorDiedError:
                pass

    def shutdown(self) -> None:
        import ray_tpu

        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
