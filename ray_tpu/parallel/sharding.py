"""Logical-axis sharding rules: how tensors map onto the mesh.

TPU-native replacement for the reference's per-framework sharding (reference:
ray.train torch path wraps DDP/FSDP per-parameter at runtime,
train_loop_utils.py:153; vLLM owns TP layout): here sharding is declarative —
params/activations carry *logical* axis names and a rule table maps logical →
mesh axes; XLA inserts the collectives. Swapping dp↔fsdp↔tp strategy is a
rule-table change, not a model change.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rule table for transformer training (MaxText-style conventions):
# logical axis name -> mesh axis (or tuple of mesh axes, or None = replicate).
DEFAULT_RULES: dict[str, object] = {
    # params
    "vocab": "tp",
    "embed": ("fsdp",),          # weight-shard over fsdp
    "mlp": "tp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "layers": None,              # stacked-layer leading axis (scan over layers)
    "expert": "ep",
    # activations
    "batch": ("dp", "fsdp"),     # global batch split over both data axes
    "seq": "sp",
    "act_embed": None,
    "act_heads": "tp",
}


@dataclass
class ShardingRules:
    rules: dict[str, object] = field(default_factory=lambda: dict(DEFAULT_RULES))

    def spec(self, *logical_axes: str | None) -> P:
        """PartitionSpec for a tensor whose dims have these logical names."""
        out = []
        used: set[str] = set()
        for ax in logical_axes:
            if ax is None:
                out.append(None)
                continue
            mesh_ax = self.rules.get(ax)
            if mesh_ax is None:
                out.append(None)
            elif isinstance(mesh_ax, tuple):
                fresh = tuple(m for m in mesh_ax if m not in used)
                used.update(fresh)
                out.append(fresh if len(fresh) > 1 else (fresh[0] if fresh else None))
            else:
                if mesh_ax in used:
                    out.append(None)
                else:
                    used.add(mesh_ax)
                    out.append(mesh_ax)
        return P(*out)

    def sharding(self, mesh: Mesh, *logical_axes: str | None) -> NamedSharding:
        return NamedSharding(mesh, self.spec(*logical_axes))

    def override(self, **updates) -> "ShardingRules":
        return ShardingRules({**self.rules, **updates})


def tree_shardings(mesh: Mesh, logical_tree, rules: ShardingRules | None = None):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    rules = rules or ShardingRules()
    return jax.tree.map(
        lambda axes: rules.sharding(mesh, *axes),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(a, (str, type(None))) for a in x
        ),
    )


def shard_params(params, mesh: Mesh, logical_tree, rules: ShardingRules | None = None):
    """Device_put a param pytree with shardings derived from logical axes."""
    shardings = tree_shardings(mesh, logical_tree, rules)
    return jax.tree.map(jax.device_put, params, shardings)
