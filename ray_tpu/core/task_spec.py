"""Task/actor specifications — the unit of scheduling.

Capability parity with the reference's TaskSpecification / lease specs
(reference: src/ray/common/lease/ + protobuf common.proto TaskSpec): a task
names a serialized function, serialized args with out-of-band ObjectRefs,
a resource-shape demand, retry policy, and a scheduling strategy. The
(resources × function × runtime-env) tuple forms the SchedulingKey used for
worker-lease reuse (reference: normal_task_submitter.h:52).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ray_tpu.utils.ids import ActorID, JobID, ObjectID, TaskID, WorkerID


@dataclass
class SchedulingStrategy:
    """DEFAULT (hybrid pack/spread), SPREAD, node-affinity, or PG bundle."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id_hex: str | None = None
    soft: bool = False
    placement_group_id_hex: str | None = None
    bundle_index: int = -1


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    fn_blob: bytes  # cloudpickled callable (or method name for actor tasks)
    args_blob: bytes  # serialized (args, kwargs) with refs replaced by markers
    # Content address of the function definition in the head's registry
    # (reference: FunctionDescriptor + GCS function table). When set,
    # fn_blob is empty and executors fetch-and-cache the definition by id —
    # repeat submissions ship O(spec-header) bytes, not the pickled code.
    fn_id: str = ""
    arg_ref_ids: list[ObjectID] = field(default_factory=list)
    arg_owner_ids: list[WorkerID | None] = field(default_factory=list)
    num_returns: int | str = 1  # int, or "streaming" (generator task)
    resources: dict[str, float] = field(default_factory=dict)
    max_retries: int = 3
    retry_exceptions: bool = False
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: dict[str, Any] | None = None
    name: str = ""
    owner_id: WorkerID | None = None
    trace_ctx: dict[str, Any] | None = None  # propagated tracing context

    # actor-task fields
    actor_id: ActorID | None = None
    method_name: str | None = None
    seq_no: int = -1

    @property
    def is_actor_task(self) -> bool:
        return self.actor_id is not None and self.method_name is not None

    def return_ids(self) -> list[ObjectID]:
        if self.num_returns == "streaming":
            # The stream-end marker is the task's one pre-declared return:
            # errors land there and the consumer's generator raises them.
            from ray_tpu.core.object_ref import STREAM_END_INDEX

            return [ObjectID.for_task_return(self.task_id, STREAM_END_INDEX)]
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]

    def scheduling_key(self) -> tuple:
        # Computed once per spec: it's consulted on both lease acquire and
        # release, and the env canonicalization walks the whole env dict.
        cached = getattr(self, "_sched_key", None)
        if cached is None:
            # Canonical JSON: runtime_env values are nested dicts/lists,
            # which are unhashable as raw tuple members. MUST be the shared
            # canonicalizer — the daemon matches worker brands on it.
            from ray_tpu.runtime_env.container import canonical_env_json

            env_key = canonical_env_json(self.runtime_env)
            res_key = tuple(sorted(self.resources.items()))
            s = self.scheduling_strategy
            strat_key = (s.kind, s.node_id_hex, s.soft)
            cached = self._sched_key = (res_key, env_key, strat_key)
        return cached


@dataclass
class ActorCreationSpec:
    actor_id: ActorID
    job_id: JobID
    cls_blob: bytes  # cloudpickled class
    args_blob: bytes
    # Registry content address of the class definition (see TaskSpec.fn_id):
    # N actors of one class ship the pickled class once, not once per actor.
    cls_id: str = ""
    arg_ref_ids: list[ObjectID] = field(default_factory=list)
    resources: dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    name: str | None = None  # named-actor registration
    namespace: str = "default"
    lifetime: str = "non_detached"  # or "detached"
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: dict[str, Any] | None = None
    owner_id: WorkerID | None = None
