"""Router hot-path microbench: routing decisions/sec and end-to-end
request throughput through one shared router, on the in-process runtime.

Three closed-loop measurements, cheapest to fullest:

- ``decide``       — the pure routing decision: choose (pow-2 / prefix
  scoring) + in-flight accounting + release, no submission. This is the
  rate the 10k gate applies to (ISSUE: "routing decisions/sec
  single-router"), load-factor-scaled like every timing gate in this
  repo (tests/_test_util.load_factor policy).
- ``assign``       — the full ``assign_request`` path: decision +
  deadline stamping + cached-handle actor submit + completion-reaper
  registration, open loop with periodic drains.
- ``e2e``          — closed-loop clients driving ``handle.remote()``
  → ``result()`` against trivial replicas: what a proxy thread
  actually pays per request.

The decision path is also measured WITH prefix hashes against a
populated prefix map (``decide_prefix``) — KV-block-aware scoring must
not price the hot path out of its gate.

Run: python devbench/router_bench.py [--quick]   → PERF_ROUTER.json
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

from _test_util import load_factor as _load_factor  # noqa: E402 - one
# load-factor policy for every timing gate in the repo (tests and bench
# floors must scale identically or they silently diverge)

NUM_REPLICAS = 4


def _deploy():
    from ray_tpu import serve

    @serve.deployment(name="RouterBenchEcho", num_replicas=NUM_REPLICAS,
                      max_ongoing_requests=1_000_000,
                      max_queued_requests=-1)
    class Echo:
        def __call__(self, x):
            return x

    return serve.run(Echo.bind(), name="router-bench", route_prefix=None)


def _measure_decide(router, reps, seconds: float,
                    prefix_hashes=None) -> float:
    t0 = time.perf_counter()
    n = 0
    deadline = t0 + seconds
    while time.perf_counter() < deadline:
        for _ in range(100):
            with router._lock:
                chosen = router._choose_locked(
                    reps, prefix_hashes=prefix_hashes)
                rid = chosen.replica_id
                router._inflight[rid] = router._inflight.get(rid, 0) + 1
            router._release(rid)
        n += 100
    return n / (time.perf_counter() - t0)


def _measure_assign(router, seconds: float) -> float:
    import ray_tpu

    refs = []
    t0 = time.perf_counter()
    n = 0
    deadline = t0 + seconds
    while time.perf_counter() < deadline:
        ref, _ = router.assign_request("__call__", (n,), {}, timeout=30.0)
        refs.append(ref)
        n += 1
        if len(refs) >= 256:
            # Drain so the replica mailboxes / reaper can't grow unbounded
            # (the drain wait is inside the measured window: an open loop
            # that never settles would be a dishonest rate).
            ray_tpu.wait(refs, num_returns=len(refs), timeout=30)
            refs = []
    took = time.perf_counter() - t0
    if refs:
        ray_tpu.wait(refs, num_returns=len(refs), timeout=30)
    return n / took


def _measure_e2e(handle, clients: int, seconds: float) -> float:
    stop = time.monotonic() + seconds
    counts = [0] * clients

    def client(k):
        while time.monotonic() < stop:
            handle.remote(k).result(timeout=30)
            counts[k] += 1

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(counts) / (time.monotonic() - t0)


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    import ray_tpu
    from ray_tpu.serve import prefix as prefix_mod

    dur = 1.0 if quick else 3.0
    ray_tpu.shutdown()
    ray_tpu.init()
    try:
        from ray_tpu import serve

        handle = _deploy()
        router = handle._ensure_router()
        for i in range(100):  # prime caches, reaper, replica pools
            handle.remote(i).result(timeout=30)

        reps = router._get_replicas()
        decide_rps = _measure_decide(router, reps, dur)

        # Prefix-scored decision: a populated map + request hashes that
        # fully match one replica (the worst non-degenerate case: every
        # request walks the scoring loop).
        shared = list(range(64))
        hashes = prefix_mod.block_hashes(shared, 8)
        now = time.monotonic()
        router._prefix_map = {
            reps[0].replica_id: (frozenset(hashes), now),
            reps[1].replica_id: (frozenset(hashes[:2]), now),
        }
        decide_prefix_rps = _measure_decide(router, reps, dur,
                                            prefix_hashes=hashes)
        router._prefix_map = {}

        assign_rps = _measure_assign(router, dur)
        e2e_rps = _measure_e2e(handle, clients=4, seconds=dur)
        serve.shutdown()
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.shutdown()

    lf = _load_factor()
    gate_floor = 10_000.0 / lf
    report = {
        "bench": "router_hot_path",
        "quick": quick,
        "config": {"num_replicas": NUM_REPLICAS, "duration_s": dur,
                   "e2e_clients": 4},
        "rates": {
            "decide_rps": round(decide_rps, 1),
            "decide_prefix_rps": round(decide_prefix_rps, 1),
            "assign_rps": round(assign_rps, 1),
            "e2e_rps": round(e2e_rps, 1),
        },
        "acceptance": {
            "decide_10k_gate": decide_rps >= gate_floor,
            "gate_floor_rps": round(gate_floor, 1),
            "load_factor": round(lf, 2),
            "prefix_scoring_within_2x_of_plain":
                decide_prefix_rps >= decide_rps / 2.0,
        },
        "provenance": {
            "date": time.strftime("%Y-%m-%d %H:%M:%S"),
            "cpus": os.cpu_count(),
            "loadavg": list(os.getloadavg()),
            "box_note": (
                "in-process runtime on a small CPU box. decide = pure "
                "routing decision (choose + in-flight accounting); assign "
                "adds deadline stamping, cached-handle actor submit, and "
                "reaper registration; e2e is the full handle round trip "
                "against 4 trivial replicas. Pre-fast-path HEAD on the "
                "same box, same day: assign ~2.2k/s (a watcher thread was "
                "created per request), handle e2e ~1.9k/s."),
        },
    }
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PERF_ROUTER.json")
    doc = report
    if quick and os.path.exists(out_path):
        # Namespaced quick refresh: never overwrite full-run provenance.
        try:
            with open(out_path) as f:
                existing = json.load(f)
            if not existing.get("quick"):
                existing["quick_refresh"] = report
                doc = existing
        except Exception:  # noqa: BLE001
            pass
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    return report


if __name__ == "__main__":
    rep = run_bench(quick="--quick" in sys.argv[1:])
    print(json.dumps(rep, indent=2))
