"""Per-block timing on the real chip: flash attention fwd/bwd and MLP
fwd/bwd at the bench geometry, vs the measured matmul ceiling (~152 TF/s).

Answers: where do the ~140 ms of backward overhead in the 1B step go?
(profile_step.py: fwd-only 137 ms, fwd+bwd dots 476 ms, ideal bwd 2x fwd.)

Protocol notes (axon tunnel):
- Per-jit dispatch+fetch costs ~70-100 ms, so each op is chained inside one
  jit via lax.scan and the per-iter time is the SLOPE between a short and a
  long chain (cancels the fixed cost).
- Backward passes pull a RANDOM cotangent through vjp — a sum() loss hands
  XLA an all-ones cotangent it can simplify (matmul-by-ones becomes a
  reduction), undercounting real backward cost.
"""
import time

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import flash_attention

B, S, H, KV, HD = 4, 2048, 32, 8, 64
HID, INTER = 2048, 8192
LAYERS = 16
L1, L2 = 16, 112


def timed_slope_chain(make_step, carry0, reps=5):
    """Per-iteration time of make_step via two chain lengths in one jit."""

    def run_for(length):
        @jax.jit
        def run(c):
            def body(c, _):
                return make_step(c), None
            c, _ = lax.scan(body, c, None, length=length)
            return jax.tree_util.tree_reduce(
                lambda a, x: a + x.ravel()[0].astype(jnp.float32), c, 0.0)
        return run

    r1, r2 = run_for(L1), run_for(L2)
    float(r1(carry0)); float(r2(carry0))  # compile both
    slopes = []
    for _ in range(reps):
        t0 = time.perf_counter(); float(r1(carry0)); t1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(r2(carry0)); t2 = time.perf_counter() - t0
        slopes.append((t2 - t1) / (L2 - L1))
    slopes.sort()
    return slopes[len(slopes) // 2]


key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (B, H, S, HD), jnp.bfloat16)
k = jax.random.normal(key, (B, KV, S, HD), jnp.bfloat16)
v = jax.random.normal(key, (B, KV, S, HD), jnp.bfloat16)
cot_o = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, HD), jnp.bfloat16)


def attn_fwd_step(c):
    qq, kk, vv = c
    o = flash_attention(qq, kk, vv, causal=True)
    return (qq + 1e-30 * o, kk, vv)


def attn_bwd_step(c):
    qq, kk, vv = c
    _, vjp = jax.vjp(lambda a, b, d: flash_attention(a, b, d, causal=True),
                     qq, kk, vv)
    dq, dk, dv = vjp(cot_o)
    return (qq + 1e-30 * dq, kk + 1e-30 * dk, vv + 1e-30 * dv)


t_fwd = timed_slope_chain(attn_fwd_step, (q, k, v))
t_bwd = timed_slope_chain(attn_bwd_step, (q, k, v))
fl = 2 * 2 * B * H * S * S * HD / 2  # causal
print(f"attn fwd      : {t_fwd*1e3:7.2f} ms  {fl/t_fwd/1e12:6.1f} TF/s "
      f"(x{LAYERS} layers = {t_fwd*LAYERS*1e3:.0f} ms)", flush=True)
print(f"attn bwd(+fwd): {t_bwd*1e3:7.2f} ms  {3.5*fl/t_bwd/1e12:6.1f} TF/s "
      f"(x{LAYERS} = {t_bwd*LAYERS*1e3:.0f} ms)", flush=True)

wg = jax.random.normal(key, (HID, INTER), jnp.bfloat16) * 0.02
wu = jax.random.normal(key, (HID, INTER), jnp.bfloat16) * 0.02
wd = jax.random.normal(key, (INTER, HID), jnp.bfloat16) * 0.02
x = jax.random.normal(key, (B * S, HID), jnp.bfloat16)
cot_x = jax.random.normal(jax.random.PRNGKey(2), (B * S, HID), jnp.bfloat16)


def mlp(xx, g, u, d):
    return (jax.nn.silu(xx @ g) * (xx @ u)) @ d


def mlp_fwd_step(c):
    xx, g, u, d = c
    o = mlp(xx, g, u, d)
    return (xx + 1e-30 * o, g, u, d)


def mlp_bwd_step(c):
    xx, g, u, d = c
    _, vjp = jax.vjp(mlp, xx, g, u, d)
    dx, dg, du, dd = vjp(cot_x)
    return (xx + 1e-30 * dx, g + 1e-30 * dg, u + 1e-30 * du, d + 1e-30 * dd)


t_mf = timed_slope_chain(mlp_fwd_step, (x, wg, wu, wd))
t_mb = timed_slope_chain(mlp_bwd_step, (x, wg, wu, wd))
mfl = 2 * 3 * B * S * HID * INTER
print(f"mlp fwd       : {t_mf*1e3:7.2f} ms  {mfl/t_mf/1e12:6.1f} TF/s "
      f"(x{LAYERS} = {t_mf*LAYERS*1e3:.0f} ms)", flush=True)
print(f"mlp bwd(+fwd) : {t_mb*1e3:7.2f} ms  {3*mfl/t_mb/1e12:6.1f} TF/s "
      f"(x{LAYERS} = {t_mb*LAYERS*1e3:.0f} ms)", flush=True)

wq = jax.random.normal(key, (HID, HID), jnp.bfloat16) * 0.02


def qo_step(c):
    xx, w = c
    o = xx @ w
    return (xx + 1e-30 * o, w)


t_qf = timed_slope_chain(qo_step, (x, wq))
qfl = 2 * B * S * HID * HID
print(f"qo proj       : {t_qf*1e3:7.2f} ms  {qfl/t_qf/1e12:6.1f} TF/s",
      flush=True)
