"""Application metrics API: Counter / Gauge / Histogram with tags.

Capability parity with the reference's metrics API (reference:
python/ray/util/metrics.py Counter/Gauge/Histogram over the C++ OpenCensus
recorder, src/ray/stats/metric.h): processes record metrics locally; the
dashboard scrapes/aggregates them in Prometheus text exposition format.

TPU-native note: no OpenCensus/OTel dependency — a lock-protected in-process
registry with Prometheus text export keeps the hot path to a dict update, and
the export shape identical to what the reference's metrics agent serves.

Cluster federation (reference: the metrics agent pushing to the dashboard's
aggregator): every process can ``snapshot()`` its registry into a
wire-serializable dict; the head collects snapshots per node and the
dashboard renders them with ``export_prometheus_federated`` — one endpoint,
every series labeled with its ``node_id``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Sequence

from ray_tpu.devtools.annotations import guarded_by

_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0,
)

_exemplar_n: int | None = None


def _exemplar_count() -> int:
    """Exemplars kept per histogram series (Config metrics_exemplar_count),
    cached once — read lazily so the module imports without a runtime."""
    global _exemplar_n
    if _exemplar_n is None:
        try:
            from ray_tpu.utils.config import get_config

            _exemplar_n = max(0, int(get_config().metrics_exemplar_count))
        except Exception:  # noqa: BLE001 - config not importable yet
            _exemplar_n = 4
    return _exemplar_n


@guarded_by("_lock", "_series")
class Metric:
    """Base: a named measurement with fixed tag keys and per-tagset series."""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] | None = None):
        if not name or not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: dict[str, str] = {}
        self._lock = threading.Lock()
        self._series: dict[tuple, float] = {}
        _registry.register(self)

    def set_default_tags(self, tags: dict[str, str]):
        unknown = set(tags) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"tags {unknown} not in declared tag_keys {self.tag_keys}")
        self._default_tags = dict(tags)
        return self

    def _series_key(self, tags: dict[str, str] | None) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            unknown = set(tags) - set(self.tag_keys)
            if unknown:
                raise ValueError(
                    f"tags {unknown} not in declared tag_keys {self.tag_keys}")
            merged.update(tags)
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def _points(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._series)


class _BoundSeries:
    """One pre-resolved series of a metric: the tag dict was merged and
    validated ONCE at bind time, so hot-path updates skip the per-call
    merge/validate/tuple-build of ``_series_key`` (measured as the
    dominant cost of a Counter.inc at router request rates). Exported
    state is identical — a bound update writes the same series the tagged
    call would."""

    __slots__ = ("_m", "_key")

    def __init__(self, metric: "Metric", key: tuple):
        self._m = metric
        self._key = key


class _BoundCounter(_BoundSeries):
    def inc(self, value: float = 1.0):
        self._m._inc_key(self._key, value)


class _BoundGauge(_BoundSeries):
    def set(self, value: float):
        self._m._set_key(self._key, value)


class _BoundHistogram(_BoundSeries):
    def observe(self, value: float, exemplar: str | None = None):
        self._m._observe_key(self._key, value, exemplar)


class Counter(Metric):
    """Monotonically increasing count."""

    def inc(self, value: float = 1.0, tags: dict[str, str] | None = None):
        self._inc_key(self._series_key(tags), value)

    def _inc_key(self, key: tuple, value: float):
        # Validated here so the bound fast path keeps the monotonicity
        # guarantee too — bound and tagged updates must behave alike.
        if value < 0:
            raise ValueError("Counter.inc() value must be >= 0")
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def bound(self, tags: dict[str, str] | None = None) -> _BoundCounter:
        return _BoundCounter(self, self._series_key(tags))

    prom_type = "counter"


class Gauge(Metric):
    """Last-set value."""

    def set(self, value: float, tags: dict[str, str] | None = None):
        self._set_key(self._series_key(tags), value)

    def _set_key(self, key: tuple, value: float):
        with self._lock:
            self._series[key] = float(value)

    def bound(self, tags: dict[str, str] | None = None) -> _BoundGauge:
        return _BoundGauge(self, self._series_key(tags))

    prom_type = "gauge"


@guarded_by("_lock", "_buckets", "_sums", "_series")
class Histogram(Metric):
    """Bucketed distribution (cumulative buckets, Prometheus-style)."""

    prom_type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] | None = None,
                 tag_keys: Sequence[str] | None = None):
        super().__init__(name, description, tag_keys)
        bounds = tuple(boundaries) if boundaries else _DEFAULT_BUCKETS
        if list(bounds) != sorted(bounds):
            raise ValueError("histogram boundaries must be sorted ascending")
        self.boundaries = bounds
        self._buckets: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        # Recent (trace_id, value, ts) per series — the metrics→traces
        # link: a TTFT bucket names the traces that landed in it.
        self._exemplars: dict[tuple, deque] = {}

    def observe(self, value: float, tags: dict[str, str] | None = None,
                exemplar: str | None = None):
        self._observe_key(self._series_key(tags), value, exemplar)

    def _observe_key(self, key: tuple, value: float,
                     exemplar: str | None = None):
        with self._lock:
            buckets = self._buckets.setdefault(key, [0] * (len(self.boundaries) + 1))
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            buckets[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._series[key] = self._series.get(key, 0.0) + 1  # observation count
            if exemplar:
                n = _exemplar_count()
                if n:
                    ring = self._exemplars.get(key)
                    if ring is None:
                        ring = self._exemplars[key] = deque(maxlen=n)
                    ring.append((exemplar, float(value), time.time()))

    def bound(self, tags: dict[str, str] | None = None) -> _BoundHistogram:
        return _BoundHistogram(self, self._series_key(tags))

    def _hist_points(self):
        with self._lock:
            return (
                {k: list(v) for k, v in self._buckets.items()},
                dict(self._sums),
                dict(self._series),
                {k: [list(e) for e in v]
                 for k, v in self._exemplars.items() if v},
            )


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}

    def register(self, metric: Metric):
        with self._lock:
            self._metrics[metric.name] = metric

    def metrics(self) -> list[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def clear(self):
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> dict:
        """Wire-serializable copy of every registered metric's state, the
        unit the telemetry pipeline ships to the head (reference: the
        OpenCensus snapshots the metrics agent exports). Series keys become
        lists so the dict survives msgpack/JSON round-trips."""
        entries = []
        for m in self.metrics():
            entry = {
                "name": m.name, "type": m.prom_type,
                "desc": m.description, "tag_keys": list(m.tag_keys),
            }
            if isinstance(m, Histogram):
                buckets, sums, counts, exemplars = m._hist_points()
                entry["boundaries"] = [float(b) for b in m.boundaries]
                entry["buckets"] = [[list(k), list(v)]
                                    for k, v in buckets.items()]
                entry["sums"] = [[list(k), v] for k, v in sums.items()]
                entry["counts"] = [[list(k), v] for k, v in counts.items()]
                if exemplars:
                    # JSON surfaces only (/api/metrics, /api/traces, the
                    # watchdog) — the Prometheus text exposition is
                    # deliberately untouched.
                    entry["exemplars"] = [[list(k), v]
                                          for k, v in exemplars.items()]
            else:
                entry["points"] = [[list(k), v]
                                   for k, v in m._points().items()]
            entries.append(entry)
        return {"metrics": entries}

    def export_prometheus(self) -> str:
        """Prometheus text exposition of every registered metric."""
        lines: list[str] = []
        for entry in self.snapshot()["metrics"]:
            lines.append(f"# HELP {entry['name']} {entry['desc']}")
            lines.append(f"# TYPE {entry['name']} {entry['type']}")
            lines.extend(_render_entry(entry))
        return "\n".join(lines) + "\n"


def merge_snapshots(snapshots: list[dict]) -> dict:
    """Merge per-process snapshots into one (several workers on one node
    report under the same node_id): counters and histograms sum, gauges
    keep the last reporter's value. Histogram merges require identical
    boundaries; a mismatched reporter's entry is kept as-is from the first."""
    merged: dict[str, dict] = {}
    for snap in snapshots:
        for entry in snap.get("metrics", []):
            have = merged.get(entry["name"])
            if have is None:
                import copy

                merged[entry["name"]] = copy.deepcopy(entry)
                continue
            if entry["type"] == "histogram":
                if have.get("boundaries") != entry.get("boundaries"):
                    continue
                for field, combine in (("buckets", "vec"), ("sums", "num"),
                                       ("counts", "num")):
                    idx = {tuple(k): v for k, v in have.get(field, [])}
                    for k, v in entry.get(field, []):
                        k = tuple(k)
                        if k not in idx:
                            idx[k] = v
                        elif combine == "vec":
                            idx[k] = [a + b for a, b in zip(idx[k], v)]
                        else:
                            idx[k] = idx[k] + v
                    have[field] = [[list(k), v] for k, v in idx.items()]
                if entry.get("exemplars"):
                    # Concat per series, keep the newest N by timestamp —
                    # same bound as one process's ring.
                    n = _exemplar_count() or 4
                    idx = {tuple(k): list(v)
                           for k, v in have.get("exemplars", [])}
                    for k, v in entry["exemplars"]:
                        k = tuple(k)
                        rows = idx.get(k, []) + list(v)
                        rows.sort(key=lambda e: e[2] if len(e) > 2 else 0.0)
                        idx[k] = rows[-n:]
                    have["exemplars"] = [[list(k), v]
                                         for k, v in idx.items()]
            else:
                idx = {tuple(k): v for k, v in have.get("points", [])}
                for k, v in entry.get("points", []):
                    k = tuple(k)
                    if entry["type"] == "counter":
                        idx[k] = idx.get(k, 0.0) + v
                    else:  # gauge: last reporter wins
                        idx[k] = v
                have["points"] = [[list(k), v] for k, v in idx.items()]
    return {"metrics": list(merged.values())}


def export_prometheus_federated(per_node: dict[str, dict]) -> str:
    """Cluster-wide Prometheus text exposition: every node's snapshot with a
    ``node_id`` label on each series, HELP/TYPE emitted once per metric name
    (reference: the dashboard's federated /metrics over per-node agents)."""
    by_name: dict[str, list[tuple[str, dict]]] = {}
    for node_id, snap in per_node.items():
        for entry in snap.get("metrics", []):
            by_name.setdefault(entry["name"], []).append((node_id, entry))
    lines: list[str] = []
    for name, rows in by_name.items():
        lines.append(f"# HELP {name} {rows[0][1]['desc']}")
        lines.append(f"# TYPE {name} {rows[0][1]['type']}")
        for node_id, entry in rows:
            lines.extend(_render_entry(entry, extra=[("node_id", node_id)]))
    return "\n".join(lines) + "\n"


def _render_entry(entry: dict, extra: list[tuple] | None = None) -> list[str]:
    """Exposition lines for one snapshot entry (shared by the local and
    federated exporters so the two can never drift)."""
    name, keys = entry["name"], tuple(entry["tag_keys"])
    lines: list[str] = []
    if entry["type"] == "histogram":
        bounds = entry["boundaries"]
        sums = {tuple(k): v for k, v in entry.get("sums", [])}
        counts = {tuple(k): v for k, v in entry.get("counts", [])}
        for key, bk in entry.get("buckets", []):
            key = tuple(key)
            base = _labels(keys, key, extra)
            cum = 0
            for bound, n in zip(bounds, bk):
                cum += n
                le = (extra or []) + [("le", _fmt_float(bound))]
                lines.append(f"{name}_bucket{_labels(keys, key, le)} {cum}")
            cum += bk[-1]
            inf = (extra or []) + [("le", "+Inf")]
            lines.append(f"{name}_bucket{_labels(keys, key, inf)} {cum}")
            lines.append(f"{name}_sum{base} {sums.get(key, 0.0)}")
            lines.append(f"{name}_count{base} {int(counts.get(key, 0))}")
    else:
        for key, v in entry.get("points", []):
            lines.append(f"{name}{_labels(keys, tuple(key), extra)} {v}")
    return lines


def _fmt_float(v: float) -> str:
    """Canonical float formatting for exposition values (`le` bounds):
    always the shortest repr of the *float*, so integer boundaries render
    identically to their float equivalents (5 -> "5.0", matching 5.0)."""
    return repr(float(v))


def _escape_label(value: str) -> str:
    """The one escaping/validation point for every label value — tag values
    and synthetic pairs (le, node_id) all pass through here."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(keys: tuple, values: tuple,
            extra: list[tuple] | None = None) -> str:
    pairs = [(k, v) for k, v in zip(keys, values) if v != ""]
    pairs.extend(extra or ())
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + inner + "}"


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry
