"""Behavior cloning: offline RL from a dataset of (obs, action) pairs.

Capability parity with the reference's offline-RL entry point (reference:
rllib/algorithms/bc/bc.py — BC trains the policy head by supervised
action log-likelihood over an offline dataset read through ray.data;
offline/offline_data.py streams the dataset into learner batches). Here the
dataset is a ray_tpu.data Dataset with "obs" and "actions" columns, batches
stream through iter_batches, and the update is a jitted cross-entropy step
on the same MLP policy PPO uses — so a BC-pretrained policy drops straight
into PPO fine-tuning.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.env import make_env
from ray_tpu.rl.ppo import init_mlp, mlp_apply
from ray_tpu.tune.trainable import Trainable


@partial(jax.jit, static_argnums=(0,))
def bc_update(optimizer, params, opt_state, obs, actions):
    def loss_fn(p):
        logits = mlp_apply(p, obs)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, actions[:, None], 1)[:, 0]
        acc = (logits.argmax(-1) == actions).mean()
        return nll.mean(), acc

    (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss, acc


@dataclass
class BCConfig:
    env: str = "CartPole-v1"           # for obs/action spaces + evaluation
    dataset: Any = None                # ray_tpu.data Dataset ("obs","actions")
    lr: float = 1e-3
    batch_size: int = 256
    epochs_per_step: int = 1
    hidden: int = 64
    evaluation_episodes: int = 0       # >0: greedy rollouts each step()
    seed: int = 0
    extra: dict = field(default_factory=dict)

    def build(self) -> "BC":
        return BC({"bc_config": self})


class BC(Trainable):
    """Supervised policy training over an offline dataset (reference:
    bc.py training_step: offline batch → log-likelihood update)."""

    def setup(self, config: dict) -> None:
        cfg = config.get("bc_config") or BCConfig(
            **{k: v for k, v in config.items()
               if k in BCConfig.__dataclass_fields__})
        if cfg.dataset is None:
            raise ValueError("BCConfig.dataset is required (offline data)")
        self.cfg = cfg
        probe = make_env(cfg.env, seed=cfg.seed)
        self.params = init_mlp(
            jax.random.PRNGKey(cfg.seed),
            [probe.observation_size, cfg.hidden, cfg.hidden,
             probe.num_actions])
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)

    def step(self) -> dict:
        cfg = self.cfg
        loss_sum = acc_sum = 0.0
        seen = 0
        for _ in range(cfg.epochs_per_step):
            for batch in cfg.dataset.iter_batches(
                    batch_size=cfg.batch_size,
                    local_shuffle_buffer_size=4 * cfg.batch_size,
                    local_shuffle_seed=cfg.seed + self.iteration):
                obs = jnp.asarray(np.asarray(batch["obs"], np.float32))
                act = jnp.asarray(np.asarray(batch["actions"], np.int32))
                self.params, self.opt_state, loss_j, acc_j = bc_update(
                    self.optimizer, self.params, self.opt_state, obs, act)
                n = len(act)
                loss_sum += float(loss_j) * n
                acc_sum += float(acc_j) * n
                seen += n
        denom = max(seen, 1)
        out = {"bc_loss": loss_sum / denom,
               "action_accuracy": acc_sum / denom,
               "num_samples_trained": seen}
        if cfg.evaluation_episodes > 0:
            out["episode_return_mean"] = self._evaluate(
                cfg.evaluation_episodes)
        return out

    def _evaluate(self, episodes: int) -> float:
        """Greedy policy rollouts (reference: evaluation_config rollouts)."""
        returns = []
        env = make_env(self.cfg.env, seed=self.cfg.seed + 10_000)
        for _ in range(episodes):
            obs = env.reset()
            total, done, steps = 0.0, False, 0
            while not done and steps < 1000:
                a = int(np.asarray(
                    mlp_apply(self.params, jnp.asarray(obs[None]))
                ).argmax(-1)[0])
                obs, r, term, trunc = env.step(a)
                done = term or trunc
                total += r
                steps += 1
            returns.append(total)
        return float(np.mean(returns))

    def save_checkpoint(self) -> Any:
        return {"params": jax.tree.map(np.asarray, self.params),
                "iteration": self.iteration}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, checkpoint["params"])
        self.iteration = checkpoint["iteration"]
