"""Ring attention: exact attention over sequences sharded across the ``sp``
mesh axis, with K/V blocks rotating around the ICI ring via ``lax.ppermute``.

New work relative to the reference framework (reference: SURVEY.md §5 — Ray
has no sequence/context parallelism anywhere; its role stops at process-group
bring-up). Here long context is first-class: each device holds Sq/N of the
sequence; at every ring step it attends its local Q against the visiting K/V
chunk with online-softmax accumulation, then passes the chunk to its ICI
neighbor. Compute/communication overlap is XLA's job (the ppermute is
independent of the attention einsum in each step, so the scheduler pipelines
them).

Causality across chunks: positions are global (chunk_index · chunk_len +
local offset); a visiting chunk strictly in the future is fully masked and
contributes nothing (the online update with all-masked logits is a no-op).

Usage: inside ``shard_map`` over a mesh with an ``sp`` axis, with q/k/v
sharded on their sequence dim. ``ring_attention_sharded`` builds that
shard_map for a global array.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import NEG_INF, _repeat_kv


def _ring_step_combine(q, k, v, o, m, l, scale, causal, q_offset, kv_offset,
                       kv_block):
    """One online-softmax accumulation of local q against a visiting kv chunk."""
    b, h, sq, d = q.shape
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qpos = jnp.arange(sq)[:, None] + q_offset
        kpos = jnp.arange(k.shape[2])[None, :] + kv_offset
        s = jnp.where(kpos <= qpos, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard: fully-masked rows keep m at NEG_INF; exp underflows to 0 — fine.
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return o_new, m_new, l_new


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         sm_scale: float | None = None,
                         impl: str = "auto"):
    """Per-shard body (call inside shard_map). q/k/v: local [B, H, S/N, D].

    ``impl``: "flash" runs each ring step through the Pallas chunk kernel
    (ops/attention.py flash_attention_chunk — data-driven causal positions,
    differentiable lse) and combines chunks by (out, lse) log-sum-exp;
    "einsum" is the materialized-score XLA path; "auto" picks flash on TPU.
    """
    b, h, sq, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if hasattr(lax, "axis_size"):
        n = lax.axis_size(axis_name)
    else:  # jax < 0.6 spelling: psum of a literal constant-folds to the size
        n = int(lax.psum(1, axis_name))
    my = lax.axis_index(axis_name)
    chunk = sq
    perm = [(i, (i + 1) % n) for i in range(n)]

    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "einsum"

    if impl == "flash":
        from ray_tpu.ops.attention import flash_attention_chunk

        qpos = my * chunk + jnp.arange(sq, dtype=jnp.int32)

        def stepf(t, carry):
            o, lse_acc, kc, vc = carry
            src = (my - t) % n
            kpos = src * chunk + jnp.arange(chunk, dtype=jnp.int32)
            o_t, lse_t = flash_attention_chunk(q, kc, vc, qpos, kpos,
                                               causal, scale)
            # log-sum-exp combine of normalized per-chunk results; a fully
            # masked chunk arrives with lse ~ -inf and weight 0.
            lse_new = jnp.logaddexp(lse_acc, lse_t)
            w_old = jnp.exp(lse_acc - lse_new)[..., None]
            w_new = jnp.exp(lse_t - lse_new)[..., None]
            o = o * w_old + o_t.astype(jnp.float32) * w_new
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            return o, lse_new, kc, vc

        o0 = jnp.zeros((b, h, sq, d), jnp.float32)
        lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
        o, _, _, _ = lax.fori_loop(0, n, stepf, (o0, lse0, k, v))
        return o.astype(q.dtype)

    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)

    # Ring: at step t, this device holds the chunk originally owned by
    # (my - t) mod n; chunks travel to the next-higher index each step.

    def step(t, carry):
        o, m, l, kc, vc = carry
        src = (my - t) % n  # owner of the visiting chunk
        o, m, l = _ring_step_combine(
            q, kc, vc, o, m, l, scale, causal,
            q_offset=my * chunk, kv_offset=src * chunk, kv_block=chunk,
        )
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o, m, l, kc, vc

    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis: str = "sp",
                           causal: bool = True,
                           sm_scale: float | None = None,
                           batch_axes=None, impl: str = "auto"):
    """Global-array entry: shard seq dim over ``axis``, run the ring.

    ``batch_axes``: optional mesh axes to shard the batch dim over (e.g.
    ("dp", "fsdp") in a combined dp×sp mesh)."""
    spec = P(batch_axes, None, axis, None)
    fn = shard_map_ring(mesh, axis, causal, sm_scale, spec, impl)
    return fn(q, k, v)


@functools.lru_cache(maxsize=64)
def shard_map_ring(mesh: Mesh, axis: str, causal: bool, sm_scale, spec: P,
                   impl: str = "auto"):
    body = functools.partial(ring_attention_local, axis_name=axis,
                             causal=causal, sm_scale=sm_scale, impl=impl)

    # compat shim: jax >= 0.6 jax.shard_map / older experimental check_rep
    from ray_tpu.collective.xla_backend import shard_map

    @jax.jit
    def fn(q, k, v):
        return shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return fn
