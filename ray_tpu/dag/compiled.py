"""Compiled DAG execution: static per-actor schedules over channels.

Capability parity with the reference's Compiled Graphs (reference:
python/ray/dag/compiled_dag_node.py:805 CompiledDAG — _get_or_compile :1550
allocates channels between actors; _build_execution_schedule :2002 emits a
static per-actor op list (READ → COMPUTE → WRITE per node,
dag_node_operation.py:14-24) run in a loop on each actor, replacing per-call
RPC with channel reads/writes).

Compilation here: walk the graph, allocate one channel per produced value
(readers = consuming actors and/or the driver), install a loop in every
participating actor via the ``__rtpu_call_fn__`` hook, and drive executions
by writing the input channel and reading the terminal channels. In cluster
mode channels default to the direct peer-to-peer transport
(ray_tpu/dag/direct.py): the head KV is consulted once at compile time for
route exchange, then every step's dataflow moves actor-to-actor with zero
control-plane RPCs (``dag_channel="kv"`` selects the head-KV fallback).

Execution is pipelined: ``execute_async()`` admits up to
``dag_max_inflight`` invocations into the stage pipeline (backpressure
blocks the submitter beyond that; per-hop channel capacity bounds each
edge), and a completion thread retires them in FIFO order. ``execute()`` is
the synchronous single-result wrapper. The first failure — an in-actor
exception surfaced on the actor's error channel, or the real
``ActorDiedError`` of a killed stage harvested from its loop ref — fails
every in-flight execution and is cached: all subsequent executes re-raise
it instead of timing out on a dead pipeline. Teardown closes the input
channel, drains in-flight values so ack-gated writers can unwind, and
force-closes every channel.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from ray_tpu.dag.channel import ChannelClosed, LocalChannel, StoreChannel
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.util import tracing

_DRIVER = "__driver__"


def _overlap_plan(ops: list[dict]) -> list[tuple[int, int]]:
    """The overlapped-execution schedule pass (reference:
    compiled_dag_node.py:2042 _generate_overlapped_execution_schedule —
    reorders communication ops ahead of compute so transfers run while
    earlier ops compute).

    Returns the channel reads (op_index, arg_position) that are SAFE to
    post at schedule start: those with NO intra-schedule producer (an
    earlier op of THIS actor writing the same channel). Dependent reads
    stay inline in the loop — posting them to a bounded transfer pool
    could starve a read the loop's own progress needs (FIFO worker
    assignment deadlock), while start-posted reads only wait on OTHER
    actors, whose progress this actor's compute never gates through the
    transfer pool."""
    start_posts: list[tuple[int, int]] = []
    for i, op in enumerate(ops):
        for pos, (kind, chan, _idx) in enumerate(op["reads"]):
            if kind != "chan":
                continue
            if not any(ops[k]["write"] is chan for k in range(i)):
                start_posts.append((i, pos))
    return start_posts


def _actor_loop(instance, ops: list[dict], error_channel,
                overlap: bool = False):
    """Installed into each participating actor: runs its static schedule
    until the upstream channels close (reference: the per-actor loop a
    compiled DAG executes instead of per-call RPC). With ``overlap``, the
    _overlap_plan pass posts channel reads early on a transfer thread so
    inbound byte movement runs concurrently with compute."""
    from ray_tpu.core.worker import global_worker

    rt = global_worker.runtime
    for op in ops:
        for kind, chan, ridx in op["reads"]:
            if kind == "chan":
                chan.connect(rt)
                # Direct channels: attach + publish the route BEFORE any
                # writer resolves it (the one compile-time KV write).
                if hasattr(chan, "ensure_reader"):
                    chan.ensure_reader(ridx)
        if op["write"] is not None:
            op["write"].connect(rt)
    error_channel.connect(rt)

    posts = _overlap_plan(ops) if overlap else None
    executor = None
    if overlap:
        from concurrent.futures import ThreadPoolExecutor

        # One worker per posted read: every posted read gets a thread, so
        # no read the loop waits on can be starved behind another blocked
        # read (posted reads block only on OTHER actors' progress).
        executor = ThreadPoolExecutor(max_workers=max(1, len(posts)),
                                      thread_name_prefix="dag-xfer")

    def cascade_close():
        # This loop is the writer of its output channels: closing them here
        # (with this process's write cursor) unwinds downstream loops in turn.
        for op in ops:
            if op["write"] is not None:
                try:
                    op["write"].close()
                except BaseException:
                    pass
        if executor is not None:
            executor.shutdown(wait=False)

    futs: dict[tuple[int, int], Any] = {}

    def post_all() -> None:
        for (i, pos) in posts:
            kind, chan, reader_idx = ops[i]["reads"][pos]
            futs[(i, pos)] = executor.submit(chan.read, reader_idx)

    while True:
        try:
            if overlap:
                post_all()
            for i, op in enumerate(ops):
                args = []
                for pos, (kind, chan_or_val, reader_idx) in \
                        enumerate(op["reads"]):
                    if kind != "chan":
                        args.append(chan_or_val)
                    elif overlap and (i, pos) in futs:
                        args.append(futs.pop((i, pos)).result())
                    else:
                        args.append(chan_or_val.read(reader_idx))
                kwargs = {k: v for k, v in op["const_kwargs"].items()}
                result = getattr(instance, op["method"])(*args, **kwargs)
                if op["write"] is not None:
                    op["write"].write(result)
        except ChannelClosed:
            cascade_close()
            return "closed"
        except BaseException as e:  # noqa: BLE001
            # Surface the failure to the driver, then stop this loop — the
            # schedule is static; a failed step poisons the whole execution.
            try:
                error_channel.write(("error", repr(e)))
            except BaseException:
                pass
            cascade_close()
            return f"error: {e!r}"


class _DagFailure(Exception):
    """Internal: carries the root-cause exception to the completion loop."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


class CompiledDAG:
    def __init__(self, root: DAGNode, *, _overlap_execution: bool = False,
                 _device_channels: bool = False,
                 _channel_kind: str | None = None,
                 _max_inflight: int | None = None,
                 _channel_capacity: int | None = None):
        """``_overlap_execution`` turns on the overlapped schedule pass
        (reference: compiled_dag_node.py:2042) — channel reads post early
        on a transfer thread so inbound bytes move while earlier ops
        compute. ``_device_channels`` wraps every channel in DeviceChannel
        so jax arrays land on the reader's device (reference: the
        accelerator channel registered via accelerator_context.py:222).
        ``_channel_kind`` overrides the ``dag_channel`` knob ("direct" |
        "kv"; local mode always uses in-process queues); ``_max_inflight``
        and ``_channel_capacity`` override the ``dag_max_inflight`` /
        ``dag_channel_capacity`` knobs."""
        import ray_tpu
        from ray_tpu.core.worker import global_worker
        from ray_tpu.utils.config import get_config

        import uuid

        ray_tpu.init(ignore_reinit_error=True)
        cfg = get_config()
        self._root = root
        self._rt = global_worker.runtime
        self._local = global_worker.mode == "local"
        self._overlap = _overlap_execution
        self._device_channels = _device_channels
        self._channel_kind = _channel_kind or cfg.dag_channel
        self._max_inflight = max(1, _max_inflight or cfg.dag_max_inflight)
        self._channel_capacity = _channel_capacity
        self._torn_down = False
        self._dag_id = uuid.uuid4().hex[:12]  # globally unique channel prefix
        # Pipelined-execution state: a bounded admission window, the FIFO
        # of in-flight futures, and the sticky first failure.
        self._window = threading.BoundedSemaphore(self._max_inflight)
        self._pending: deque = deque()
        self._submit_lock = threading.Lock()
        self._completer: threading.Thread | None = None
        self._completer_lock = threading.Lock()
        self._completer_stop = threading.Event()
        self._work = threading.Event()
        self._error: BaseException | None = None
        self._error_msg: str | None = None
        self._compile()

    # ------------------------------------------------------------------ compile
    def _make_channel(self, name: str, num_readers: int):
        if self._local:
            chan = LocalChannel(name, num_readers,
                                maxsize=self._channel_capacity)
        elif self._channel_kind == "kv":
            chan = StoreChannel(name, num_readers)
        else:
            from ray_tpu.dag.direct import DirectChannel

            chan = DirectChannel(name, num_readers,
                                 capacity=self._channel_capacity)
        if self._device_channels:
            from ray_tpu.dag.communicator import (
                get_accelerator_communicator,
            )

            chan = get_accelerator_communicator("jax_device").wrap_channel(
                chan)
        return chan

    def _compile(self):
        nodes = self._root.walk()
        self._input_node = next(
            (n for n in nodes if isinstance(n, InputNode)), None)
        if self._input_node is None:
            raise ValueError(
                "compiled DAGs require an InputNode (teardown propagates by "
                "closing the input channel)")
        terminal = self._root

        if isinstance(terminal, InputNode):
            raise ValueError("DAG must contain at least one actor-method node")

        # Pass A: count read sites per producer. Every consuming arg-use gets
        # its OWN reader slot — one actor reading a value in two ops is two
        # readers (each slot queues/deletes independently; sharing a slot
        # would lose one of the reads).
        reader_counts: dict[int, int] = {}

        def count_edges(node: DAGNode):
            if isinstance(node, ClassMethodNode):
                for arg in node.args:
                    if isinstance(arg, DAGNode):
                        reader_counts[arg.node_id] = (
                            reader_counts.get(arg.node_id, 0) + 1)
            elif isinstance(node, MultiOutputNode):
                for up in node.outputs:
                    reader_counts[up.node_id] = (
                        reader_counts.get(up.node_id, 0) + 1)

        for node in nodes:
            count_edges(node)
        if isinstance(terminal, ClassMethodNode):
            reader_counts[terminal.node_id] = (
                reader_counts.get(terminal.node_id, 0) + 1)

        self._channels: dict[int, Any] = {}
        for node in nodes:
            n = reader_counts.get(node.node_id, 0)
            if n:
                self._channels[node.node_id] = self._make_channel(
                    f"dag{self._dag_id}/n{node.node_id}", n)

        # Pass B: build schedules, assigning reader indices in the SAME node
        # order as pass A so every read site gets a unique slot.
        next_reader: dict[int, int] = {}

        def claim(producer_id: int) -> int:
            idx = next_reader.get(producer_id, 0)
            next_reader[producer_id] = idx + 1
            return idx

        schedules: dict[str, list[dict]] = {}
        self._handles: dict[str, Any] = {}
        self._output_plan = []
        self._multi_output = isinstance(terminal, MultiOutputNode)
        for node in nodes:
            if isinstance(node, ClassMethodNode):
                key = node.handle.actor_id.hex()
                self._handles[key] = node.handle
                reads = []
                for arg in node.args:
                    if isinstance(arg, DAGNode):
                        reads.append(("chan", self._channels[arg.node_id],
                                      claim(arg.node_id)))
                    else:
                        reads.append(("const", arg, -1))
                const_kwargs = {}
                for k, v in node.kwargs.items():
                    if isinstance(v, DAGNode):
                        raise ValueError(
                            "DAG deps must be positional args in compiled graphs")
                    const_kwargs[k] = v
                schedules.setdefault(key, []).append({
                    "node_id": node.node_id,
                    "method": node.method_name,
                    "reads": reads,
                    "const_kwargs": const_kwargs,
                    "write": self._channels.get(node.node_id),
                    "rank": getattr(node, "schedule_rank", None),
                })
            elif isinstance(node, MultiOutputNode):
                for up in node.outputs:
                    self._output_plan.append(
                        (self._channels[up.node_id], claim(up.node_id)))
        if isinstance(terminal, ClassMethodNode):
            self._output_plan.append(
                (self._channels[terminal.node_id], claim(terminal.node_id)))

        # Per-actor op ORDER defaults to the topological walk order, which
        # serializes multi-microbatch graphs (a DFS chain interleaves each
        # microbatch's forward with its backward). Nodes may carry a
        # ``schedule_rank`` attribute to impose an explicit order — the MPMD
        # builder (ray_tpu/dag/mpmd.py) uses it to emit GPipe / 1F1B
        # per-stage schedules. Sorted only when EVERY op of the actor is
        # ranked: a partial ranking cannot be checked for feasibility.
        for key, ops in schedules.items():
            if all(op["rank"] is not None for op in ops):
                ops.sort(key=lambda op: op["rank"])

        # One error channel per actor: channels are single-writer, and a
        # shared one would interleave writers' sequence numbers.
        self._error_channels = {
            key: self._make_channel(f"dag{self._dag_id}/err/{key}", 1)
            for key in schedules
        }

        # Driver attaches its reader ends FIRST (direct channels publish
        # their routes here — the compile-time KV exchange), so no actor
        # writer ever waits on a late driver registration.
        for chan, ridx in self._output_plan:
            chan.connect(self._rt)
            if hasattr(chan, "ensure_reader"):
                chan.ensure_reader(ridx)
        for chan in self._error_channels.values():
            chan.connect(self._rt)
            if hasattr(chan, "ensure_reader"):
                chan.ensure_reader(0)
        self._in_chan = self._channels[self._input_node.node_id].connect(
            self._rt)

        # Install the loops.
        self._loop_refs = []
        for key, ops in schedules.items():
            handle = self._handles[key]
            self._loop_refs.append(
                handle._call_fn(_actor_loop, ops, self._error_channels[key],
                                self._overlap))

    # ------------------------------------------------------------------ execute
    def execute(self, *input_values, timeout: float | None = 60.0):
        """One synchronous execution through the compiled pipeline."""
        import concurrent.futures as cf

        fut = self.execute_async(*input_values)
        try:
            return fut.result(timeout)
        except cf.TimeoutError:
            raise TimeoutError(
                f"compiled DAG execution timed out after {timeout}s"
            ) from None

    def execute_async(self, *input_values):
        """Admit one execution into the pipeline and return its
        ``concurrent.futures.Future``. Up to ``dag_max_inflight``
        executions overlap across stages (GPipe-style fill); beyond that
        the call blocks until the oldest retires (backpressure). Results
        retire in submission order. The first stage failure fails every
        in-flight future and is re-raised by all later submissions."""
        import concurrent.futures as cf

        if self._torn_down:
            raise RuntimeError("compiled DAG has been torn down")
        if self._error is not None:
            raise self._error
        while not self._window.acquire(timeout=0.1):
            if self._error is not None:
                raise self._error
            if self._torn_down:
                raise RuntimeError("compiled DAG has been torn down")
        value = input_values[0] if len(input_values) == 1 else input_values
        fut: cf.Future = cf.Future()
        try:
            with self._submit_lock:
                # Append BEFORE writing: the completion thread retires
                # futures in FIFO order against the pipeline's FIFO
                # outputs, so both sequences must be built under one lock.
                self._pending.append(fut)
                # Span around the input write: the channel injects the
                # context into its push frame, so the first hop (and every
                # downstream hop, each re-injecting at its own write)
                # parents this execution's dataflow under one trace.
                if tracing.tracing_enabled():
                    with tracing.span(f"dag.execute.{self._dag_id}",
                                      kind="client"):
                        self._in_chan.write(value)
                else:
                    self._in_chan.write(value)
        except BaseException as e:
            with self._submit_lock:
                try:
                    self._pending.remove(fut)
                except ValueError:
                    pass
            self._window.release()
            err = self._check_failure(settle=1.0)
            raise (err if err is not None else e)
        self._work.set()
        self._ensure_completer()
        return fut

    def _ensure_completer(self) -> None:
        with self._completer_lock:
            if self._completer is None or not self._completer.is_alive():
                self._completer = threading.Thread(
                    target=self._completer_main,
                    name=f"dag-{self._dag_id}-completer", daemon=True)
                self._completer.start()

    def _completer_main(self) -> None:
        """Retire in-flight executions in FIFO order: read the terminal
        channels once per pending future, resolve it, free its window slot.
        On any failure sign, harvest the ROOT cause (dead-actor loop refs
        first, then error frames) and fail everything in flight."""
        while not self._completer_stop.is_set():
            if not self._pending:
                self._work.wait(timeout=0.1)
                self._work.clear()
                continue
            fut = self._pending[0]
            try:
                outs = []
                for chan, reader_idx in self._output_plan:
                    outs.append(self._read_output(chan, reader_idx))
                if self._completer_stop.is_set():
                    return
                result = outs if self._multi_output else outs[0]
                self._retire(fut, value=result)
            except _DagFailure as e:
                self._fail_inflight(e.cause)
                return
            except _CompleterStopped:
                return
            except BaseException as e:  # noqa: BLE001
                self._fail_inflight(e)
                return

    def _read_output(self, chan, reader_idx: int):
        while True:
            try:
                return chan.read(reader_idx, timeout=0.25)
            except TimeoutError:
                if self._completer_stop.is_set():
                    raise _CompleterStopped() from None
                err = self._check_failure()
                if err is not None:
                    raise _DagFailure(err) from None
            except ChannelClosed:
                # A failed stage closes its channels after reporting;
                # surface the actor's own error, not the secondary symptom.
                err = self._check_failure(settle=3.0)
                if err is None:
                    err = self._set_error(RuntimeError(
                        "compiled DAG output channel closed"))
                raise _DagFailure(err) from None

    def _retire(self, fut, value=None, exc: BaseException | None = None):
        with self._submit_lock:
            try:
                self._pending.remove(fut)
            except ValueError:
                pass
        if exc is not None:
            if not fut.done():
                fut.set_exception(exc)
        elif not fut.done():
            fut.set_result(value)
        self._window.release()

    def _set_error(self, exc: BaseException) -> BaseException:
        """First error wins — later failures are secondary symptoms.
        Submitter and completer both race to publish."""
        with self._submit_lock:
            if self._error is None:
                self._error = exc
            return self._error

    def _fail_inflight(self, cause: BaseException) -> None:
        cause = self._set_error(cause)
        while self._pending:
            self._retire(self._pending[0], exc=cause)

    # ------------------------------------------------------------------ errors
    def _poll_error(self, timeout: float = 0.001):
        """First error frame reported by any actor loop. The frame is
        consumed once and CACHED — every later poll (and every later
        execute) sees the same first error instead of a secondary
        timeout."""
        if self._error_msg is not None:
            return self._error_msg
        for chan in self._error_channels.values():
            try:
                kind, msg = chan.read(0, timeout=timeout)
                if kind == "error":
                    with self._submit_lock:
                        self._error_msg = msg
                    return msg
            except Exception:
                continue
        return None

    def _check_failure(self, settle: float = 0.0) -> BaseException | None:
        """Root-cause harvest: a dead stage actor's loop ref raises the
        real ``ActorDiedError`` (preferred over any secondary channel
        symptom); an in-actor exception arrives as an error frame. With
        ``settle`` > 0, poll for up to that long before giving up — death
        notifications race the channel teardown cascade."""
        import ray_tpu

        if self._error is not None:
            return self._error
        deadline = time.monotonic() + settle
        soft: str | None = None
        while True:
            for ref in list(self._loop_refs):
                try:
                    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
                except Exception:
                    continue
                if not ready:
                    continue
                try:
                    res = ray_tpu.get(ref)
                except BaseException as e:  # the real actor death
                    return self._set_error(e)
                if isinstance(res, str) and res.startswith("error:"):
                    soft = res[len("error:"):].strip()
            msg = self._poll_error(timeout=0.01)
            if msg is None and soft is not None:
                with self._submit_lock:
                    self._error_msg = msg = soft
            if msg is not None:
                return self._set_error(RuntimeError(
                    f"compiled DAG execution failed: {msg}"))
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.05)

    # ------------------------------------------------------------------ teardown
    def teardown(self):
        """Close the input channel; each actor loop cascades the close to
        its own output channels and exits. In-flight executions are
        drained (so ack-gated writers can unwind) and their futures fail
        as torn down."""
        if self._torn_down:
            return
        self._torn_down = True
        self._completer_stop.set()
        self._work.set()
        if self._completer is not None:
            self._completer.join(timeout=5.0)
        try:
            self._in_chan.close()
        except Exception:
            pass
        # Drain whatever the pipeline still produces: every consumed output
        # acks its upstream writer, letting each stage reach (and cascade)
        # the close marker instead of wedging on channel backpressure. A
        # failed DAG skips the long drain — its loops already unwound (or
        # died), so waiting out the deadline would just stall teardown.
        deadline = time.monotonic() + (1.0 if self._error is not None
                                       else 10.0)
        open_outputs = set(range(len(self._output_plan)))
        while open_outputs and time.monotonic() < deadline:
            progressed = False
            for i in list(open_outputs):
                chan, reader_idx = self._output_plan[i]
                try:
                    chan.read(reader_idx, timeout=0.2)
                    progressed = True
                except TimeoutError:
                    continue
                except Exception:
                    open_outputs.discard(i)
            if not progressed and not open_outputs:
                break
        # Reclaim channel resources (registry entries locally; KV slots,
        # route keys and receiver queues in cluster mode). Destroy BEFORE
        # waiting on the loops: direct channels force-close every attached
        # reader, which is what unwedges a loop blocked reading from a DEAD
        # upstream stage (its writer will never send a close marker) — the
        # healthy path already drained to quiescence above, so nothing is
        # truncated.
        for chan in list(self._channels.values()) + list(
                self._error_channels.values()):
            try:
                chan.connect(self._rt).destroy()
            except Exception:
                pass
        # The loop results confirm shutdown (and surface loop errors in tests).
        import ray_tpu

        try:
            ray_tpu.wait(self._loop_refs, num_returns=len(self._loop_refs),
                         timeout=10.0)
        except Exception:
            pass
        # Fail anything still in flight with the cached root cause if one
        # exists, else as torn down.
        exc = self._error or RuntimeError(
            "compiled DAG torn down with executions in flight")
        while self._pending:
            self._retire(self._pending[0], exc=exc)

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


class _CompleterStopped(Exception):
    pass
