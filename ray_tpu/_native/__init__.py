"""Native (C++) components, compiled on demand.

The reference ships its runtime as prebuilt C++ (src/ray/...); this build
compiles small C++ components with the system toolchain at first use and
caches the .so beside the sources' hash, so `pip install`-less environments
work and rebuilds happen exactly when sources change.
"""

from ray_tpu._native.build import load_library

__all__ = ["load_library"]
