"""Compiled graphs: lazy DAGs, channels, static per-actor schedules.

Mirrors the reference's compiled-graph test surface (reference:
python/ray/dag/tests/ — bind/execute, experimental_compile round trips,
multi-output, teardown, error propagation).
"""

import pytest

from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import ChannelClosed, LocalChannel, StoreChannel


class TestChannels:
    def test_local_channel_roundtrip(self):
        ch = LocalChannel("t1", num_readers=2)
        ch.write({"x": 1})
        assert ch.read(0) == {"x": 1}
        assert ch.read(1) == {"x": 1}
        ch.close()
        with pytest.raises(ChannelClosed):
            ch.read(0)

    def test_local_channel_pickle_identity(self):
        import cloudpickle

        ch = LocalChannel("t2")
        ch2 = cloudpickle.loads(cloudpickle.dumps(ch))
        assert ch2 is ch

    def test_store_channel_roundtrip(self, rt_start):
        from ray_tpu.core.worker import global_worker

        rt = global_worker.runtime
        w = StoreChannel("s1").connect(rt)
        r = StoreChannel("s1").connect(rt)
        w.write([1, 2, 3])
        w.write([4])
        assert r.read() == [1, 2, 3]
        assert r.read() == [4]
        w.close()
        with pytest.raises(ChannelClosed):
            r.read(timeout=5)

    def test_store_channel_timeout(self, rt_start):
        from ray_tpu.core.worker import global_worker

        r = StoreChannel("s2").connect(global_worker.runtime)
        with pytest.raises(TimeoutError):
            r.read(timeout=0.05)


class TestDagApi:
    def test_bind_and_eager_execute(self, rt_start):
        rt = rt_start

        @rt.remote
        class Adder:
            def __init__(self, k):
                self.k = k

            def add(self, x):
                return x + self.k

        a, b = Adder.remote(1), Adder.remote(10)
        with InputNode() as inp:
            dag = b.add.bind(a.add.bind(inp))
        assert dag.execute(5) == 16  # (5+1)+10

    def test_multi_output_eager(self, rt_start):
        rt = rt_start

        @rt.remote
        class M:
            def double(self, x):
                return 2 * x

            def triple(self, x):
                return 3 * x

        m = M.remote()
        with InputNode() as inp:
            dag = MultiOutputNode([m.double.bind(inp), m.triple.bind(inp)])
        assert dag.execute(4) == [8, 12]


class TestCompiledDag:
    def test_compiled_pipeline(self, rt_start):
        rt = rt_start

        @rt.remote
        class Stage:
            def __init__(self, k):
                self.k = k

            def f(self, x):
                return x * self.k

        s1, s2 = Stage.remote(2), Stage.remote(5)
        with InputNode() as inp:
            dag = s2.f.bind(s1.f.bind(inp))
        compiled = dag.experimental_compile()
        try:
            for i in range(10):
                assert compiled.execute(i) == i * 10
        finally:
            compiled.teardown()

    def test_compiled_multi_output_fanout(self, rt_start):
        rt = rt_start

        @rt.remote
        class W:
            def __init__(self, tag):
                self.tag = tag

            def go(self, x):
                return f"{self.tag}:{x}"

        a, b = W.remote("a"), W.remote("b")
        with InputNode() as inp:
            dag = MultiOutputNode([a.go.bind(inp), b.go.bind(inp)])
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(7) == ["a:7", "b:7"]
            assert compiled.execute(8) == ["a:8", "b:8"]
        finally:
            compiled.teardown()

    def test_compiled_same_actor_fanout(self, rt_start):
        """One actor consuming the same upstream value in two ops needs two
        reader slots (regression: per-actor dedupe deadlocked this shape)."""
        rt = rt_start

        @rt.remote
        class M:
            def double(self, x):
                return 2 * x

            def triple(self, x):
                return 3 * x

        m = M.remote()
        with InputNode() as inp:
            dag = MultiOutputNode([m.double.bind(inp), m.triple.bind(inp)])
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(4, timeout=10) == [8, 12]
            assert compiled.execute(5, timeout=10) == [10, 15]
        finally:
            compiled.teardown()

    def test_compiled_error_propagates(self, rt_start):
        rt = rt_start

        @rt.remote
        class Bad:
            def f(self, x):
                raise ValueError("boom-in-dag")

        bad = Bad.remote()
        with InputNode() as inp:
            dag = bad.f.bind(inp)
        compiled = dag.experimental_compile()
        try:
            with pytest.raises((RuntimeError, TimeoutError)):
                compiled.execute(1, timeout=5)
        finally:
            compiled.teardown()

    def test_execute_after_teardown_raises(self, rt_start):
        rt = rt_start

        @rt.remote
        class S:
            def f(self, x):
                return x

        s = S.remote()
        with InputNode() as inp:
            dag = s.f.bind(inp)
        compiled = dag.experimental_compile()
        compiled.teardown()
        with pytest.raises(RuntimeError):
            compiled.execute(1)

    def test_requires_input_node(self, rt_start):
        rt = rt_start

        @rt.remote
        class S:
            def f(self, x):
                return x

        s = S.remote()
        dag = s.f.bind(41)
        with pytest.raises(ValueError):
            dag.experimental_compile()

    def test_compiled_cluster_mode(self):
        """Cross-process channels: the pipeline spans real worker procs."""
        import ray_tpu

        ray_tpu.shutdown()
        ray_tpu.init(address="local-cluster", num_cpus=2)
        try:
            @ray_tpu.remote
            class Stage:
                def __init__(self, k):
                    self.k = k

                def f(self, x):
                    return x + self.k

            s1, s2 = Stage.remote(100), Stage.remote(1000)
            with InputNode() as inp:
                dag = s2.f.bind(s1.f.bind(inp))
            compiled = dag.experimental_compile()
            try:
                assert compiled.execute(5, timeout=30) == 1105
                assert compiled.execute(6, timeout=30) == 1106
            finally:
                compiled.teardown()
        finally:
            ray_tpu.shutdown()


class TestCommunicatorRegistry:
    def test_register_and_default(self):
        from ray_tpu.dag import (
            Communicator,
            get_accelerator_communicator,
            register_accelerator_communicator,
        )

        assert get_accelerator_communicator().name == "collective"

        class Fake(Communicator):
            name = "fake-tpu"

        register_accelerator_communicator(Fake())
        assert get_accelerator_communicator("fake-tpu").name == "fake-tpu"
        assert get_accelerator_communicator().name == "collective"
