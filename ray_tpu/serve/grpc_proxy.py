"""gRPC ingress proxy.

Capability parity with the reference's gRPC proxy (reference:
python/ray/serve/_private/proxy.py gRPCProxy — a grpc.server whose service
methods route to the application's ingress deployment; the app is selected
with the `application` request-metadata key; streaming methods yield).

Proto-agnostic design: a GenericRpcHandler accepts ANY fully-qualified
method (`/pkg.Service/Method`) with identity (de)serializers, so user
deployments work with raw request bytes (decode with their own protobuf or
codec) and return bytes/str/JSON-able values. A client that sets the
`streaming` metadata key gets a server-streaming call whose responses are
the chunks the deployment generator yields. This keeps the reference's
"bring your own servicer" capability without a protoc build step.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field


@dataclass
class GrpcRequest:
    """What an ingress deployment's __call__ receives for a gRPC request."""

    method: str                                  # "/pkg.Service/Method"
    data: bytes = b""
    metadata: dict[str, str] = field(default_factory=dict)

    def json(self):
        return json.loads(self.data) if self.data else None


def _encode(chunk) -> bytes:
    if isinstance(chunk, (bytes, bytearray)):
        return bytes(chunk)
    if isinstance(chunk, str):
        return chunk.encode()
    return json.dumps(chunk).encode()


class GrpcProxyActor:
    """Binds a grpc.server; routes every method to the application ingress
    selected by the `application` metadata key (or the only route)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc
        from concurrent import futures

        self._routes: dict[str, str] = {}   # route_prefix -> deployment
        self._apps: dict[str, str] = {}     # app name -> deployment
        self._handles: dict[str, object] = {}
        self._lock = threading.Lock()
        proxy = self

        class AnyService(grpc.GenericRpcHandler):
            def service(self, details):
                streaming = any(k == "streaming" and str(v).lower() in
                                ("1", "true")
                                for k, v in (details.invocation_metadata or []))
                method = details.method

                def unary(request, context):
                    return proxy._call(method, request, context,
                                       stream=False)

                def stream(request, context):
                    yield from proxy._call(method, request, context,
                                           stream=True)

                if streaming:
                    return grpc.unary_stream_rpc_method_handler(
                        stream, request_deserializer=None,
                        response_serializer=None)
                return grpc.unary_unary_rpc_method_handler(
                    unary, request_deserializer=None,
                    response_serializer=None)

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((AnyService(),))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def _pick(self, metadata: dict[str, str]) -> str | None:
        with self._lock:
            app = metadata.get("application")
            if app and app in self._apps:
                return self._apps[app]
            if self._routes:
                # deterministic default: shortest route prefix (the "/" app)
                route = sorted(self._routes)[0]
                return self._routes[route]
            if len(self._apps) == 1:  # single gRPC-only app: route to it
                return next(iter(self._apps.values()))
        return None

    def _call(self, method: str, request: bytes, context, stream: bool):
        import grpc

        md = {k: str(v) for k, v in (context.invocation_metadata() or [])}
        dep = self._pick(md)
        if dep is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          "no serve application for this call")
        req = GrpcRequest(method=method, data=bytes(request or b""),
                          metadata=md)
        # Deadline: the client's native gRPC deadline (time_remaining)
        # becomes the serve request budget, propagated through router,
        # replica admission, and batcher.
        timeout_s = None
        try:
            rem = context.time_remaining()
            if rem is not None and rem > 0:
                timeout_s = rem
        except Exception:
            pass
        try:
            gen = self._get_handle(dep).options(
                stream=True, timeout_s=timeout_s).remote(req)
        except Exception as e:  # noqa: BLE001 - mapped below
            self._abort_resilience(context, e)
            raise
        gen.timeout = timeout_s or 60.0
        if stream:
            return self._stream_chunks(gen, context)
        # Unary: take exactly the first chunk. A bare next() would leak
        # StopIteration through the grpc handler as an opaque UNKNOWN error,
        # and silently drop any extra chunks the deployment yields.
        try:
            first = next(gen)
        except StopIteration:
            context.abort(grpc.StatusCode.OUT_OF_RANGE,
                          "deployment yielded no response for unary call")
        except Exception as e:  # noqa: BLE001 - mapped below
            self._abort_resilience(context, e)
            raise
        finally:
            close = getattr(gen, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        return _encode(first)

    def _stream_chunks(self, gen, context):
        try:
            for c in gen:
                yield _encode(c)
        except Exception as e:  # noqa: BLE001 - mapped below
            self._abort_resilience(context, e)
            raise

    @staticmethod
    def _abort_resilience(context, err: BaseException) -> None:
        """Map resilience failures to canonical gRPC codes (reference:
        serve's gRPC proxy surfaces backpressure as RESOURCE_EXHAUSTED so
        clients with retry policies back off):

        - Overloaded       → RESOURCE_EXHAUSTED (retry-after in details)
        - DeadlineExceeded → DEADLINE_EXCEEDED

        Anything else falls through to the default UNKNOWN mapping."""
        import grpc

        from ray_tpu.serve import resilience

        cause = resilience.unwrap(err)
        if isinstance(cause, resilience.Overloaded):
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"overloaded ({cause.where}); "
                f"retry after {cause.retry_after_s:.1f}s")
        if isinstance(cause, (resilience.DeadlineExceeded, TimeoutError)):
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                          "request deadline exceeded")

    def _get_handle(self, deployment_name: str):
        from ray_tpu.serve.handle import DeploymentHandle

        with self._lock:
            if deployment_name not in self._handles:
                self._handles[deployment_name] = DeploymentHandle(
                    deployment_name)
            return self._handles[deployment_name]

    # -- control plane --

    def update_routes(self, routes: dict[str, str],
                      apps: dict[str, str] | None = None) -> None:
        """``apps`` is the controller's authoritative app→ingress map
        (get_app_ingresses), which includes gRPC-only route_prefix=None
        applications the HTTP route table can't represent."""
        with self._lock:
            self._routes = dict(routes)
            if apps is not None:
                self._apps = dict(apps)

    def port(self) -> int:
        return self._port

    def ready(self) -> bool:
        return True

    def shutdown(self) -> None:
        self._server.stop(grace=None)
