"""One profiling capture session for THIS process.

``capture_profile(seconds)`` runs, for a clamped duration:

(a) the Python stack sampler (collapsed flamegraph lines + sample timeline),
(b) a ``jax.profiler`` trace session — guarded so it degrades to a no-op
    marker when jax was never initialized here or the backend is CPU-only
    (tier-1), and
(c) a before/after memory snapshot (device buffers, RSS, store occupancy).

Exactly one capture runs per process at a time: a second request returns a
``busy`` error (and counts into ``profiler_dropped_captures``) instead of
double-sampling — the per-NODE concurrency cap lives in the node daemon.
"""

from __future__ import annotations

import os
import threading
import time

from ray_tpu.utils.config import get_config

_capture_lock = threading.Lock()


def _xla_trace_begin(logdir: str | None) -> tuple[dict, bool]:
    """Start a jax.profiler trace when it is meaningful; otherwise return
    the degradation marker. Never initializes a jax backend in a process
    that hasn't."""
    from ray_tpu.profiling.memory import jax_backend_ready

    cfg = get_config()
    if not cfg.profiler_xla_trace:
        return {"status": "skipped", "reason": "disabled by config "
                "(profiler_xla_trace=False)"}, False
    if not jax_backend_ready():
        return {"status": "skipped",
                "reason": "jax not initialized in this process"}, False
    try:
        import jax

        backend = jax.default_backend()
        if backend == "cpu":
            # CPU-only tier-1: a device trace has nothing to say and the
            # TensorBoard plugin deps may be absent — no-op marker.
            return {"status": "skipped",
                    "reason": "cpu-only backend (no XLA device trace)"}, \
                False
        logdir = logdir or os.path.join(
            cfg.temp_dir, "xla_traces", f"{os.getpid()}-{time.time_ns()}")
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
        return {"status": "capturing", "backend": backend,
                "logdir": logdir}, True
    except Exception as e:  # noqa: BLE001 - trace must not fail the capture
        return {"status": "error", "reason": f"{type(e).__name__}: {e}"}, \
            False


def _xla_trace_end(state: dict) -> dict:
    try:
        import jax

        jax.profiler.stop_trace()
        state = dict(state)
        state["status"] = "captured"
    except Exception as e:  # noqa: BLE001
        state = dict(state)
        state["status"] = "error"
        state["reason"] = f"{type(e).__name__}: {e}"
    return state


def capture_profile(seconds: float, *, sample_hz: float | None = None,
                    xla_logdir: str | None = None,
                    meta: dict | None = None) -> dict:
    """Blocking capture (callers run it on an executor thread, never the
    event loop). Returns the capture bundle, or ``{"error": "busy", ...}``
    when this process is already capturing."""
    from ray_tpu.profiling import count_dropped, profiler_metrics
    from ray_tpu.profiling.memory import memory_snapshot
    from ray_tpu.profiling.sampler import StackSampler

    cfg = get_config()
    seconds = max(0.05, min(float(seconds), cfg.profiler_max_capture_s))
    hz = float(sample_hz or cfg.profiler_sample_hz)
    if not _capture_lock.acquire(blocking=False):
        count_dropped("busy")
        return {"error": "busy", "reason": "a capture is already running in "
                f"this process (pid {os.getpid()})", "meta": dict(meta or {})}
    try:
        mem_before = memory_snapshot()
        xla, xla_live = _xla_trace_begin(xla_logdir)
        sampler = StackSampler(hz=hz).start()
        hz = sampler.hz  # report the CLAMPED rate (sampler enforces _MAX_HZ)
        t0 = time.monotonic()
        time.sleep(seconds)
        sampler.stop()
        if xla_live:
            xla = _xla_trace_end(xla)
        duration = time.monotonic() - t0
        bundle = {
            "meta": dict(meta or {}),
            "pid": os.getpid(),
            "duration_s": duration,
            "sample_hz": hz,
            "samples": sampler.samples,
            "collapsed": sampler.collapsed(),
            "sample_events": sampler.sample_events(),
            "xla_trace": xla,
            "memory": memory_snapshot(),
            "memory_before": mem_before,
            "started_at": sampler.started_at,
            "ended_at": sampler.ended_at,
        }
        try:
            kind = (meta or {}).get("kind", "process")
            profiler_metrics()["capture_seconds"].inc(
                duration, tags={"kind": kind})
        except Exception:
            pass
        return bundle
    finally:
        _capture_lock.release()
