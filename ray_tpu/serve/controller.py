"""ServeController: the serve control plane.

Capability parity with the reference's controller (reference:
python/ray/serve/_private/controller.py:121 ServeController — singleton
actor owning desired state; _private/deployment_state.py:2278
DeploymentState replica FSM STARTING/RUNNING/STOPPING with rolling updates
and health checks; autoscaling_state.py metrics-driven replica targets;
config pushed to routers via the long-poll host).
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
import traceback
import uuid
from dataclasses import dataclass, field
from typing import Any

import ray_tpu
from ray_tpu.serve.config import DeploymentConfig, DeploymentStatus, ReplicaInfo
from ray_tpu.serve.long_poll import LongPollHost
from ray_tpu.serve.replica import ServeReplica

STARTING, RUNNING, STOPPING = "STARTING", "RUNNING", "STOPPING"


@dataclass
class _Replica:
    replica_id: str
    actor_name: str
    actor: Any
    version: str
    state: str = STARTING
    ready_ref: Any = None
    health_ref: Any = None
    health_sent_at: float = 0.0
    consecutive_failures: int = 0
    drain_ref: Any = None
    stop_deadline: float = 0.0
    pg: Any = None  # per-replica gang placement group, if configured
    # Prefix-cache publication (KV-block-aware routing): last collected
    # router_meta state. prefix_capable None = not yet probed; False =
    # replica answered None once, never polled again (non-LLM deployment).
    prefix_blocks: tuple | None = None
    prefix_block: int = 0
    prefix_capable: bool | None = None
    prefix_ref: Any = None
    prefix_sent_at: float = 0.0


@dataclass
class _DeploymentState:
    name: str
    app_name: str
    cls_blob: bytes
    init_args_blob: bytes
    config: DeploymentConfig
    version: str
    replicas: list[_Replica] = field(default_factory=list)
    deleting: bool = False
    published: list | None = None  # last replica snapshot sent to routers
    # autoscaling bookkeeping
    last_metric_pull: float = 0.0
    total_ongoing: float = 0.0
    desired_since: tuple[int, float] | None = None  # (desired, since_ts)
    autoscale_target: int | None = None
    message: str = ""


class ServeController:
    """Runs as a named detached-style actor; reconciles in a background
    thread (reference: controller's run_control_loop)."""

    def __init__(self, reconcile_interval_s: float = 0.05):
        self._interval = reconcile_interval_s
        self._lock = threading.RLock()
        self._deployments: dict[str, _DeploymentState] = {}
        self._apps: dict[str, list[str]] = {}
        self._routes: dict[str, str] = {}  # route_prefix -> deployment name
        self._app_ingress: dict[str, str] = {}  # app name -> ingress dep
        self._long_poll = LongPollHost()
        self._shutdown = threading.Event()
        self._thread = threading.Thread(target=self._control_loop, daemon=True)
        self._thread.start()

    # ---- API (called by serve.api / handles / proxies) ----

    def deploy_application(self, app_name: str, deployments: list[dict],
                           ingress_name: str | None,
                           route_prefix: str | None) -> None:
        with self._lock:
            old = set(self._apps.get(app_name, []))
            new_names = []
            for d in deployments:
                name = d["name"]
                new_names.append(name)
                version = d["config"].version or hashlib.sha1(
                    d["cls_blob"] + d["init_args_blob"] +
                    repr(d["config"].user_config).encode() +
                    repr(d["config"].num_replicas).encode()
                ).hexdigest()[:12]
                cur = self._deployments.get(name)
                if cur is None:
                    self._deployments[name] = _DeploymentState(
                        name=name, app_name=app_name, cls_blob=d["cls_blob"],
                        init_args_blob=d["init_args_blob"], config=d["config"],
                        version=version)
                else:
                    cur.cls_blob = d["cls_blob"]
                    cur.init_args_blob = d["init_args_blob"]
                    cur.config = d["config"]
                    cur.version = version
                    cur.deleting = False
            for stale in old - set(new_names):
                self._deployments[stale].deleting = True
            self._apps[app_name] = new_names
            if ingress_name:
                # gRPC routes by app name even when there is no HTTP route
                # prefix (route_prefix=None).
                self._app_ingress[app_name] = ingress_name
            if ingress_name and route_prefix is not None:
                self._routes[route_prefix] = ingress_name
                self._long_poll.notify_changed("routes", dict(self._routes))

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            self._app_ingress.pop(app_name, None)
            for name in self._apps.pop(app_name, []):
                if name in self._deployments:
                    self._deployments[name].deleting = True
            self._routes = {r: d for r, d in self._routes.items()
                            if d in {n for ns in self._apps.values() for n in ns}}
            self._long_poll.notify_changed("routes", dict(self._routes))

    def get_replicas(self, deployment_name: str) -> list[ReplicaInfo]:
        with self._lock:
            ds = self._deployments.get(deployment_name)
            if ds is None:
                return []
            return self._running_infos(ds)

    def listen(self, keys_to_versions: dict, timeout: float = 10.0) -> dict:
        return self._long_poll.listen(keys_to_versions, timeout)

    def get_routes(self) -> dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def report_replica_unhealthy(self, deployment_name: str,
                                 replica_id: str, reason: str = "") -> None:
        """Router circuit-breaker feedback: a breaker opened on this
        replica. Counts as one failed health check AND schedules an
        immediate out-of-band probe — a genuinely sick replica fails it
        and gets replaced for every router, while a healthy-but-slow one
        passes and stays up (blacklisted only where the breaker saw the
        latency). Repeated breaker trips therefore converge on replacement
        without letting one router's opinion kill a replica outright."""
        with self._lock:
            ds = self._deployments.get(deployment_name)
            if ds is None:
                return
            for r in ds.replicas:
                if r.replica_id == replica_id and r.state == RUNNING:
                    # Reports alone must never reach the replacement
                    # threshold — several routers (driver + each proxy)
                    # tripping at once would stop a slow-but-healthy
                    # replica before its probe returns. Cap one below:
                    # only an actually failed/timed-out probe pushes over.
                    r.consecutive_failures = min(
                        r.consecutive_failures + 1,
                        ds.config.max_consecutive_health_failures - 1)
                    if r.health_ref is None:
                        # Probe on the next reconcile. Only when no probe
                        # is already outstanding: zeroing health_sent_at
                        # under an in-flight probe would trip the
                        # stale-probe timeout branch — a spurious SECOND
                        # strike that also discards the (likely passing)
                        # probe result.
                        r.health_sent_at = 0.0
                    ds.message = (f"router breaker opened on "
                                  f"{replica_id}: {reason}")
                    break

    def get_app_ingresses(self) -> dict[str, str]:
        """app name -> ingress deployment, including HTTP-less (gRPC-only,
        route_prefix=None) applications."""
        with self._lock:
            return dict(self._app_ingress)

    def status(self) -> dict[str, DeploymentStatus]:
        with self._lock:
            out = {}
            for name, ds in self._deployments.items():
                counts: dict[str, int] = {}
                for r in ds.replicas:
                    counts[r.state] = counts.get(r.state, 0) + 1
                target = self._target_count(ds)
                healthy = sum(1 for r in ds.replicas
                              if r.state == RUNNING and r.version == ds.version)
                status = ("HEALTHY" if healthy >= target and not ds.deleting
                          else "UPDATING")
                out[name] = DeploymentStatus(name=name, status=status,
                                             replica_states=counts,
                                             message=ds.message)
            return out

    def graceful_shutdown(self) -> None:
        with self._lock:
            for ds in self._deployments.values():
                ds.deleting = True
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with self._lock:
                if all(not ds.replicas for ds in self._deployments.values()):
                    break
            time.sleep(0.05)
        self._shutdown.set()

    # ---- reconcile loop ----

    def _control_loop(self) -> None:
        consecutive_conn_failures = 0
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
                consecutive_conn_failures = 0
            except Exception as e:  # noqa: BLE001 - loop must survive all
                from ray_tpu.core.cluster.protocol import RpcConnectionLost

                if isinstance(e, RpcConnectionLost):
                    # Head outage that outlived the runtime's retry
                    # budget: keep the controller alive and back off —
                    # replicas keep serving (the data plane is
                    # router→replica direct), and reconciliation resumes
                    # the moment the control plane answers again. A
                    # traceback per reconcile tick would just flood logs.
                    consecutive_conn_failures += 1
                    self._shutdown.wait(
                        min(2.0, 0.2 * consecutive_conn_failures))
                else:
                    traceback.print_exc()
            time.sleep(self._interval)

    def _reconcile_once(self) -> None:
        with self._lock:
            items = list(self._deployments.items())
        for name, ds in items:
            with self._lock:
                self._check_starting(ds)
                self._check_health(ds)
                self._collect_prefix_state(ds)
                self._autoscale(ds)
                target = 0 if ds.deleting else self._target_count(ds)
                self._scale_and_roll(ds, target)
                self._reap_stopped(ds)
                after = self._running_infos(ds)
                # Compare against the LAST PUBLISHED snapshot, not a
                # same-pass before (a settings-only redeploy swaps
                # ds.config between passes — an intra-pass before/after
                # would already both carry the new settings and compare
                # equal). Dataclass equality covers the settings dict, so
                # draining transitions AND settings-only redeploys (e.g.
                # tightening max_queued_requests during an incident, which
                # rolls no replicas) both reach routers.
                if after != ds.published:
                    ds.published = after
                    self._long_poll.notify_changed(f"replicas:{name}", after)
                if ds.deleting and not ds.replicas:
                    del self._deployments[name]

    def _target_count(self, ds: _DeploymentState) -> int:
        asc = ds.config.autoscaling_config
        if asc is None:
            return ds.config.num_replicas
        if ds.autoscale_target is None:
            ds.autoscale_target = asc.min_replicas
        return ds.autoscale_target

    def _running_infos(self, ds: _DeploymentState) -> list[ReplicaInfo]:
        """Router-facing snapshot: RUNNING replicas plus gracefully-draining
        ones flagged ``draining=True`` (published, never assigned — a
        router that saw the pre-drain snapshot must learn the replica is
        retiring rather than racing new work onto it). Each info carries
        the deployment-level resilience settings dict."""
        settings = ds.config.resilience_settings().to_dict()
        infos = []
        for r in ds.replicas:
            draining = r.state == STOPPING and r.drain_ref is not None
            if r.state != RUNNING and not draining:
                continue
            infos.append(ReplicaInfo(
                replica_id=r.replica_id,
                deployment_name=ds.name,
                actor_name=r.actor_name,
                max_ongoing_requests=ds.config.max_ongoing_requests,
                draining=draining,
                settings=settings,
                # Prefix-cache publication rides the snapshot; dataclass
                # equality against ds.published means a changed hash set
                # republishes (throttled by the collection cadence).
                prefix_blocks=r.prefix_blocks,
                prefix_block=r.prefix_block))
        return infos

    # -- replica lifecycle --

    def _start_replica(self, ds: _DeploymentState) -> "_Replica | None":
        rid = uuid.uuid4().hex[:8]
        actor_name = f"SERVE_REPLICA::{ds.name}#{rid}"
        if ds.config.placement_group_bundles:
            # Gang reservation per replica (reference: serve deployment
            # placement_group_bundles; ray.llm replica PGs hold the TP/PP
            # worker hosts). The PG 2PC commits asynchronously, so the
            # replica record starts actor-less and _check_starting launches
            # the actor once the PG reports CREATED — never blocking the
            # control loop on reservation.
            from ray_tpu.util.placement_group import placement_group

            try:
                pg = placement_group(
                    [dict(b) for b in ds.config.placement_group_bundles],
                    strategy=ds.config.placement_group_strategy)
            except Exception as e:  # noqa: BLE001 - bad bundle config
                ds.message = f"placement group creation failed: {e!r}"
                return None
            rep = _Replica(replica_id=rid, actor_name=actor_name, actor=None,
                           version=ds.version, pg=pg)
            rep.stop_deadline = time.monotonic() + 60.0  # PG-wait deadline
            ds.replicas.append(rep)
            return rep
        rep = _Replica(replica_id=rid, actor_name=actor_name, actor=None,
                       version=ds.version)
        ds.replicas.append(rep)
        self._launch_replica_actor(ds, rep)
        return rep if rep in ds.replicas else None

    def _launch_replica_actor(self, ds: _DeploymentState,
                              rep: _Replica) -> None:
        opts = dict(ds.config.ray_actor_options)
        sched_kw = {}
        if rep.pg is not None:
            from ray_tpu.util.placement_group import (
                PlacementGroupSchedulingStrategy)

            sched_kw["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=rep.pg, placement_group_bundle_index=0)
        Remote = ray_tpu.remote(ServeReplica)
        # Thread budget must exceed the replica's admission cap
        # (max_ongoing + queue slack) so over-cap calls actually reach the
        # admission check and get an Overloaded answer promptly instead of
        # queuing silently in the actor mailbox.
        slack = getattr(ds.config, "replica_queue_slack", 8)
        try:
            rep.actor = Remote.options(
                name=rep.actor_name, namespace="serve",
                num_cpus=opts.get("num_cpus", 0),
                num_tpus=opts.get("num_tpus", 0),
                resources=opts.get("resources"),
                max_concurrency=ds.config.max_ongoing_requests + slack + 4,
                **sched_kw,
            ).remote(ds.name, rep.replica_id, ds.cls_blob, ds.init_args_blob,
                     ds.config.user_config,
                     max_ongoing_requests=ds.config.max_ongoing_requests,
                     replica_queue_slack=slack)
        except Exception as e:  # noqa: BLE001 - infeasible/registration fail
            ds.message = f"replica actor creation failed: {e!r}"
            self._release_pg(rep)
            ds.replicas.remove(rep)
            return
        rep.ready_ref = rep.actor.get_metrics.remote()  # readiness probe

    def _release_pg(self, rep: _Replica) -> None:
        if rep.pg is None:
            return
        try:
            from ray_tpu.util.placement_group import remove_placement_group

            remove_placement_group(rep.pg)
        except Exception:  # noqa: BLE001
            pass
        rep.pg = None

    def _check_starting(self, ds: _DeploymentState) -> None:
        from ray_tpu.core.worker import global_worker

        now = time.monotonic()
        for r in list(ds.replicas):
            if r.state != STARTING:
                continue
            if r.actor is None:
                # Waiting on the gang PG's async 2PC (non-blocking poll).
                try:
                    state = global_worker.runtime.placement_group_state(
                        r.pg.id)
                except Exception:  # noqa: BLE001
                    state = "PENDING"
                if state == "CREATED":
                    r.stop_deadline = 0.0
                    self._launch_replica_actor(ds, r)
                elif state in ("REMOVED", "FAILED") or now > r.stop_deadline:
                    ds.message = (f"replica {r.replica_id} placement group "
                                  f"not satisfiable (state {state})")
                    self._release_pg(r)
                    ds.replicas.remove(r)
                continue
            if r.ready_ref is None:
                continue
            ready, _ = ray_tpu.wait([r.ready_ref], num_returns=1, timeout=0)
            if ready:
                try:
                    ray_tpu.get(r.ready_ref)
                    r.state = RUNNING
                    r.ready_ref = None
                except Exception as e:
                    ds.message = f"replica failed to start: {e!r}"
                    self._stop_replica(ds, r, force=True)

    def _check_health(self, ds: _DeploymentState) -> None:
        now = time.monotonic()
        for r in ds.replicas:
            if r.state != RUNNING:
                continue
            if r.health_ref is None:
                if now - r.health_sent_at >= ds.config.health_check_period_s:
                    r.health_ref = r.actor.check_health.remote()
                    r.health_sent_at = now
                continue
            ready, _ = ray_tpu.wait([r.health_ref], num_returns=1, timeout=0)
            if ready:
                try:
                    ray_tpu.get(r.health_ref)
                    r.consecutive_failures = 0
                except Exception as e:
                    from ray_tpu.core.exceptions import ActorDiedError
                    from ray_tpu.serve.resilience import unwrap

                    # A DEAD actor is not a flaky health check: skip the
                    # 3-strikes grace and replace it now — every second of
                    # grace is a second of routers retrying into a corpse.
                    if isinstance(unwrap(e), ActorDiedError):
                        r.consecutive_failures = \
                            ds.config.max_consecutive_health_failures
                    else:
                        r.consecutive_failures += 1
                r.health_ref = None
            elif now - r.health_sent_at > ds.config.health_check_timeout_s:
                r.consecutive_failures += 1
                r.health_ref = None
            if r.consecutive_failures >= ds.config.max_consecutive_health_failures:
                ds.message = f"replica {r.replica_id} failed health checks"
                self._stop_replica(ds, r, force=True)

    def _collect_prefix_state(self, ds: _DeploymentState) -> None:
        """Poll each RUNNING replica's router_meta() on a cadence and stash
        its prefix-cache chain hashes on the replica record; _running_infos
        piggybacks them on the long-poll snapshot (KV-block-aware routing,
        serve/prefix.py). Non-blocking like the health checks: one
        outstanding probe per replica, collected on a later pass. A replica
        that answers None once (no router_prefix_blocks on the callable) is
        marked incapable and never polled again."""
        from ray_tpu.utils.config import get_config

        period = float(getattr(get_config(),
                               "serve_prefix_publish_period_s", 0.5))
        if period <= 0 or ds.deleting:
            return
        now = time.monotonic()
        for r in ds.replicas:
            if r.state != RUNNING or r.prefix_capable is False:
                continue
            if r.prefix_ref is None:
                if now - r.prefix_sent_at >= period:
                    try:
                        r.prefix_ref = r.actor.router_meta.remote()
                        r.prefix_sent_at = now
                    except Exception:  # noqa: BLE001 - replica racing away
                        pass
                continue
            ready, _ = ray_tpu.wait([r.prefix_ref], num_returns=1, timeout=0)
            if ready:
                meta, answered = None, True
                try:
                    meta = ray_tpu.get(r.prefix_ref)
                except Exception:  # noqa: BLE001 - health checks own
                    answered = False  # replica-death handling; retry later
                r.prefix_ref = None
                if not answered:
                    # Transient RPC failure is NOT a "doesn't publish"
                    # answer — marking incapable here would blind every
                    # router to this replica's cache for its lifetime.
                    continue
                if meta is None:
                    if r.prefix_capable is None:
                        r.prefix_capable = False
                    continue
                r.prefix_capable = True
                r.prefix_blocks = tuple(meta.get("blocks") or ())
                r.prefix_block = int(meta.get("block") or 0)
            elif now - r.prefix_sent_at > 10.0:
                r.prefix_ref = None  # wedged probe: retry next period

    def _autoscale(self, ds: _DeploymentState) -> None:
        asc = ds.config.autoscaling_config
        if asc is None or ds.deleting:
            return
        now = time.monotonic()
        if now - ds.last_metric_pull >= asc.metrics_interval_s:
            ds.last_metric_pull = now
            refs = [r.actor.get_metrics.remote() for r in ds.replicas
                    if r.state == RUNNING]
            total = 0.0
            try:
                for m in ray_tpu.get(refs, timeout=2.0):
                    total += m["ongoing"]
            except Exception:
                return
            ds.total_ongoing = total
        cur = ds.autoscale_target or asc.min_replicas
        raw = math.ceil(ds.total_ongoing / max(asc.target_ongoing_requests, 1e-9))
        desired = max(asc.min_replicas, min(asc.max_replicas, raw))
        if desired == cur:
            ds.desired_since = None
            return
        if ds.desired_since is None or ds.desired_since[0] != desired:
            ds.desired_since = (desired, now)
            return
        delay = (asc.upscale_delay_s if desired > cur
                 else asc.downscale_delay_s)
        if now - ds.desired_since[1] >= delay:
            ds.autoscale_target = desired
            ds.desired_since = None

    def _scale_and_roll(self, ds: _DeploymentState, target: int) -> None:
        live = [r for r in ds.replicas if r.state in (STARTING, RUNNING)]
        current_version = [r for r in live if r.version == ds.version]
        old_version = [r for r in live if r.version != ds.version]

        # Scale up with current-version replicas (also drives rolling
        # updates: new version starts first, old stops as new turn RUNNING).
        while len(current_version) < target:
            rep = self._start_replica(ds)
            if rep is None:  # PG creation / actor registration failed
                break        # ds.message set; next reconcile pass retries
            current_version.append(rep)

        running_new = sum(1 for r in current_version if r.state == RUNNING)
        # Retire old-version replicas as replacements come up.
        for r in list(old_version):
            if running_new > 0:
                self._stop_replica(ds, r)
                running_new -= 1

        # Scale down extras (prefer STARTING ones).
        extras = len(current_version) - target
        if extras > 0:
            victims = sorted(current_version,
                             key=lambda r: 0 if r.state == STARTING else 1)
            for r in victims[:extras]:
                self._stop_replica(ds, r)

    def _stop_replica(self, ds: _DeploymentState, r: _Replica,
                      force: bool = False) -> None:
        if r.state == STOPPING:
            return
        was_running = r.state == RUNNING
        r.state = STOPPING
        if r.actor is None:  # PG-pending replica: nothing to kill/drain
            self._release_pg(r)
            r.stop_deadline = 0.0
            return
        if force or not was_running:
            try:
                ray_tpu.kill(r.actor)
            except Exception:
                pass
            r.stop_deadline = 0.0  # reap immediately
        else:
            # Drain in-flight requests, then kill once drained/timed out.
            timeout = ds.config.graceful_shutdown_timeout_s
            r.drain_ref = r.actor.prepare_for_shutdown.remote(timeout)
            r.stop_deadline = time.monotonic() + timeout + 1.0

    def _reap_stopped(self, ds: _DeploymentState) -> None:
        keep = []
        now = time.monotonic()
        for r in ds.replicas:
            if r.state != STOPPING:
                keep.append(r)
                continue
            if r.drain_ref is not None:
                done, _ = ray_tpu.wait([r.drain_ref], num_returns=1, timeout=0)
                if not done and now < r.stop_deadline:
                    keep.append(r)
                    continue
                try:
                    ray_tpu.kill(r.actor)
                except Exception:
                    pass
            # else: already killed; drop the record
            self._release_pg(r)
        ds.replicas = keep
