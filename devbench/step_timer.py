"""Dev harness: time one train step config on the real TPU chip."""
import argparse, functools, time, sys
import jax, jax.numpy as jnp, numpy as np, optax

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train.spmd import make_llama_train_step

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--attn", default="flash")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--profile", default="")
    ap.add_argument("--remat", default="full")
    args = ap.parse_args()

    cfg = LlamaConfig(
        vocab_size=32128, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        max_seq_len=args.seq, tie_embeddings=True, dtype="bfloat16")
    n_params = cfg.num_params()
    mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])
    step_fn, init_state, shard = make_llama_train_step(
        cfg, mesh, attn_impl=args.attn, remat=args.remat != "none")
    state = init_state()
    rng = np.random.default_rng(0)
    tokens = shard(rng.integers(0, cfg.vocab_size, (args.batch, args.seq), dtype=np.int32))
    targets = shard(rng.integers(0, cfg.vocab_size, (args.batch, args.seq), dtype=np.int32))

    t0=time.time()
    state, m = step_fn(state, tokens, targets)
    jax.block_until_ready(m["loss"]); print(f"compile+1st: {time.time()-t0:.1f}s", flush=True)
    for _ in range(args.warmup):
        state, m = step_fn(state, tokens, targets)
    jax.block_until_ready(m["loss"])
    if args.profile:
        jax.profiler.start_trace(args.profile)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = step_fn(state, tokens, targets)
    jax.block_until_ready(m["loss"])
    dt = (time.perf_counter() - t0) / args.steps
    if args.profile:
        jax.profiler.stop_trace()
    toks = args.batch * args.seq / dt
    flops = 6 * n_params * toks
    print(f"batch={args.batch} seq={args.seq} attn={args.attn}: {dt*1e3:.1f} ms/step, "
          f"{toks:,.0f} tok/s, {flops/1e12:.1f} TFLOP/s (6N), vs_baseline={flops/1.59e14:.3f}, "
          f"loss={float(m['loss']):.3f}", flush=True)

if __name__ == "__main__":
    main()
