"""R3 fixture: blocking calls on the event loop, incl. the PR-5
jax-backend-init hazard.

PR-5's profiler originally called ``jax.devices()`` from a process that
had merely imported jax — initializing a TPU backend (seconds of work,
and the WRONG process to own the devices) from a loop-side snapshot
handler. Plus the classic trio: ``time.sleep``, sync ``RpcClient.call``,
and file I/O inside ``async def``."""

import time

import jax

import ray_tpu


class SnapshotHandler:
    def __init__(self, rpc_client):
        self._client = rpc_client

    async def handle_snapshot(self, conn):
        # BUG (PR-5): may initialize the jax backend on the loop.
        devices = jax.devices()
        # BUG: parks the whole event loop.
        time.sleep(0.5)
        # BUG: sync RPC round-trip on the loop (use the async client).
        info = self._client.call("get_info")
        # BUG: sync object fetch on the loop.
        payload = ray_tpu.get(info["ref"])
        # BUG: blocking file I/O on the loop.
        with open("/tmp/snapshot.json", "w") as f:
            f.write(str((devices, payload)))
