"""Head-side health watchdog: ingest -> detect -> capture evidence, always on.

The loop closes what the pull-based surfaces (PR 1 /metrics + flight
recorder, PR 5 on-demand profiler) leave open: nobody is watching 1000
nodes by hand, so the cluster must notice its own regressions and grab the
perishable evidence (stacks, series windows, queue states) WHILE the
incident is live. Three stages:

1. **ingest** — every ``report_telemetry`` push hands its delta-encoded
   series payload here; samples land in the bounded
   :class:`~ray_tpu.observability.timeseries.SeriesStore` and flow straight
   through the streaming detectors (O(1) per sample). The head's own
   heartbeat table is sampled into ``node_heartbeat_gap_s`` series by the
   loop, so heartbeat jitter is watched without any reporter cooperation.
2. **detect** — :mod:`~ray_tpu.observability.detectors` rules with warmup/
   debounce/per-rule-cooldown fire :class:`Trip`s into a small queue.
3. **evidence** — the loop assembles each trip into an *incident*: the
   implicated entity (train trips reuse PR-5 straggler attribution; others
   implicate the offending series' reporter), the offending series window,
   a flight-recorder bundle, and a *targeted* profiler capture scoped to
   the implicated node over the PR-5 ``profile_node`` RPC — under hard
   guardrails (concurrent-capture cap, per-node cooldown, lifetime budget)
   so the watchdog can never become the thing that melts a sick cluster.

Incidents are a bounded deque surfaced through the state API
(``incidents``/``timeseries``), the CLI (``incidents``, ``watch``) and the
dashboard (``/api/incidents``, ``/api/timeseries``). Self-metrics:
``watchdog_incidents_total{rule}``, ``watchdog_eval_seconds``,
``watchdog_dropped_samples``.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid
from collections import deque

from ray_tpu.devtools.annotations import loop_confined
from ray_tpu.observability.detectors import Rule, Trip, build_rules
from ray_tpu.observability.timeseries import SeriesKey, SeriesStore
from ray_tpu.utils.config import get_config

_PENDING_MAX = 16  # trips queued for assembly; floods drop (counted)
# Hang bound on one targeted capture beyond the capture window itself
# (daemon fan-out + worker RTT); a dead daemon usually fails fast with a
# connect error — this is the backstop for a WEDGED one.
CAPTURE_RPC_SLACK_S = 20.0

_wd_metrics = None


def _get_wd_metrics():
    global _wd_metrics
    if _wd_metrics is None:
        from ray_tpu.util.metrics import Counter

        _wd_metrics = {
            "incidents": Counter(
                "watchdog_incidents_total",
                "incidents the health watchdog opened, by rule",
                tag_keys=("rule",)),
            "eval_seconds": Counter(
                "watchdog_eval_seconds",
                "cumulative wall time spent in watchdog ingest+eval "
                "(duty-cycle numerator on the head)"),
            "dropped": Counter(
                "watchdog_dropped_samples",
                "samples dropped at ingest (unknown sid / series cap / "
                "trip-queue overflow)"),
        }
    return _wd_metrics


@loop_confined
class Watchdog:
    """``train_stats_fn``/``nodes_fn`` are synchronous reads of the head's
    tables; ``profile_fn(node_id, seconds)`` is an awaitable returning the
    PR-5 ``profile_node`` result for ONE node. Injectable so incident
    assembly is unit-testable without a cluster."""

    def __init__(self, train_stats_fn=None, nodes_fn=None, profile_fn=None,
                 cfg=None, rules: list[Rule] | None = None,
                 store: SeriesStore | None = None, exemplars_fn=None):
        cfg = cfg or get_config()
        self.cfg = cfg
        self.store = store or SeriesStore(
            max_points=cfg.watchdog_series_samples,
            max_series=cfg.watchdog_series_max)
        self.rules = rules if rules is not None else build_rules(cfg)
        self._train_stats_fn = train_stats_fn or (lambda: {})
        self._nodes_fn = nodes_fn or (lambda: {})
        self._profile_fn = profile_fn
        # exemplars_fn(metric, deployment) -> [(trace_id, value, ts)]: the
        # head's SLO-exemplar stash, linking a tripped serve rule straight
        # to kept traces. Optional — incidents omit the field without it.
        self._exemplars_fn = exemplars_fn
        self.incidents: deque = deque(maxlen=cfg.watchdog_max_incidents)
        self._pending: deque = deque()
        self._hb_last: dict[str, float] = {}
        self._node_capture_ts: dict[str, float] = {}
        self._captures_inflight = 0
        self.captures_done = 0
        self.eval_s = 0.0
        self._dropped_trips = 0
        self._store_dropped_seen = 0
        self._task: asyncio.Task | None = None
        self._updated_buf: list = []  # reused per ingest (no per-push alloc)

    # ------------------------------------------------------------- ingest
    def ingest(self, source: str, node_id: str, payload: dict) -> bool:
        """Called from the head's ``_report_telemetry`` handler. Returns
        True when the reporter must resync its series declarations."""
        t0 = time.perf_counter()
        try:
            updated = self._updated_buf
            updated.clear()
            resync = self.store.ingest(source, node_id, payload,
                                       updated=updated)
            if self.store.dropped != self._store_dropped_seen:
                delta = self.store.dropped - self._store_dropped_seen
                self._store_dropped_seen = self.store.dropped
                try:
                    _get_wd_metrics()["dropped"].inc(delta)
                except Exception:
                    pass
            for series, ts, value in updated:
                self._detect(series, ts, value)
            updated.clear()
            return resync
        finally:
            self._spend(time.perf_counter() - t0)

    def _detect(self, series, ts: float, value: float) -> None:
        for rule in self.rules:
            if not rule.matches(series.key.name):
                continue
            trip = rule.update(series, ts, value)
            if trip is not None:
                if len(self._pending) >= _PENDING_MAX:
                    self._dropped_trips += 1
                    try:
                        _get_wd_metrics()["dropped"].inc()
                    except Exception:
                        pass
                    continue
                self._pending.append(trip)

    def _spend(self, dt: float) -> None:
        self.eval_s += dt
        try:
            _get_wd_metrics()["eval_seconds"].inc(dt)
        except Exception:
            pass

    def drop_source(self, source: str) -> None:
        """Evict one reporter everywhere: store rings AND every rule's
        per-series detector state (worker churn on an always-on head must
        not grow either without bound)."""
        self.store.drop_source(source)
        for rule in self.rules:
            rule.drop_source(source)

    # ------------------------------------------------------ heartbeat feed
    def observe_heartbeats(self) -> None:
        """Sample per-node heartbeat gaps into the store (head-local: the
        gap between consecutive heartbeats as the head saw them). Fed by
        the loop each tick; the jitter rule does the judging.

        A FULLY stalled heartbeat must not be invisible: while a node is
        silent past one health period, each tick also samples the
        gap-SO-FAR (now - last heartbeat, a rising value) — so the jitter
        rule trips while the daemon is still wedged, inside the gray zone
        before heartbeat aging declares the node dead. Waiting for the
        next heartbeat to measure the gap would capture the evidence only
        after the incident ended."""
        t0 = time.perf_counter()
        try:
            nodes = self._nodes_fn() or {}
            for gone in [nid for nid in self._hb_last if nid not in nodes]:
                self._hb_last.pop(gone, None)
                key = SeriesKey(source="head", name="node_heartbeat_gap_s",
                                tags=(("node", gone),))
                self.store.drop_key(key)
                for rule in self.rules:
                    rule.drop_key(key)
            try:
                stall_floor = 2.0 * get_config().health_check_period_s
            except Exception:
                stall_floor = 2.0
            now_mono = time.monotonic()
            for node_id, info in nodes.items():
                hb = getattr(info, "last_heartbeat", None)
                alive = getattr(info, "alive", True)
                if hb is None or hb <= 0 or not alive:
                    continue
                prev = self._hb_last.get(node_id)
                self._hb_last[node_id] = hb
                if prev is None:
                    continue
                if hb > prev:
                    gap = hb - prev
                elif now_mono - hb > stall_floor:
                    gap = now_mono - hb  # silent node: gap-so-far, rising
                    self._hb_last[node_id] = prev  # keep the real base
                else:
                    continue
                updated: list = []
                self.store.append("head", "node_heartbeat_gap_s",
                                  {"node": node_id}, gap,
                                  node_id=node_id, updated=updated)
                for series, ts, value in updated:
                    self._detect(series, ts, value)
        finally:
            self._spend(time.perf_counter() - t0)

    # --------------------------------------------------------------- loop
    def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self._task is None or self._task.done():
            self._task = loop.create_task(self.run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.watchdog_eval_interval_s)
            try:
                self.observe_heartbeats()
                while self._pending:
                    trip = self._pending.popleft()
                    await self._assemble(trip)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # the watchdog must never take the head down

    # ----------------------------------------------------------- evidence
    async def _assemble(self, trip: Trip) -> dict:
        """One incident: attribution + series window + flight record +
        targeted profile. Every leg is best-effort and bounded — a dead
        implicated worker yields partial evidence, never a hang."""
        t0 = time.perf_counter()
        key = trip.series.key
        incident = {
            "id": uuid.uuid4().hex[:12],
            "ts": trip.ts,
            "wall_ts": time.time(),
            "rule": trip.rule,
            "kind": trip.kind,
            "reason": trip.reason,
            "value": trip.value,
            "baseline": trip.baseline,
            "series": {"name": key.name, "tags": key.tag_dict(),
                       "source": key.source,
                       "node_id": trip.series.node_id},
        }
        implicated = self._implicate(trip)
        incident["implicated"] = implicated
        incident["window"] = self.store.window(key, seconds=120.0,
                                               max_points=240)
        incident["related"] = self._related(trip)
        if self._exemplars_fn is not None:
            # Metrics→traces: recent exemplar trace ids for the tripped
            # metric (scoped to its deployment tag when present) — each id
            # resolves via ``ray_tpu trace <id>`` / /api/traces.
            try:
                rows = self._exemplars_fn(
                    key.name, key.tag_dict().get("deployment", "")) or []
                if rows:
                    incident["exemplar_traces"] = [
                        {"trace_id": r[0], "value": r[1], "ts": r[2]}
                        for r in rows[-4:]]
            except Exception:
                pass  # exemplars are a hint — never block assembly
        self._spend(time.perf_counter() - t0)

        # Flight record: head-side bundle carrying the incident context
        # (record() detects the running loop and stays local — no RPC).
        try:
            from ray_tpu.core import flight_recorder

            incident["flight_record"] = flight_recorder.record(
                "watchdog_incident", reason=trip.reason,
                node_id=implicated.get("node_id") or "",
                extra={"incident_id": incident["id"], "rule": trip.rule,
                       "series": incident["series"],
                       "implicated": implicated,
                       "window_tail": incident["window"][-32:]})
        except Exception:
            incident["flight_record"] = None

        incident["profile"] = await self._auto_capture(
            incident["id"], implicated.get("node_id") or "")
        incident["assembly_s"] = round(time.perf_counter() - t0, 4)
        self.incidents.append(incident)
        try:
            _get_wd_metrics()["incidents"].inc(tags={"rule": trip.rule})
        except Exception:
            pass
        return incident

    def _implicate(self, trip: Trip) -> dict:
        """The entity an operator would restart. Train trips reuse the
        PR-5 straggler attribution (the slow RANK's host, not the victim
        ranks waiting at the allreduce); everything else implicates the
        offending series' reporter."""
        key = trip.series.key
        out = {"node_id": trip.series.node_id, "source": key.source,
               "detail": ""}
        if trip.kind == "train":
            # The offending series already names the rank (its tag); the
            # straggler report can only sharpen that — its rolling-window
            # MEDIAN lags a fresh regression by half the window, so it
            # often hasn't flagged anyone yet at trip time.
            rank_tag = key.tag_dict().get("rank")
            if rank_tag is not None:
                try:
                    out["rank"] = int(rank_tag)
                except ValueError:
                    pass
            try:
                from ray_tpu.profiling.straggler import build_report

                report = build_report(self._train_stats_fn() or {},
                                      threshold=1.15)
                if report.get("lagging_host"):
                    out["node_id"] = report["lagging_host"]
                    out["rank"] = report.get("lagging_rank")
                    st = next((w for w in report.get("stragglers", [])
                               if w.get("rank") == out.get("rank")), None)
                    if st:
                        out["source"] = st.get("source", out["source"])
                        out["detail"] = st.get("cause", "")
            except Exception:
                pass
        elif trip.kind == "node":
            out["node_id"] = key.tag_dict().get(
                "node", trip.series.node_id)
        return out

    def _related(self, trip: Trip, max_series: int = 6) -> list[dict]:
        """A few sibling series from the same reporter — the queue depth
        next to the p99 spike, the RSS next to the step drift."""
        key = trip.series.key
        out = []
        for series in self.store.series():
            if series.key.source != key.source or series.key == key:
                continue
            pts = self.store.window(series.key, seconds=120.0,
                                    max_points=60)
            if not pts:
                continue
            out.append({"name": series.key.name,
                        "tags": series.key.tag_dict(), "points": pts})
            if len(out) >= max_series:
                break
        return out

    async def _auto_capture(self, incident_id: str, node_id: str) -> dict:
        """Targeted profiler capture scoped to the implicated node, under
        hard guardrails. Returns a summary dict; the full capture payload
        is written under <temp_dir>/watchdog/ (an incident row must stay
        cheap to list)."""
        cfg = self.cfg
        if not cfg.watchdog_auto_capture or self._profile_fn is None:
            return {"status": "skipped: auto-capture disabled"}
        if not node_id:
            return {"status": "skipped: no implicated node"}
        if self._captures_inflight >= cfg.watchdog_max_auto_captures:
            return {"status": "skipped: concurrent capture cap"}
        if self.captures_done >= cfg.watchdog_capture_budget:
            return {"status": "skipped: capture budget exhausted"}
        now = time.monotonic()
        last = self._node_capture_ts.get(node_id)
        if last is not None and \
                now - last < cfg.watchdog_capture_cooldown_s:
            return {"status": f"skipped: node cooldown "
                              f"({cfg.watchdog_capture_cooldown_s}s)"}
        nodes = self._nodes_fn() or {}
        info = nodes.get(node_id)
        if info is not None and not getattr(info, "alive", True):
            return {"status": "skipped: implicated node is dead"}
        self._node_capture_ts[node_id] = now
        self._captures_inflight += 1
        try:
            res = await asyncio.wait_for(
                self._profile_fn(node_id, cfg.watchdog_capture_seconds),
                timeout=cfg.watchdog_capture_seconds + CAPTURE_RPC_SLACK_S)
        except Exception as e:  # noqa: BLE001 - partial evidence wins
            return {"status": f"error: {type(e).__name__}: {e}"}
        finally:
            self._captures_inflight -= 1
        self.captures_done += 1
        captures = (res or {}).get("captures") or []
        summary = {
            "status": "captured",
            "node_id": node_id,
            "captures": len(captures),
            "samples": sum(int(c.get("samples", 0)) for c in captures),
            "errors": (res or {}).get("errors") or {},
        }
        try:
            d = os.path.join(get_config().temp_dir, "watchdog")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"incident-{incident_id}-profile.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(res, f, default=str)
            os.replace(tmp, path)
            summary["path"] = path
        except Exception:
            pass
        return summary

    # -------------------------------------------------------------- events
    def record_event(self, rule: str, reason: str,
                     detail: dict | None = None) -> dict:
        """Open a lightweight incident WITHOUT a detector trip — control-
        plane lifecycle events (``head_restart``) that belong on the same
        timeline as the anomalies they may explain. No series window, no
        targeted profile; still counted in ``watchdog_incidents_total``
        and dumped as a flight-recorder bundle."""
        incident = {
            "id": uuid.uuid4().hex[:12],
            "ts": time.monotonic(),
            "wall_ts": time.time(),
            "rule": rule,
            "kind": "control",
            "reason": reason,
            "value": None,
            "baseline": None,
            "series": None,
            "implicated": dict(detail or {}),
            "window": [],
            "related": [],
            "profile": {"status": "skipped: lifecycle event"},
            "flight_record": None,
            "assembly_s": 0.0,
        }
        try:
            from ray_tpu.core import flight_recorder

            incident["flight_record"] = flight_recorder.record(
                "watchdog_incident", reason=reason,
                extra={"incident_id": incident["id"], "rule": rule,
                       "detail": dict(detail or {})})
        except Exception:
            pass
        self.incidents.append(incident)
        try:
            _get_wd_metrics()["incidents"].inc(tags={"rule": rule})
        except Exception:
            pass
        return incident

    # -------------------------------------------------------------- reads
    def list_incidents(self, since: float = 0.0, limit: int = 100,
                       incident_id: str | None = None) -> list[dict]:
        rows = [i for i in self.incidents
                if i["wall_ts"] >= since
                and (incident_id is None or i["id"] == incident_id)]
        return rows[-max(1, int(limit)):]

    def status(self) -> dict:
        return {
            "enabled": True,
            "rules": [r.name for r in self.rules],
            "incidents": len(self.incidents),
            "pending_trips": len(self._pending),
            "captures_done": self.captures_done,
            "eval_seconds": round(self.eval_s, 4),
            "dropped_trips": self._dropped_trips,
            "store": self.store.stats(),
        }
