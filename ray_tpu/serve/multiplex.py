"""Model multiplexing: many models share one replica pool.

Capability parity with the reference's multiplexing (reference:
python/ray/serve/multiplex.py — @serve.multiplexed wraps a model loader
with a per-replica LRU; handle.options(multiplexed_model_id=...) routes the
request to a replica likely to hold the model;
serve.get_multiplexed_model_id() reads the id inside the replica): routing
affinity rides the router's rendezvous-hash route_hint, so every handle
independently maps one model id to the same replica.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import threading
from collections import OrderedDict
from typing import Any, Callable

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rtpu_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (empty when the request
    carried none) — call inside replica code."""
    return _current_model_id.get()


def _set_multiplexed_model_id(model_id: str):
    return _current_model_id.set(model_id)


class _ModelCache:
    """Per-replica LRU of loaded models with optional per-model teardown.
    Loads are single-flight: concurrent first requests for one model id
    wait on the leader's load instead of loading twice (two simultaneous
    copies of an LLM-sized model would blow memory, and the displaced
    duplicate's teardown would never run)."""

    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self._models: OrderedDict[str, Any] = OrderedDict()
        self._loading: dict[str, threading.Event] = {}
        self._lock = threading.Lock()

    def get(self, owner, model_id: str):
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                ev = self._loading.get(model_id)
                if ev is None:
                    ev = threading.Event()
                    self._loading[model_id] = ev
                    break  # we are the loader
            ev.wait(timeout=600)  # follower: retry once the leader finishes
        try:
            model = self.loader(owner, model_id)
            if asyncio.iscoroutine(model):
                model = asyncio.run(model)
        except BaseException:
            with self._lock:
                self._loading.pop(model_id, None)
            ev.set()  # unblock followers; they retry and re-lead
            raise
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            self._loading.pop(model_id, None)
            while len(self._models) > self.max_models:
                _mid, evicted = self._models.popitem(last=False)
                del_fn = getattr(evicted, "__del_multiplexed_model__", None)
                if callable(del_fn):
                    try:
                        del_fn()
                    except Exception:
                        pass
        ev.set()
        return model

    def loaded_ids(self) -> list[str]:
        with self._lock:
            return list(self._models)


def multiplexed(func: Callable | None = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a replica method that loads a model by id; calls hit a
    per-replica LRU (evicting least-recently-used beyond the cap)."""

    def deco(loader: Callable):
        attr = f"_rtpu_mux_cache_{loader.__name__}"

        @functools.wraps(loader)
        def wrapper(self, model_id: str | None = None):
            # Cache created lazily PER replica instance: the class body is
            # cloudpickled to replicas, and a decoration-time cache would
            # embed an unpicklable lock in it.
            cache = getattr(self, attr, None)
            if cache is None:
                cache = _ModelCache(loader, max_num_models_per_replica)
                setattr(self, attr, cache)
            mid = model_id if model_id is not None \
                else get_multiplexed_model_id()
            if not mid:
                raise ValueError(
                    "no model id: pass one or call through "
                    "handle.options(multiplexed_model_id=...)")
            return cache.get(self, mid)

        return wrapper

    if func is not None:
        return deco(func)
    return deco
