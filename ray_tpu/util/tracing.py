"""Distributed tracing: spans around every task/actor call, with context
propagated through task metadata.

Capability parity with the reference's tracing helper (reference:
python/ray/util/tracing/tracing_helper.py — _tracing_task_invocation wraps
submission, _inject_tracing_into_class wraps actor methods, _DictPropagator
:165 carries the context dict inside task metadata, enablement via
_enable_tracing :98): submission creates a client span whose context rides in
``TaskSpec.trace_ctx``; the executing worker opens a child span around the user
function. No OpenTelemetry dependency — spans land in an in-process buffer
exportable as dicts (same span fields an OTLP exporter would see) and into the
chrome timeline.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    kind: str  # "client" | "worker" | "internal"
    start_ts: float
    end_ts: float = 0.0
    status: str = "OK"
    attributes: dict = field(default_factory=dict)


_enabled = False
_ctx = threading.local()  # .trace_id, .span_id
_spans: deque[Span] = deque(maxlen=100_000)
_spans_total = 0  # monotone append count (flush cursor base)
_dropped_metered = 0  # drops already exported to the registry counter
_lock = threading.Lock()

_drop_metrics = None
_drop_metrics_lock = threading.Lock()


def _get_drop_metrics():
    """Lazy: the module must stay importable without the registry."""
    global _drop_metrics
    with _drop_metrics_lock:
        if _drop_metrics is None:
            from ray_tpu.util.metrics import Counter

            _drop_metrics = {
                "dropped": Counter(
                    "tracing_spans_dropped",
                    "finished spans silently discarded by this process's "
                    "bounded span buffer (deque wraparound / clear) — "
                    "nonzero means the timeline has holes"),
            }
        return _drop_metrics


def dropped_spans() -> int:
    """Spans this process has discarded (wraparound + clear), cumulative."""
    with _lock:
        return _spans_total - len(_spans)


def enable_tracing() -> None:
    """Turn span recording on for this process (reference: _enable_tracing)."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


def _new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


def current_context() -> tuple[str, str] | None:
    tid = getattr(_ctx, "trace_id", None)
    sid = getattr(_ctx, "span_id", None)
    return (tid, sid) if tid else None


def inject() -> dict | None:
    """Context dict to ship inside a TaskSpec (reference: _DictPropagator.inject)."""
    if not _enabled:
        return None
    cur = current_context()
    if cur is None:
        # Root: submitting from untraced code still starts a trace.
        return {"trace_id": _new_id(16), "parent_span_id": None}
    return {"trace_id": cur[0], "parent_span_id": cur[1]}


@contextlib.contextmanager
def span(name: str, kind: str = "internal", attributes: dict | None = None,
         ctx: dict | None = None):
    """Record a span; nests under the thread's current span unless ``ctx``
    (a propagated context) is given."""
    if not _enabled and ctx is None:
        yield None
        return
    if ctx is not None:
        trace_id = ctx.get("trace_id") or _new_id(16)
        parent_id = ctx.get("parent_span_id")
    else:
        cur = current_context()
        trace_id = cur[0] if cur else _new_id(16)
        parent_id = cur[1] if cur else None
    s = Span(
        trace_id=trace_id, span_id=_new_id(), parent_id=parent_id, name=name,
        kind=kind, start_ts=time.time(), attributes=dict(attributes or {}),
    )
    # Save the raw thread-local slots (not current_context(), which collapses
    # partial state to None): executor pool threads are reused across
    # unrelated work, and an inexact restore leaks this span's ids into the
    # next task that happens to land on the same thread.
    prev_tid = getattr(_ctx, "trace_id", None)
    prev_sid = getattr(_ctx, "span_id", None)
    _ctx.trace_id, _ctx.span_id = s.trace_id, s.span_id
    try:
        yield s
    except BaseException as e:
        s.status = f"ERROR: {type(e).__name__}"
        s.attributes["exception.type"] = type(e).__name__
        s.attributes["exception.message"] = str(e)
        raise
    finally:
        s.end_ts = time.time()
        _ctx.trace_id, _ctx.span_id = prev_tid, prev_sid
        global _spans_total
        with _lock:
            _spans.append(s)
            _spans_total += 1


def record_span(name: str, start_ts: float, end_ts: float,
                kind: str = "internal",
                attributes: dict | None = None) -> None:
    """Append an already-finished span (the goodput ledger lane: phase
    intervals are classified after the fact, so there is no ``with``
    block to wrap). No-op when tracing is off."""
    if not _enabled:
        return
    s = Span(
        trace_id=_new_id(16), span_id=_new_id(), parent_id=None, name=name,
        kind=kind, start_ts=float(start_ts), end_ts=float(end_ts),
        attributes=dict(attributes or {}),
    )
    global _spans_total
    with _lock:
        _spans.append(s)
        _spans_total += 1


@contextlib.contextmanager
def task_span(name: str, trace_ctx: dict | None, kind: str = "worker",
              attributes: dict | None = None):
    """Worker-side span around task execution; no-op unless the submitter
    propagated a context or this process has tracing on."""
    if trace_ctx is None and not _enabled:
        yield None
        return
    with span(name, kind=kind, attributes=attributes, ctx=trace_ctx) as s:
        yield s


def spans() -> list[Span]:
    with _lock:
        return list(_spans)


def export() -> list[dict]:
    return [asdict(s) for s in spans()]


def flush_new(cursor: int, limit: int = 2000) -> tuple[list[dict], int]:
    """Finished spans recorded since ``cursor`` as wire dicts, plus the new
    cursor. The telemetry flusher ships these to the head WITHOUT removing
    them locally (the in-process buffer stays useful for the flight recorder
    and local /api/traces); attribute values are stringified so the batch
    always survives msgpack. Bounded per call like the event flush
    (reference: task_event_buffer.h kMaxNumTaskEventsToFlush)."""
    import itertools

    global _dropped_metered
    with _lock:
        # _spans_total is monotone across clear() (cleared spans count as
        # dropped), so a caller's cursor can never exceed it and there is
        # no window where post-clear spans get skipped.
        dropped = _spans_total - len(_spans)
        start = max(0, min(cursor, _spans_total) - dropped)
        batch = list(itertools.islice(_spans, start, start + limit))
        new_cursor = dropped + start + len(batch)
        new_drops, _dropped_metered = \
            dropped - _dropped_metered, max(dropped, _dropped_metered)
    if new_drops > 0:
        # Surfaced on the flush path (every process with a telemetry
        # flusher calls it) so /metrics shows span loss without adding a
        # counter inc to the hot span-record path.
        try:
            _get_drop_metrics()["dropped"].inc(new_drops)
        except Exception:  # noqa: BLE001 - visibility must not break flush
            pass
    out = [{
        "trace_id": s.trace_id, "span_id": s.span_id,
        "parent_id": s.parent_id, "name": s.name, "kind": s.kind,
        "start_ts": s.start_ts, "end_ts": s.end_ts, "status": s.status,
        "attributes": {k: str(v) for k, v in s.attributes.items()},
    } for s in batch]
    return out, new_cursor


def clear() -> None:
    # _spans_total deliberately NOT reset: it is the monotone cursor base
    # for flush_new(), and cleared spans simply count as dropped.
    with _lock:
        _spans.clear()


# -- exporters --------------------------------------------------------------


def export_otlp() -> dict:
    """Spans in OTLP/JSON shape (resourceSpans → scopeSpans → spans) — the
    wire format OTel collectors ingest (reference: tracing_helper.py exports
    through opentelemetry SDK; here the structure is emitted directly so no
    SDK dependency is needed)."""
    def ns(ts: float) -> str:
        return str(int(ts * 1e9))

    otel_spans = []
    for s in spans():
        otel_spans.append({
            "traceId": s.trace_id,
            "spanId": s.span_id,
            "parentSpanId": s.parent_id or "",
            "name": s.name,
            "kind": {"client": 3, "worker": 2,
                     "internal": 1}.get(s.kind, 1),
            "startTimeUnixNano": ns(s.start_ts),
            "endTimeUnixNano": ns(s.end_ts),
            "status": {"code": 1 if s.status == "OK" else 2,
                       "message": s.status},
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in s.attributes.items()
            ],
        })
    return {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "ray_tpu"}}]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.tracing"},
                "spans": otel_spans,
            }],
        }]
    }


def save_otlp(path: str) -> str:
    import json

    with open(path, "w") as f:
        json.dump(export_otlp(), f)
    return path


@contextlib.contextmanager
def profile(logdir: str):
    """XLA profiler capture around a block: writes an xplane trace viewable
    in TensorBoard/XProf alongside a framework span (reference: SURVEY §5 —
    hooks to dump jax.profiler traces into the same timeline channel)."""
    import jax

    with span("jax.profile", attributes={"logdir": logdir}):
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
