"""Router: assigns requests to replicas (power-of-two-choices).

Capability parity with the reference's router (reference:
python/ray/serve/_private/router.py:510 Router.assign_request :1028 →
request_router/pow_2_router.py:27 PowerOfTwoChoicesRequestRouter
.choose_replicas :52 — sample two replicas, pick the one with the smaller
queue; requests queue router-side when all replicas are saturated), plus
the request-resilience layer (ray_tpu/serve/resilience.py):

- queue waits are bounded by the request's absolute deadline;
- admission control sheds with :class:`Overloaded` once
  ``max_queued_requests`` callers are parked (bounded queues, not
  unbounded latency);
- the choose loop never picks a draining replica, a replica the caller
  already tried (retry exclusion), or one whose circuit breaker is open;
- per-replica breakers track consecutive failures and latency outliers
  from the completion watcher, blacklist sick replicas with half-open
  recovery probes, and nudge the controller's health check on open.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable

import ray_tpu
from ray_tpu.serve.config import ReplicaInfo
from ray_tpu.serve.resilience import (
    DEADLINE_KEY,
    CircuitBreaker,
    DeadlineExceeded,
    Overloaded,
    ResilienceSettings,
)
from ray_tpu.util import tracing

_router_metrics = None
_router_metrics_lock = threading.Lock()


def _get_router_metrics():
    """Process-wide router metrics: admission wait, parked-caller depth,
    request count, and the resilience counters (shed/expired/retry/hedge/
    breaker) per deployment (reference: serve's
    ray_serve_num_router_requests / queued gauges). Lock-guarded creation:
    two racing first-requests must not register two metric objects and
    strand increments on the one the exporter can't see."""
    global _router_metrics
    with _router_metrics_lock:
        if _router_metrics is not None:
            return _router_metrics
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _router_metrics = {
            "queue_wait": Histogram(
                "serve_router_queue_wait_s",
                "time a request waited in the router for a replica slot",
                tag_keys=("deployment",)),
            "queue_depth": Gauge(
                "serve_router_queue_depth",
                "callers currently parked waiting for replica capacity",
                tag_keys=("deployment",)),
            "requests": Counter(
                "serve_router_requests_total",
                "requests assigned to replicas", tag_keys=("deployment",)),
            "retries": Counter(
                "serve_retries_total",
                "assignment retries after replica failure/rejection",
                tag_keys=("deployment",)),
            "hedges": Counter(
                "serve_hedges_total",
                "tail-hedge duplicate attempts launched",
                tag_keys=("deployment",)),
            "breaker_transitions": Counter(
                "serve_breaker_transitions_total",
                "circuit breaker open transitions",
                tag_keys=("deployment", "replica")),
            "breaker_open": Gauge(
                "serve_breaker_open_replicas",
                "replicas currently blacklisted by the circuit breaker",
                tag_keys=("deployment",)),
        }
    return _router_metrics


class Router:
    def __init__(self, deployment_name: str,
                 get_replicas: Callable[[], list[ReplicaInfo]],
                 report_unhealthy: Callable[[str, str], None] | None = None):
        self._deployment = deployment_name
        self._get_replicas = get_replicas
        self._inflight: dict[str, int] = {}  # replica_id -> local in-flight
        self._lock = threading.Lock()
        self._not_saturated = threading.Condition(self._lock)
        self._rng = random.Random()
        self._waiting = 0  # callers parked for capacity (queue-depth gauge)
        # Set by _choose_locked (under _lock) when the chosen replica's
        # admission consumed a half-open breaker probe slot; read by
        # assign_request immediately after, per request.
        self._choice_was_probe = False
        self._report_unhealthy = report_unhealthy
        self.settings = ResilienceSettings()
        self._settings_adopted = False
        self.breaker = CircuitBreaker(self.settings.breaker,
                                      on_open=self._on_breaker_open)

    # ------------------------------------------------------------ settings

    def _adopt_settings(self, replicas: list[ReplicaInfo]) -> None:
        """Adopt the deployment-level resilience settings riding the newest
        replica snapshot (cheap: dict identity check short-circuits)."""
        for r in replicas:
            s = getattr(r, "settings", None)
            if s is not None:
                if s is not getattr(self, "_last_settings_dict", None):
                    self._last_settings_dict = s
                    self.settings = ResilienceSettings.from_dict(s)
                    self.breaker.config = self.settings.breaker
                self._settings_adopted = True
                return

    def _on_breaker_open(self, replica_id: str, reason: str) -> None:
        mtr = _get_router_metrics()
        try:
            mtr["breaker_transitions"].inc(
                tags={"deployment": self._deployment, "replica": replica_id})
            mtr["breaker_open"].set(
                self.breaker.open_count(),
                tags={"deployment": self._deployment})
        except Exception:
            pass
        # Feed the controller's health check: a breaker trip means THIS
        # router has stopped routing there, but only the controller can
        # probe-and-replace a genuinely sick replica for everyone.
        if self._report_unhealthy is not None:
            try:
                self._report_unhealthy(replica_id, reason)
            except Exception:
                pass

    # ---------------------------------------------------------- data plane

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       timeout: float | None = None, stream: bool = False,
                       route_hint: str | None = None,
                       deadline: float | None = None,
                       exclude: set[str] | frozenset[str] | None = None,
                       no_park: bool = False):
        """Pick a replica (pow-2 on local in-flight counts), submit, and
        return ``(result, replica_id)`` where result is the ObjectRef (or
        ``(gen, on_done)`` when streaming). One attempt — retry/hedge loops
        live in the handle, which excludes already-tried replicas here.

        The wait for a replica slot is bounded by ``deadline`` (absolute
        wall clock; defaults to now + the deployment's request_timeout_s,
        or the legacy ``timeout`` argument when given). While every
        eligible replica is saturated the caller parks on a Condition that
        is notified on request completion and on replica-set changes — no
        sleep-poll — but only ``settings.max_queued_requests`` callers may
        park: beyond that, :class:`Overloaded` sheds the request
        immediately (admission control, reference: serve's
        max_queued_requests handle option).

        ``route_hint`` biases placement for cache locality: the same hint
        routes to the same replica while that replica's load stays within a
        bounded delta of the least-loaded one (reference: multiplexed-model
        routing + the prefix-aware policy — affinity-by-key with a balance
        threshold, so a shared system prompt can't pin a whole deployment
        to one replica)."""
        from ray_tpu.serve.resilience import shed_metrics

        mtr = _get_router_metrics()
        smtr = shed_metrics()
        dep_tag = {"deployment": self._deployment}
        t_enter = time.time()
        if deadline is None:
            budget = timeout if timeout is not None \
                else self.settings.request_timeout_s
            deadline = t_enter + budget
        with self._lock:
            parked = False
            try:
                while True:
                    replicas = self._get_replicas()
                    if replicas and not self._settings_adopted:
                        self._adopt_settings(replicas)
                    if replicas and exclude and all(
                            r.replica_id in exclude or
                            getattr(r, "draining", False)
                            for r in replicas):
                        # Retry exclusion covers every published replica:
                        # nothing a wake can change for THIS call — fail
                        # fast so the handle surfaces the original error
                        # instead of a full-budget park that also occupies
                        # an admission slot (a 0.5s retry-after shed must
                        # not become a 30s stall on a 1-replica app).
                        raise Overloaded(
                            f"{self._deployment!r}: every replica already "
                            f"tried by this request", retry_after_s=0.5,
                            where="router")
                    chosen = (self._choose_locked(replicas, route_hint,
                                                  exclude)
                              if replicas else None)
                    if chosen is not None:
                        is_probe = self._choice_was_probe
                        self._inflight[chosen.replica_id] = \
                            self._inflight.get(chosen.replica_id, 0) + 1
                        break
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        smtr["expired"].inc(tags={**dep_tag,
                                                  "where": "router"})
                        raise DeadlineExceeded(
                            f"no available replica for {self._deployment!r} "
                            f"within the request budget "
                            f"({deadline - t_enter:.1f}s)")
                    if not parked:
                        if no_park:
                            # Internal opportunistic assignment (hedging):
                            # take a free slot now or give up — a hedge
                            # that parks would add load exactly at
                            # saturation and block the caller's drive
                            # loop. Not counted as a shed: never
                            # user-visible.
                            raise Overloaded(
                                f"{self._deployment!r} has no free replica "
                                f"for an opportunistic assignment",
                                retry_after_s=0.0, where="router")
                        cap = self.settings.max_queued_requests
                        if cap >= 0 and self._waiting >= cap:
                            # Bounded router queue: shed instead of joining
                            # an unbounded wait (the client owns backoff).
                            smtr["shed"].inc(tags={**dep_tag,
                                                   "where": "router"})
                            raise Overloaded(
                                f"{self._deployment!r} router queue full "
                                f"({cap} waiting)",
                                retry_after_s=1.0, where="router")
                        parked = True
                        self._waiting += 1
                        mtr["queue_depth"].set(self._waiting, tags=dep_tag)
                    # Bounded wait: replica-set changes arrive via
                    # notify_replicas_changed(), completions via _release();
                    # the 0.5 s cap only covers lost-notify edge cases.
                    self._not_saturated.wait(timeout=min(remaining, 0.5))
            finally:
                if parked:
                    self._waiting -= 1
                    mtr["queue_depth"].set(self._waiting, tags=dep_tag)
        mtr["queue_wait"].observe(time.time() - t_enter, tags=dep_tag)
        mtr["requests"].inc(tags=dep_tag)

        # Propagate the budget: the replica drops the request if it expires
        # before execution starts (and exposes it to user code / batcher).
        kwargs = dict(kwargs)
        kwargs[DEADLINE_KEY] = deadline

        rid = chosen.replica_id
        try:
            handle = ray_tpu.get_actor(chosen.actor_name, namespace="serve")
        except Exception as e:
            # Replica vanished between the long-poll snapshot and submission:
            # give the slot back (a leaked increment would read as permanent
            # saturation), return any half-open probe slot, and count the
            # miss against the breaker. Surfaced as a NEVER-SENT actor death
            # (the request provably didn't reach any replica) carrying the
            # replica id, so the handle's retry loop can exclude it and
            # re-resolve onto a live sibling.
            from ray_tpu.core.exceptions import ActorDiedError

            self._release(rid)
            if is_probe:
                self.breaker.cancel_probe(rid)
            self.breaker.record_failure(rid)
            raise ActorDiedError(
                rid, f"replica {rid} vanished before submit: {e!r}",
                never_sent=True) from e
        if stream:
            try:
                # Client span around submission: inject() rides the
                # TaskSpec, so the replica's execution shows up as a child
                # of serve.request — one trace across processes.
                with tracing.span(f"serve.request.{self._deployment}",
                                  kind="client",
                                  attributes={"method": method_name,
                                              "replica": rid,
                                              "stream": "true"}):
                    gen = handle.handle_request_streaming.options(
                        num_returns="streaming").remote(
                            method_name, args, kwargs)
            except Exception:
                self._release(rid)
                if is_probe:
                    self.breaker.cancel_probe(rid)
                self.breaker.record_failure(rid)
                raise

            done = threading.Event()

            def on_stream_done():
                # In-flight until the consumer exhausts/abandons the stream
                # (keeps max_ongoing_requests honest for long-lived SSE).
                if not done.is_set():
                    done.set()
                    self._release(rid)
                    if is_probe:
                        # Settle this request's half-open probe slot if no
                        # outcome was recorded (abandoned stream): no-op
                        # once record_success/failure already moved the
                        # breaker out of half-open.
                        self.breaker.cancel_probe(rid)

            return (gen, on_stream_done), rid
        try:
            with tracing.span(f"serve.request.{self._deployment}",
                              kind="client",
                              attributes={"method": method_name,
                                          "replica": rid}):
                ref = handle.handle_request.remote(method_name, args, kwargs)
        except Exception:
            self._release(rid)
            if is_probe:
                self.breaker.cancel_probe(rid)
            self.breaker.record_failure(rid)
            raise

        t_submit = time.perf_counter()

        def _done():
            try:
                ray_tpu.wait([ref], num_returns=1, timeout=None,
                             fetch_local=False)
            finally:
                # Release the capacity the moment the replica is done:
                # _observe_outcome may still block on a local result
                # fetch (cluster mode, large payloads), and parked
                # callers must not wait out that fetch for a slot the
                # replica already freed.
                self._release(rid)
            latency = time.perf_counter() - t_submit
            outcome = None
            try:
                outcome = self._observe_outcome(ref)
            finally:
                if outcome is True:
                    self.breaker.record_success(rid, latency)
                elif outcome is False:
                    self.breaker.record_failure(rid)
                elif is_probe:
                    # Neutral (shed/expired/unknown): no health signal
                    # either way — but THIS request's half-open probe
                    # slot must be returned so the breaker doesn't wedge
                    # half-open (and a shed must NOT close the breaker
                    # on a still-sick replica). Only the probe request
                    # settles the slot: a non-probe neutral completion
                    # canceling it would over-admit probes.
                    self.breaker.cancel_probe(rid)
                self._refresh_breaker_gauge()
        threading.Thread(target=_done, daemon=True).start()
        return ref, rid

    def _observe_outcome(self, ref) -> bool | None:
        """Ternary outcome of the completed call: True = healthy answer,
        False = failure (infra or application), None = neutral — sheds and
        deadline expiries say nothing about replica health in EITHER
        direction (counting a fast shed as success would close a half-open
        breaker on a still-overloaded replica and seed its cleared latency
        window with bogus samples). The result is already local (actor
        replies land in the caller's store), so this get is cheap."""
        from ray_tpu.serve import resilience

        try:
            # Bounded get: in cluster mode the reply may still be a local
            # fetch away after wait(fetch_local=False); a timeout here is
            # "unknown" (neutral).
            ray_tpu.get(ref, timeout=5.0)
            return True
        except (resilience.Overloaded, resilience.DeadlineExceeded):
            return None
        except Exception as e:  # noqa: BLE001 - classify
            kind = resilience.classify(e)
            if kind in ("overloaded_replica", "overloaded_router",
                        "expired"):
                return None
            return False

    def _refresh_breaker_gauge(self) -> None:
        try:
            _get_router_metrics()["breaker_open"].set(
                self.breaker.open_count(),
                tags={"deployment": self._deployment})
        except Exception:
            pass

    # ----------------------------------------------------------- feedback

    def record_stream_outcome(self, replica_id: str, ok: bool,
                              latency_s: float | None = None) -> None:
        """Breaker feedback for streaming calls: the generator wrapper
        reports first-chunk success (with TTFT as the latency sample) or a
        mid-stream failure (the completion watcher can't see stream
        errors — they surface in the consumer)."""
        if ok:
            self.breaker.record_success(replica_id, latency_s or 0.0)
        else:
            self.breaker.record_failure(replica_id)
        self._refresh_breaker_gauge()

    def count_retry(self) -> None:
        try:
            _get_router_metrics()["retries"].inc(
                tags={"deployment": self._deployment})
        except Exception:
            pass

    def count_hedge(self) -> None:
        try:
            _get_router_metrics()["hedges"].inc(
                tags={"deployment": self._deployment})
        except Exception:
            pass

    def _release(self, replica_id: str) -> None:
        with self._lock:
            self._inflight[replica_id] -= 1
            self._not_saturated.notify_all()

    def notify_replicas_changed(self,
                                replicas: list[ReplicaInfo] | None = None
                                ) -> None:
        """Wake parked assign loops after a replica-set update (called from
        the long-poll callback in DeploymentHandle). With the new snapshot
        in hand, also adopt its settings and garbage-collect breaker state
        for replicas the controller no longer publishes."""
        if replicas is not None:
            self._adopt_settings(replicas)
            self.breaker.forget([r.replica_id for r in replicas])
        with self._lock:
            self._not_saturated.notify_all()

    # How far above the least-loaded replica a hint-preferred replica may
    # be before load balancing overrides cache locality.
    HINT_BALANCE_DELTA = 2

    def _eligible_locked(self, r: ReplicaInfo,
                         exclude) -> bool:
        if getattr(r, "draining", False):
            return False
        if exclude and r.replica_id in exclude:
            return False
        return not self.breaker.is_open(r.replica_id)

    def _choose_locked(self, replicas: list[ReplicaInfo],
                       route_hint: str | None = None,
                       exclude: set[str] | frozenset[str] | None = None
                       ) -> ReplicaInfo | None:
        """Pow-2 choice over the ELIGIBLE set: never a draining replica,
        never one the caller already tried, never one whose breaker is
        open (half-open admission happens below, via breaker.allow)."""
        self._choice_was_probe = False
        replicas = [r for r in replicas if self._eligible_locked(r, exclude)]
        if not replicas:
            return None
        if route_hint is not None:
            # Rendezvous hashing: every router maps the same hint to the
            # same replica without coordination — but only while the hinted
            # replica's load stays within HINT_BALANCE_DELTA of the
            # least-loaded replica. Beyond that, locality yields to pow-2
            # balancing (a deployment-wide shared prefix must not pin all
            # traffic to one replica while siblings idle).
            import zlib

            min_load = min(self._inflight.get(r.replica_id, 0)
                           for r in replicas)
            ranked = sorted(
                replicas,
                key=lambda r: zlib.crc32(
                    f"{route_hint}:{r.replica_id}".encode()),
            )
            for r in ranked:
                load = self._inflight.get(r.replica_id, 0)
                if load >= r.max_ongoing_requests:
                    continue
                if load - min_load <= self.HINT_BALANCE_DELTA:
                    ok, probe = self.breaker.allow_ex(r.replica_id)
                    if ok:
                        self._choice_was_probe = probe
                        return r
                    continue  # half-open and out of probe slots
                break  # hinted replica overloaded — balance instead
        candidates = (self._rng.sample(replicas, 2)
                      if len(replicas) >= 2 else list(replicas))
        best, best_load = None, None
        for r in candidates:
            load = self._inflight.get(r.replica_id, 0)
            if load >= r.max_ongoing_requests:
                continue
            if best_load is None or load < best_load:
                best, best_load = r, load
        if best is None:
            return None
        ok, probe = self.breaker.allow_ex(best.replica_id)
        if not ok:
            # Half-open with its probe budget spent: try the other pow-2
            # candidate; otherwise report saturation (the caller parks and
            # the breaker re-admits on the next wake).
            for r in candidates:
                if r.replica_id == best.replica_id:
                    continue
                load = self._inflight.get(r.replica_id, 0)
                if load < r.max_ongoing_requests:
                    ok2, probe2 = self.breaker.allow_ex(r.replica_id)
                    if ok2:
                        self._choice_was_probe = probe2
                        return r
            return None
        self._choice_was_probe = probe
        return best

    def metrics(self) -> dict[str, int]:
        with self._lock:
            return dict(self._inflight)
