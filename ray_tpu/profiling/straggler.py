"""Straggler attribution for training: who is slow, and why.

Input: the head's train-stats table — per-worker step-time/sync-time decile
summaries streamed with every telemetry push (train/session.py collects
them from ``session.report()`` call intervals; reference capability: the
Pathways paper's centralized attribution of per-step variance across
islands, PAPERS.md).

Output: workers ranked by median step time against the fleet median, each
attributed as compute-bound vs collective-wait-bound from its reported
compute/sync share, with the lagging HOST named (the telemetry row's
node_id) — the thing an operator actually restarts.

Attribution logic: in a synchronous data-parallel step the LAGGING worker
shows a high compute share and LOW collective-wait share (everyone else
waits for it at the allreduce); a worker showing high sync share is the
victim, not the cause. ``cause`` encodes exactly that reading.
"""

from __future__ import annotations

import time


def _median(xs: list[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def build_report(sources: dict, threshold: float = 1.15,
                 max_age_s: float = 300.0) -> dict:
    """``sources``: the head table ``{source: {node_id, ts, stats: {rank:
    {...}}}}`` (see HeadServer._report_telemetry). Returns the ranked
    report; ``threshold`` is the median-vs-fleet ratio above which a worker
    is flagged."""
    now = time.time()
    workers: list[dict] = []
    for source, row in (sources or {}).items():
        if now - float(row.get("ts", now)) > max_age_s:
            continue
        for rank, st in (row.get("stats") or {}).items():
            deciles = list(st.get("deciles") or [])
            workers.append({
                "rank": int(rank),
                "source": source,
                "node_id": row.get("node_id", ""),
                "steps": int(st.get("steps", 0)),
                "median_step_s": float(st.get("median_step_s") or
                                       (_median(deciles) if deciles else 0)),
                "p90_step_s": float(deciles[9]) if len(deciles) >= 10
                else 0.0,
                "deciles": deciles,
                "sync_share": st.get("sync_share"),
                "compute_share": st.get("compute_share"),
                "world_size": int(st.get("world_size", 0)),
            })
    if not workers:
        return {"fleet": {"workers": 0, "median_step_s": 0.0},
                "workers": [], "stragglers": [], "lagging_host": None}

    fleet_median = _median([w["median_step_s"] for w in workers]) or 1e-12
    known_sync = [w["sync_share"] for w in workers
                  if w["sync_share"] is not None]
    fleet_sync = (sum(known_sync) / len(known_sync)) if known_sync else None
    for w in workers:
        w["vs_fleet"] = w["median_step_s"] / fleet_median
        if w["vs_fleet"] < threshold:
            w["cause"] = "ok"
        elif w["sync_share"] is None or fleet_sync is None:
            w["cause"] = "slow (no sync/compute split reported)"
        elif w["sync_share"] <= fleet_sync:
            # Slow AND not waiting on collectives: this worker IS the drag.
            w["cause"] = "compute-bound (others wait on it)"
        else:
            w["cause"] = "collective-wait (victim of another straggler)"
    workers.sort(key=lambda w: -w["vs_fleet"])
    stragglers = [w for w in workers if w["vs_fleet"] >= threshold]
    # The lagging host: prefer a compute-bound straggler (the cause) over a
    # collective-wait one (a victim).
    lagging = next((w for w in stragglers
                    if w["cause"].startswith("compute")), None) or \
        (stragglers[0] if stragglers else None)
    return {
        "fleet": {
            "workers": len(workers),
            "median_step_s": fleet_median,
            "mean_sync_share": fleet_sync,
        },
        "threshold": threshold,
        "workers": workers,
        "stragglers": stragglers,
        "lagging_host": lagging["node_id"] if lagging else None,
        "lagging_rank": lagging["rank"] if lagging else None,
    }


def format_report(report: dict) -> str:
    """Human-readable table for the ``stragglers`` CLI verb."""
    fleet = report.get("fleet") or {}
    lines = [
        f"fleet: {fleet.get('workers', 0)} worker(s), median step "
        f"{fleet.get('median_step_s', 0.0) * 1e3:.1f} ms",
    ]
    rows = report.get("workers") or []
    if not rows:
        lines.append("(no train stats reported yet)")
        return "\n".join(lines)
    hdr = (f"{'rank':>4}  {'host':<12} {'median_ms':>9} {'p90_ms':>8} "
           f"{'vs_fleet':>8} {'sync%':>6}  cause")
    lines += [hdr, "-" * len(hdr)]
    for w in rows:
        sync = (f"{w['sync_share'] * 100:.0f}"
                if w.get("sync_share") is not None else "-")
        lines.append(
            f"{w['rank']:>4}  {w['node_id'][:12]:<12} "
            f"{w['median_step_s'] * 1e3:>9.1f} {w['p90_step_s'] * 1e3:>8.1f} "
            f"{w['vs_fleet']:>7.2f}x {sync:>6}  {w['cause']}")
    host = report.get("lagging_host")
    if host:
        lines.append(f"lagging host: {host} (rank {report['lagging_rank']})")
    else:
        lines.append("no straggler above threshold "
                     f"{report.get('threshold')}x")
    return "\n".join(lines)
