"""Head-side time-series store: bounded rolling history for hot-path series.

The always-on health watchdog needs *history* — "is this step time drifting"
is unanswerable from the head's latest-snapshot telemetry table. This store
keeps a bounded ring of ``(ts, value)`` points per series, fed by the
delta-encoded sample payloads the per-process telemetry flushers piggyback
on their existing ``report_telemetry`` pushes (reference capability: the
reference dashboard's Prometheus+Grafana pairing collapsed into the head —
no external TSDB, just enough rolling window for streaming detectors and
the `timeseries`/`watch` surfaces).

Series identity is ``(source, name, tags)``: the *source* (one per reporting
process, ``<node>:<pid>``) disambiguates same-named series from different
processes (two serve replicas both export ``serve_ttft_s:p99`` with the same
deployment tag), and the reporter's node_id rides along for attribution.

Wire format (one payload per telemetry push, built by
:class:`~ray_tpu.observability.sampler.SeriesSampler`)::

    {"t": 1699....2,                  # sample instant (reporter wall clock)
     "defs": [[sid, name, {tags}]],   # NEW series declared this push
     "s": [[sid, value], ...]}        # samples; sid -> defs sent earlier

``sid`` is a small per-reporter integer: a series' name+tags cross the wire
ONCE, every later sample is two numbers — this is the down-payment on
ROADMAP item 5's delta-based telemetry sync (1000 nodes re-shipping full
label sets every 500 ms is exactly the head-egress shape that item calls
out). A head that has forgotten a reporter's ids (restart, eviction)
answers with ``series_resync`` and the reporter re-declares on its next
flush.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field


@dataclass(frozen=True)
class SeriesKey:
    source: str
    name: str
    tags: tuple  # sorted (k, v) pairs

    def tag_dict(self) -> dict:
        return dict(self.tags)


@dataclass
class Series:
    key: SeriesKey
    node_id: str = ""
    points: deque = field(default_factory=deque)  # (ts, value)

    def latest(self) -> tuple[float, float] | None:
        return self.points[-1] if self.points else None


class SeriesStore:
    """Bounded per-series rings + per-source sid maps. Not thread-safe by
    itself — the head mutates it only from its asyncio loop."""

    def __init__(self, max_points: int = 360, max_series: int = 4096):
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self._series: dict[SeriesKey, Series] = {}
        # source -> {sid: (name, tags_tuple)}
        self._sids: dict[str, dict[int, tuple[str, tuple]]] = {}
        self.ingested = 0   # samples accepted
        self.dropped = 0    # samples dropped (unknown sid / series cap)

    # ------------------------------------------------------------- ingest
    def ingest(self, source: str, node_id: str, payload: dict,
               updated: list | None = None) -> bool:
        """Apply one wire payload. Returns True when the reporter must
        resync (it referenced a sid this store doesn't know — head restart
        or source eviction). ``updated``, when given, collects the
        (Series, ts, value) triples appended — the watchdog feeds them
        straight into its streaming detectors."""
        if not payload:
            return False
        sids = self._sids.setdefault(source, {})
        for row in payload.get("defs") or ():
            try:
                sid, name, tags = int(row[0]), str(row[1]), dict(row[2])
            except (TypeError, ValueError, IndexError):
                continue
            sids[sid] = (name, tuple(sorted(tags.items())))
        ts = float(payload.get("t") or time.time())
        # A reporter clock far in the future must not poison detector
        # ordering; trust it only within a minute of arrival.
        now = time.time()
        if not (now - 60.0 <= ts <= now + 60.0):
            ts = now
        resync = False
        for row in payload.get("s") or ():
            try:
                sid, value = int(row[0]), float(row[1])
            except (TypeError, ValueError, IndexError):
                continue
            ref = sids.get(sid)
            if ref is None:
                self.dropped += 1
                resync = True
                continue
            key = SeriesKey(source=source, name=ref[0], tags=ref[1])
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    self.dropped += 1
                    continue
                series = Series(key=key, node_id=node_id,
                                points=deque(maxlen=self.max_points))
                self._series[key] = series
            series.node_id = node_id or series.node_id
            series.points.append((ts, value))
            self.ingested += 1
            if updated is not None:
                updated.append((series, ts, value))
        return resync

    def append(self, source: str, name: str, tags: dict, value: float,
               node_id: str = "", ts: float | None = None,
               updated: list | None = None) -> None:
        """Direct head-side append (heartbeat-gap series, tests)."""
        key = SeriesKey(source=source, name=name,
                        tags=tuple(sorted((tags or {}).items())))
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_series:
                self.dropped += 1
                return
            series = Series(key=key, node_id=node_id,
                            points=deque(maxlen=self.max_points))
            self._series[key] = series
        ts = time.time() if ts is None else float(ts)
        series.points.append((ts, float(value)))
        self.ingested += 1
        if updated is not None:
            updated.append((series, ts, float(value)))

    def drop_source(self, source: str) -> None:
        """Forget a reporter: its sid map and series (dead workers must not
        pin ring memory forever)."""
        self._sids.pop(source, None)
        for key in [k for k in self._series if k.source == source]:
            self._series.pop(key, None)

    def drop_key(self, key: SeriesKey) -> None:
        """Forget ONE series (e.g. a removed node's heartbeat-gap ring)."""
        self._series.pop(key, None)

    # -------------------------------------------------------------- query
    def series(self) -> list[Series]:
        return list(self._series.values())

    def get(self, key: SeriesKey) -> Series | None:
        return self._series.get(key)

    def window(self, key: SeriesKey, seconds: float = 120.0,
               max_points: int | None = None) -> list[list[float]]:
        series = self._series.get(key)
        if series is None:
            return []
        cutoff = time.time() - seconds
        pts = [[ts, v] for ts, v in series.points if ts >= cutoff]
        if max_points and len(pts) > max_points:
            pts = pts[-max_points:]
        return pts

    def query(self, name: str | None = None, source: str | None = None,
              node_id: str | None = None, tags: dict | None = None,
              since: float = 0.0, max_points: int = 0,
              max_age_s: float = 0.0) -> list[dict]:
        """Filtered listing for the state API / dashboard / `watch` CLI.
        ``name`` matches exactly or as a prefix ending in ``*``.
        ``max_age_s`` > 0 keeps only points younger than that, judged
        against THIS store's clock — remote callers wanting a liveness
        window must use it rather than computing ``since`` from their own
        wall clock (client/head skew would blank or falsify the view)."""
        if max_age_s and max_age_s > 0:
            since = max(since, time.time() - max_age_s)
        out: list[dict] = []
        for series in self._series.values():
            key = series.key
            if name:
                if name.endswith("*"):
                    if not key.name.startswith(name[:-1]):
                        continue
                elif key.name != name:
                    continue
            if source and key.source != source:
                continue
            if node_id and series.node_id != node_id:
                continue
            if tags:
                have = key.tag_dict()
                if any(have.get(k) != str(v) for k, v in tags.items()):
                    continue
            pts = [[ts, v] for ts, v in series.points if ts >= since]
            if max_points and len(pts) > max_points:
                pts = pts[-max_points:]
            out.append({
                "name": key.name, "tags": key.tag_dict(),
                "source": key.source, "node_id": series.node_id,
                "points": pts,
            })
        out.sort(key=lambda r: (r["name"], r["source"]))
        return out

    def stats(self) -> dict:
        return {"series": len(self._series), "sources": len(self._sids),
                "ingested": self.ingested, "dropped": self.dropped,
                "max_points": self.max_points,
                "max_series": self.max_series}
