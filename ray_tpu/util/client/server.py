"""Client proxy server: hosts a driver-grade runtime on behalf of remote
thin clients (reference: python/ray/util/client/server/server.py — the
RayletServicer executes API calls against the real core worker and tracks
per-client object ownership, releasing it on disconnect)."""

from __future__ import annotations

import asyncio

from ray_tpu.core.cluster.protocol import RpcServer, ServerConnection
from ray_tpu.devtools.annotations import loop_confined
from ray_tpu.core.object_ref import ObjectRef, refcount_disabled
from ray_tpu.utils import serialization
from ray_tpu.utils.ids import ActorID, ObjectID


@loop_confined
class ClientServer:
    """One RpcServer fronting one ClusterRuntime. Each client connection
    gets a pin-set of ObjectRefs the server holds alive on its behalf."""

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 0):
        self.runtime = runtime
        self.rpc = RpcServer(host, port)
        r = self.rpc.register
        r("c_put", self._put)
        r("c_get", self._get)
        r("c_wait", self._wait)
        r("c_submit_task", self._submit_task)
        r("c_create_actor", self._create_actor)
        r("c_submit_actor_task", self._submit_actor_task)
        r("c_kill_actor", self._kill_actor)
        r("c_cancel", self._cancel)
        r("c_get_named_actor", self._get_named_actor)
        r("c_actor_is_alive", self._actor_is_alive)
        r("c_release", self._release)
        r("c_cluster_resources", self._cluster_resources)
        r("c_available_resources", self._available_resources)
        r("c_kv", self._kv)
        self.rpc.on_disconnect = self._client_gone
        # conn -> pinned ObjectIDs: one explicit local ref held in the
        # backend runtime's counter per client-visible object, released on
        # c_release or client disconnect (explicit — NOT via ObjectRef GC,
        # which binds to the process-global runtime).
        self._pins: dict[ServerConnection, dict[str, ObjectID]] = {}

    async def start(self):
        return await self.rpc.start()

    async def stop(self):
        await self.rpc.stop()

    def _client_gone(self, conn: ServerConnection) -> None:
        for oid in (self._pins.pop(conn, None) or {}).values():
            self.runtime.refs.remove_local_ref(oid)

    def _pin(self, conn, refs) -> None:
        pins = self._pins.setdefault(conn, {})
        for ref in refs:
            if ref.hex() not in pins:
                pins[ref.hex()] = ref.id
                self.runtime.refs.add_local_ref(ref.id)

    def _run(self, fn, *args):
        """Runtime calls block (store waits, RPCs); keep the loop free. Ref
        accounting is suppressed: refs materialized inside handlers are
        transport-only (pinning is explicit via the backend's counter)."""
        from ray_tpu.core.object_ref import refcount_disabled

        def wrapped():
            with refcount_disabled():
                return fn(*args)

        return asyncio.get_running_loop().run_in_executor(None, wrapped)

    # ---- handlers ----
    async def _put(self, conn, blob: bytes):
        value = serialization.deserialize(blob)
        ref = await self._run(self.runtime.put, value)
        self._pin(conn, [ref])
        return {"oid": ref.hex(), "owner": self.runtime.worker_id.hex()}

    async def _get(self, conn, oids: list[str], api_timeout: float | None):
        with refcount_disabled():
            refs = [ObjectRef(ObjectID.from_hex(h), self.runtime.worker_id)
                    for h in oids]

        def fetch():
            try:
                values = self.runtime.get(refs, timeout=api_timeout)
                return [{"blob": serialization.serialize(v)} for v in values]
            except BaseException as e:  # noqa: BLE001 - errors cross the wire
                return {"error": serialization.serialize(e)}

        return await self._run(fetch)

    async def _wait(self, conn, oids: list[str], num_returns: int,
                    api_timeout: float | None):
        with refcount_disabled():
            refs = [ObjectRef(ObjectID.from_hex(h), self.runtime.worker_id)
                    for h in oids]
        ready, pending = await self._run(
            lambda: self.runtime.wait(refs, num_returns=num_returns,
                                      timeout=api_timeout))
        return {"ready": [r.hex() for r in ready],
                "pending": [r.hex() for r in pending]}

    async def _submit_task(self, conn, spec_blob: bytes):
        spec = serialization.loads_spec(spec_blob)
        spec.owner_id = self.runtime.worker_id
        refs = await self._run(self.runtime.submit_task, spec)
        self._pin(conn, refs)
        return {"oids": [r.hex() for r in refs],
                "owner": self.runtime.worker_id.hex()}

    async def _create_actor(self, conn, spec_blob: bytes):
        spec = serialization.loads_spec(spec_blob)
        spec.owner_id = self.runtime.worker_id
        await self._run(self.runtime.create_actor, spec)
        return {"ok": True}

    async def _submit_actor_task(self, conn, spec_blob: bytes):
        spec = serialization.loads_spec(spec_blob)
        spec.owner_id = self.runtime.worker_id
        refs = await self._run(self.runtime.submit_actor_task, spec)
        self._pin(conn, refs)
        return {"oids": [r.hex() for r in refs],
                "owner": self.runtime.worker_id.hex()}

    async def _kill_actor(self, conn, actor_id: str, no_restart: bool):
        await self._run(lambda: self.runtime.kill_actor(
            ActorID.from_hex(actor_id), no_restart=no_restart))
        return {"ok": True}

    async def _cancel(self, conn, oid: str, force: bool):
        with refcount_disabled():
            ref = ObjectRef(ObjectID.from_hex(oid), self.runtime.worker_id)
        self.runtime.cancel(ref, force=force)
        return {"ok": True}

    async def _get_named_actor(self, conn, name: str, namespace: str):
        aid = await self._run(
            lambda: self.runtime.get_named_actor(name, namespace))
        return {"actor_id": aid.hex() if aid else None}

    async def _actor_is_alive(self, conn, actor_id: str):
        alive = await self._run(
            lambda: self.runtime.actor_is_alive(ActorID.from_hex(actor_id)))
        return {"alive": bool(alive)}

    async def _release(self, conn, oids: list[str]):
        pins = self._pins.get(conn, {})
        for h in oids:
            oid = pins.pop(h, None)
            if oid is not None:
                self.runtime.refs.remove_local_ref(oid)
        return {"ok": True}

    async def _cluster_resources(self, conn):
        return await self._run(self.runtime.cluster_resources)

    async def _available_resources(self, conn):
        return await self._run(self.runtime.available_resources)

    async def _kv(self, conn, op: str, ns: str, key: str = "",
                  value: bytes | None = None, prefix: str = ""):
        if op == "put":
            await self._run(lambda: self.runtime.kv_put(key, value, ns=ns))
            return {"ok": True}
        if op == "get":
            return {"value": await self._run(
                lambda: self.runtime.kv_get(key, ns=ns))}
        if op == "del":
            await self._run(lambda: self.runtime.kv_del(key, ns=ns))
            return {"ok": True}
        return {"keys": await self._run(
            lambda: self.runtime.kv_keys(prefix, ns=ns))}


def start_client_server(runtime, host: str = "127.0.0.1",
                        port: int = 0) -> ClientServer:
    """Attach a client proxy to an existing driver runtime (typically run on
    the head node — reference: ray start --ray-client-server-port)."""
    from ray_tpu.core.cluster.protocol import EventLoopThread

    srv = ClientServer(runtime, host, port)
    EventLoopThread.get().run(srv.start())
    return srv
