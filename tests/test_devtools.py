"""rtlint (ray_tpu/devtools): fixture-based positive/negative cases per
rule, allowlist round-trip, annotation metadata, and the whole-package
zero-unallowlisted-findings gate at HEAD."""

from __future__ import annotations

import os

import pytest

from ray_tpu.devtools.annotations import ATTR, CONFINED_ATTR, guarded_by, loop_confined
from ray_tpu.devtools.engine import (
    AllowlistError,
    load_allowlist,
    run_lint,
)
from ray_tpu.devtools.model import parse_module
from ray_tpu.devtools.rules import RuleContext, rule_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "rtlint")


def lint_fixture(name: str, rules=None):
    return run_lint([os.path.join(FIXTURES, name)], allowlist=None,
                    rules=rules)


def symbols(res, rule=None):
    return {f.symbol for f in res.findings
            if rule is None or f.rule == rule}


# ------------------------------------------------------------------ fixtures

def test_r0_unused_import_detected_and_noqa_respected():
    res = lint_fixture("unused_import.py", rules=["R0"])
    assert symbols(res) == {"import:textwrap"}  # os has noqa, json is used


def test_r1_seq_no_race_fixture():
    """The PR-12 bug class: racy += minting duplicate task ids."""
    res = lint_fixture("seq_no_race.py", rules=["R1"])
    by_symbol = {f.symbol: f for f in res.findings}
    assert "Handle._seq_no" in by_symbol
    assert "non-atomic read-modify-write" in by_symbol["Handle._seq_no"].message


def test_r1_deque_iteration_race_fixture():
    """The PR-5 bug class: step window appended while the flusher
    iterates."""
    res = lint_fixture("deque_iter_race.py", rules=["R1"])
    assert "StepWindow._window" in symbols(res)
    (f,) = [f for f in res.findings if f.symbol == "StepWindow._window"]
    assert "thread:_flush_loop" in f.message


def test_r1_guarded_by_violation_fixture():
    res = lint_fixture("guarded_violation.py", rules=["R1"])
    assert len(res.findings) == 1
    f = res.findings[0]
    assert f.symbol == "Registry._table"
    assert "guarded attribute" in f.message
    # The locked site and the @guarded_by("_lock") method are NOT flagged.
    assert f.line == 23


def test_r2_lock_cycle_and_await_under_lock_fixture():
    res = lint_fixture("lock_cycle.py", rules=["R2"])
    cycles = [f for f in res.findings if f.symbol.startswith("lockcycle:")]
    awaits = [f for f in res.findings if f.symbol.endswith(":await")]
    assert len(cycles) == 1
    assert "Transfer._alock" in cycles[0].message
    assert "Transfer._block" in cycles[0].message
    assert len(awaits) == 1
    assert "self._alock" in awaits[0].message


def test_r3_loop_blocking_fixture():
    """time.sleep / sync call / ray_tpu.get / open / jax backend init in
    an async body — incl. the PR-5 jax-backend-in-the-wrong-process
    class."""
    res = lint_fixture("loop_blocking.py", rules=["R3"])
    got = symbols(res)
    assert {"handle_snapshot:time.sleep", "handle_snapshot:open",
            "handle_snapshot:ray_tpu.get",
            "handle_snapshot:jax.devices"} <= got
    assert any(s.endswith(".call") for s in got)


def test_r4_metric_double_registration_fixture():
    """The PR-8 bug class: second Counter(same_name) call site strands
    increments; node_id tag key is reserved for federation (PR-9)."""
    res = lint_fixture("metric_dup.py", rules=["R4"])
    got = symbols(res)
    assert "dup:fixture_shed_total" in got
    assert "fixture_node_counter" in got
    dup = [f for f in res.findings
           if f.symbol == "dup:fixture_shed_total"]
    assert len(dup) == 1  # one finding per extra site, not per site


def test_r5_unregistered_knob_fixture():
    """The PR-7 bug class: RTPU_* env reads with no registry entry."""
    res = lint_fixture("knob_unregistered.py", rules=["R5"])
    assert symbols(res) == {"RTPU_FIXTURE_SECRET_KNOB",
                            "RTPU_FIXTURE_OTHER_KNOB"}


def test_clean_fixture_has_zero_findings():
    """False-positive canary: the same shapes done right."""
    res = lint_fixture("clean.py")
    assert res.findings == [], [f.render() for f in res.findings]


def test_every_rule_detects_its_bug_class():
    """Acceptance: >= 5 rules each detect their reproduced historical
    bug class in the corpus."""
    res = run_lint([FIXTURES], allowlist=None)
    fired = {f.rule for f in res.findings}
    assert {"R0", "R1", "R2", "R3", "R4", "R5"} <= fired


# ---------------------------------------------------------------- allowlist

def test_allowlist_round_trip(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "# comment\n"
        "R1 tests/fixtures/rtlint/seq_no_race.py Handle._seq_no"
        " -- reproduction fixture, accepted\n")
    res = run_lint([os.path.join(FIXTURES, "seq_no_race.py")],
                   allowlist=str(allow), rules=["R1"])
    assert "Handle._seq_no" not in symbols(res)
    assert any(f.symbol == "Handle._seq_no" for f in res.allowlisted)
    assert res.stale_entries == []


def test_allowlist_wildcard_and_stale(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "R1 tests/fixtures/rtlint/seq_no_race.py Handle.* -- fixture\n"
        "R1 tests/fixtures/rtlint/seq_no_race.py Gone.attr -- stale row\n")
    res = run_lint([os.path.join(FIXTURES, "seq_no_race.py")],
                   allowlist=str(allow), rules=["R1"])
    assert res.findings == []          # wildcard swallowed the class
    assert len(res.stale_entries) == 1  # and the dead row is reported
    assert res.stale_entries[0].symbol == "Gone.attr"


def test_allowlist_requires_justification(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("R1 some/file.py Klass.attr\n")
    with pytest.raises(AllowlistError):
        load_allowlist(str(allow))
    allow.write_text("R1 some/file.py Klass.attr -- \n")
    with pytest.raises(AllowlistError):
        load_allowlist(str(allow))


# -------------------------------------------------------------- annotations

def test_guarded_by_runtime_metadata():
    @guarded_by("_lock", "_a", "_b")
    class K:
        pass

    assert getattr(K, ATTR) == {"_a": "_lock", "_b": "_lock"}

    class M:
        @guarded_by("_lock")
        def helper(self):
            pass

    assert getattr(M.helper, ATTR) == {"<body>": "_lock"}
    with pytest.raises(TypeError):
        guarded_by("")
    with pytest.raises(TypeError):
        guarded_by("_lock", 42)


def test_loop_confined_runtime_metadata():
    @loop_confined
    class K:
        pass

    assert getattr(K, CONFINED_ATTR) is True


def test_loop_confined_suppresses_caller_context():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        self._m = {}\n"
        "    async def handler(self):\n"
        "        self._m['k'] = 1\n"
        "    def public_sync(self):\n"
        "        self._m.pop('k', None)\n"
    )
    mod = parse_module("<mem>", "mem.py", src)
    from ray_tpu.devtools.rules import rule_races
    assert rule_races([mod], RuleContext())  # caller+loop: flagged
    mod2 = parse_module("<mem>", "mem.py", "@loop_confined\n" + src)
    assert rule_races([mod2], RuleContext()) == []  # confined: clean


def test_thread_inside_loop_confined_class_still_flagged():
    src = (
        "@loop_confined\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        import threading\n"
        "        self._m = {}\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "    def _run(self):\n"
        "        self._m['x'] = 1\n"
        "    async def handler(self):\n"
        "        self._m.pop('x', None)\n"
    )
    mod = parse_module("<mem>", "mem.py", src)
    from ray_tpu.devtools.rules import rule_races
    found = rule_races([mod], RuleContext())
    assert any(f.symbol == "C._m" for f in found)


# ------------------------------------------------------------ R4 hot paths

def test_r4_unbound_tags_on_declared_hot_path():
    src = (
        "from ray_tpu.util.metrics import Counter\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self._c = Counter('fixture_hot_total',\n"
        "                          tag_keys=('deployment',))\n"
        "    def assign(self, dep):\n"
        "        self._c.inc(tags={'deployment': dep})\n"
    )
    mod = parse_module("<mem>", "serve/hot.py", src)
    ctx = RuleContext(hot_modules=("serve/hot.py",))
    found = rule_metrics([mod], ctx)
    assert any("bound()" in f.message for f in found)
    # Same module NOT declared hot: no unbound finding.
    cold = rule_metrics([mod], RuleContext(hot_modules=()))
    assert not any("bound()" in f.message for f in cold)


# -------------------------------------------------------------- whole tree

def test_whole_package_zero_unallowlisted_findings():
    """Acceptance: `ray_tpu lint` exits 0 at HEAD — every finding fixed
    or allowlisted with a justification, and no stale allowlist rows."""
    res = run_lint([os.path.join(REPO, "ray_tpu")])
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    assert res.stale_entries == [], [
        f"{e.rule} {e.relpath} {e.symbol}" for e in res.stale_entries]
    assert res.allowlisted, "allowlist unexpectedly empty — baseline gone?"


def test_whole_package_within_wall_budget():
    res = run_lint([os.path.join(REPO, "ray_tpu")])
    assert res.wall_seconds < 30.0, res.wall_seconds


# ---------------------------------------------------------------------- CLI

def test_cli_lint_exit_codes(capsys):
    from ray_tpu.scripts.cli import main

    rc = main(["lint", os.path.join(FIXTURES, "seq_no_race.py"),
               "--no-allowlist"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "R1" in out and "seq_no_race.py" in out

    rc = main(["lint", os.path.join(FIXTURES, "clean.py")])
    assert rc == 0

    rc = main(["lint", "/definitely/not/a/path"])
    assert rc == 2


def test_cli_lint_json(capsys):
    import json as _json

    from ray_tpu.scripts.cli import main

    rc = main(["lint", os.path.join(FIXTURES, "metric_dup.py"),
               "--no-allowlist", "--json"])
    assert rc == 1
    payload = _json.loads(capsys.readouterr().out)
    assert payload["counts"].get("R4", 0) >= 2
    assert payload["files"] == 1
    assert all({"rule", "file", "line", "symbol", "message"}
               <= set(f) for f in payload["findings"])


def test_r3_wrapped_await_not_flagged():
    """`await asyncio.wait_for(client.call(...), t)` is the async path —
    every call feeding an await is loop-side, not a sync block."""
    from ray_tpu.devtools.rules import rule_event_loop

    m = parse_module("<m>", "m.py", (
        "import asyncio\n"
        "class C:\n"
        "    async def ping(self):\n"
        "        return await asyncio.wait_for("
        "self._client.call('p'), 5)\n"))
    assert not [f for f in rule_event_loop([m], RuleContext())
                if ".call" in f.symbol]


def test_r5_documented_check_is_whole_word():
    """RTPU_SHM must not ride on a documented RTPU_SHM_NAME entry."""
    from ray_tpu.devtools.rules import rule_knobs

    ctx = RuleContext(config_source="#   RTPU_SHM_NAME (internal): x")
    bad = parse_module("<m>", "m.py",
                       "import os\nv = os.environ.get('RTPU_SHM')\n")
    assert [f for f in rule_knobs([bad], ctx) if f.symbol == "RTPU_SHM"]
    ok = parse_module("<m>", "m.py",
                      "import os\nv = os.environ.get('RTPU_SHM_NAME')\n")
    assert not rule_knobs([ok], ctx)


def test_r0_same_name_imports_cannot_vouch_for_each_other():
    from ray_tpu.devtools.rules import rule_style

    m = parse_module("<m>", "m.py",
                     "import json\nfrom simplejson import json as json2\n")
    assert {f.symbol for f in rule_style([m], RuleContext())} == \
        {"import:json", "import:json2"}


def test_cli_no_python_files_is_usage_error(tmp_path, capsys):
    from ray_tpu.scripts.cli import main

    target = tmp_path / "notes.md"
    target.write_text("not python\n")
    assert main(["lint", str(target)]) == 2
    assert "no Python files" in capsys.readouterr().err


def test_unknown_rule_id_is_usage_error():
    from ray_tpu.devtools.engine import LintUsageError
    from ray_tpu.scripts.cli import main

    with pytest.raises(LintUsageError):
        run_lint([os.path.join(FIXTURES, "clean.py")], allowlist=None,
                 rules=["R9"])
    # lowercase + spaces normalize instead of crashing
    res = run_lint([os.path.join(FIXTURES, "metric_dup.py")],
                   allowlist=None, rules=["r4", " R4 "])
    assert symbols(res, "R4")
    assert main(["lint", os.path.join(FIXTURES, "clean.py"),
                 "--rules", "R9"]) == 2


def test_overlapping_paths_do_not_double_parse():
    one = os.path.join(FIXTURES, "metric_dup.py")
    res_single = run_lint([one], allowlist=None, rules=["R4"])
    res_overlap = run_lint([one, FIXTURES], allowlist=None, rules=["R4"])
    dup = lambda r: [f for f in r.findings  # noqa: E731
                     if f.symbol == "dup:fixture_shed_total"]
    assert len(dup(res_single)) == len(dup(res_overlap)) == 1


def test_cli_stale_allowlist_entry_fails(tmp_path, capsys):
    from ray_tpu.scripts.cli import main

    allow = tmp_path / "allow.txt"
    allow.write_text(
        "R1 tests/fixtures/rtlint/clean.py Gone.attr -- dead row\n")
    rc = main(["lint", os.path.join(FIXTURES, "clean.py"),
               "--allowlist", str(allow)])
    assert rc == 1  # stale rows fail the CLI, not just the dryrun gate
    out = capsys.readouterr().out
    assert "STALE" in out
    assert str(allow) in out  # points at the file actually used


def test_syntax_error_file_is_a_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n  pass\n")
    res = run_lint([str(bad)], allowlist=None)
    assert any(f.symbol == "syntax-error" for f in res.findings)


def test_lint_bench_quick_record():
    import sys

    sys.path.insert(0, REPO)
    from devbench.lint_bench import run_bench

    rec = run_bench(quick=True, write=False)
    assert rec["findings"] == 0
    assert rec["within_budget"]
    assert set(rec["rule_seconds"]) == {"R0", "R1", "R2", "R3", "R4", "R5"}
