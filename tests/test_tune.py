"""Tune layer tests (reference test model: python/ray/tune/tests/ —
test_tune_basic, searcher/scheduler unit tests)."""

import random

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers import AsyncHyperBandScheduler, PopulationBasedTraining
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trial import Trial


@pytest.fixture(autouse=True)
def _rt():
    ray_tpu.init()
    yield
    ray_tpu.shutdown()


def test_grid_search_cross_product():
    gen = BasicVariantGenerator(seed=0)
    gen.set_search_properties("m", "max", {
        "a": tune.grid_search([1, 2, 3]),
        "b": tune.grid_search(["x", "y"]),
        "c": 7,
    })
    gen._materialize(num_samples=1)
    cfgs = [gen.suggest(f"t{i}") for i in range(6)]
    assert all(c is not None for c in cfgs)
    assert gen.suggest("t6") is None
    assert {(c["a"], c["b"]) for c in cfgs} == {(a, b) for a in (1, 2, 3)
                                               for b in ("x", "y")}
    assert all(c["c"] == 7 for c in cfgs)


def test_random_domains_and_sample_from():
    gen = BasicVariantGenerator(seed=42)
    gen.set_search_properties("m", "max", {
        "lr": tune.loguniform(1e-5, 1e-1),
        "bs": tune.choice([16, 32]),
        "n": tune.randint(0, 10),
        "double_n": tune.sample_from(lambda cfg: cfg["n"] * 2),
    })
    gen._materialize(num_samples=5)
    for i in range(5):
        c = gen.suggest(f"t{i}")
        assert 1e-5 <= c["lr"] <= 1e-1
        assert c["bs"] in (16, 32)
        assert 0 <= c["n"] < 10
        assert c["double_n"] == c["n"] * 2


def test_function_trainable_end_to_end():
    def objective(config):
        acc = 0.0
        for i in range(5):
            acc += config["lr"]
            tune.report({"acc": acc})

    tuner = tune.Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.1, 0.2, 0.3])},
        tune_config=tune.TuneConfig(metric="acc", mode="max"),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.config["lr"] == 0.3
    assert best.metrics["acc"] == pytest.approx(1.5)


def test_class_trainable_and_stop_criteria():
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.x = config["start"]

        def step(self):
            self.x += 1
            return {"x": self.x}

        def save_checkpoint(self):
            return {"x": self.x}

        def load_checkpoint(self, ckpt):
            self.x = ckpt["x"]

    tuner = tune.Tuner(
        MyTrainable,
        param_space={"start": tune.grid_search([0, 100])},
        tune_config=tune.TuneConfig(metric="x", mode="max"),
        stop={"training_iteration": 3},
    )
    grid = tuner.fit()
    assert len(grid) == 2
    best = grid.get_best_result()
    assert best.metrics["x"] == 103
    assert best.checkpoint == {"x": 103}


def test_asha_stops_bad_trials():
    sched = AsyncHyperBandScheduler(grace_period=1, reduction_factor=2,
                                    max_t=16)
    sched.set_search_properties("score", "max")
    good, bad = Trial({"q": 1}), Trial({"q": 0})
    decisions = []
    for it in range(1, 6):
        d_good = sched.on_trial_result(good, {"training_iteration": it,
                                              "score": 10.0 * it})
        d_bad = sched.on_trial_result(bad, {"training_iteration": it,
                                            "score": 0.1 * it})
        decisions.append((d_good, d_bad))
    assert all(dg == "CONTINUE" for dg, _ in decisions)
    assert any(db == "STOP" for _, db in decisions)


def test_tune_errors_surface_in_results():
    def broken(config):
        if config["i"] == 1:
            raise ValueError("boom")
        tune.report({"ok": 1})

    grid = tune.Tuner(
        broken,
        param_space={"i": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
    ).fit()
    assert len(grid.errors) == 1
    assert "boom" in grid.errors[0]
    assert grid.get_best_result().config["i"] == 0


def test_pbt_exploits_and_explores():
    # Trainable whose improvement rate IS its hyperparameter; PBT should
    # propagate high-rate configs/weights to low-rate trials.
    class Rate(tune.Trainable):
        def setup(self, config):
            self.w = 0.0

        def step(self):
            self.w += self.config["rate"]
            return {"score": self.w}

        def save_checkpoint(self):
            return {"w": self.w}

        def load_checkpoint(self, ckpt):
            self.w = ckpt["w"]

    rng = random.Random(0)
    sched = PopulationBasedTraining(
        perturbation_interval=2,
        hyperparam_mutations={"rate": lambda: rng.uniform(0.5, 1.0)},
        quantile_fraction=0.5, seed=0)
    grid = tune.Tuner(
        Rate,
        param_space={"rate": tune.grid_search([0.01, 1.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched),
        stop={"training_iteration": 8},
    ).fit()
    # The weak trial must have been boosted by an exploit (its final score
    # would be ~0.08 without PBT).
    scores = sorted(r.metrics["score"] for r in grid.results)
    assert scores[0] > 0.5


def test_trainer_under_tune():
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def train_fn(config):
        from ray_tpu.train.session import report
        report({"loss": 1.0 / config["lr"]})

    trainer = DataParallelTrainer(
        train_fn, train_loop_config={"lr": 1.0},
        scaling_config=ScalingConfig(num_workers=1))
    grid = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {"lr": tune.grid_search([1.0, 2.0])}},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
    ).fit()
    assert len(grid) == 2
    assert grid.get_best_result().config["train_loop_config"]["lr"] == 2.0


def test_tpe_beats_random_on_deterministic_objective():
    """Model-based search: with the same trial budget, TPE's best objective
    beats pure random search on a smooth deterministic function (averaged
    over seeds — both samplers fully seeded, so this is deterministic)."""
    from ray_tpu.tune.search import TPESearcher

    space = {
        "x": tune.uniform(-2.0, 2.0),
        "y": tune.uniform(-2.0, 2.0),
        "lr": tune.loguniform(1e-5, 1e-1),
    }

    def objective(cfg):
        # Minimum 0 at (0.7, -0.3, 1e-3).
        import math as _m

        return ((cfg["x"] - 0.7) ** 2 + (cfg["y"] + 0.3) ** 2
                + (_m.log10(cfg["lr"]) + 3.0) ** 2)

    def run(searcher, n):
        searcher.set_search_properties("loss", "min", space)
        best = float("inf")
        for i in range(n):
            cfg = searcher.suggest(f"t{i}")
            score = objective(cfg)
            searcher.on_trial_complete(f"t{i}", {"loss": score})
            best = min(best, score)
        return best

    n_trials, seeds = 60, [0, 1, 2, 3, 4]
    tpe_best, rand_best = [], []
    for s in seeds:
        tpe_best.append(run(TPESearcher(n_startup=12, seed=s), n_trials))

        class _Random(tune.Searcher):
            def __init__(self, seed):
                self._rng = random.Random(seed)

            def suggest(self, trial_id):
                from ray_tpu.tune.search import Domain, _deepcopy_plain, \
                    _set_path, _walk

                cfg = _deepcopy_plain(self.space)
                for p, v in _walk(self.space):
                    if isinstance(v, Domain):
                        _set_path(cfg, p, v.sample(self._rng))
                return cfg

        rand_best.append(run(_Random(s), n_trials))
    tpe_mean = sum(tpe_best) / len(tpe_best)
    rand_mean = sum(rand_best) / len(rand_best)
    assert tpe_mean < rand_mean, (tpe_best, rand_best)


def test_tpe_in_tuner_end_to_end():
    """TPESearcher drops into the Tuner loop (suggest/on_trial_complete
    protocol) and converges toward the known optimum."""
    from ray_tpu.tune.search import TPESearcher

    def train_fn(config):
        tune.report({"loss": (config["x"] - 1.0) ** 2,
                     "done": True})

    tuner = tune.Tuner(
        train_fn,
        param_space={"x": tune.uniform(-4.0, 4.0),
                     "opt": tune.choice(["sgd", "adam"])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    num_samples=20,
                                    search_alg=TPESearcher(n_startup=6,
                                                           seed=3)),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["loss"] < 1.0
