"""Compiled-HLO analysis: cross-slice collective bytes AND peak HBM.

The scale-proof harness (devbench/multislice_perf.py) and tests need a
*measured* answer to "how many bytes does one train step push over DCN?",
even on CPU hosts where no real slice interconnect exists. XLA's partitioned
module is the ground truth: every collective op carries its per-device
payload shape and a ``replica_groups`` assignment, and a group whose members
live on more than one slice must move its payload across the slice boundary.
This module parses ``jit(...).lower(...).compile().as_text()`` and prices
each cross-slice op with the standard ring-algorithm cost model (stated on
the result so the number is reproducible):

- all-reduce over m slices: each participant sends ``2*(m-1)/m * payload``
  across the boundary (reduce-scatter + all-gather phases);
- all-gather / reduce-scatter / all-to-all: ``(m-1)/m * payload``;
- collective-permute: ``payload`` per cross-slice pair.

Payload is the op's per-device buffer size as listed in the partitioned
module (output shape), so quantized wire formats (int8 + scales) are priced
at their real width.

The second half of this module (:func:`hbm_stats`) extends the same
HLO-text grounding from comms bytes to memory: a liveness sweep over the
SCHEDULED module (``is_scheduled=true`` — instructions appear in execution
order, so def-to-last-use intervals are real lifetimes) estimates peak
live HBM without executing anything. The train-step autotuner
(ray_tpu/autotune) uses it to record predicted-vs-actual HBM per
candidate, and the recorded-fixture tests hold it within 15% of XLA's own
``compiled.memory_analysis()`` numbers.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

import numpy as np

COST_MODEL = ("per participant: all-reduce 2*(m-1)/m*payload, "
              "all-gather/all-to-all (m-1)/m*payload, reduce-scatter "
              "(m-1)/m*input (= payload*group_size), collective-permute "
              "payload; m = slices spanned by the replica group; payload = "
              "per-device result buffer bytes in the partitioned HLO "
              "(async -start ops: result = tuple minus operand aliases)")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_OP_RE = re.compile(
    # result: nested tuple (multi-operand async starts return
    # ((operands...), (results...))), flat tuple, or plain shape; two
    # nesting levels so TPU tiled layouts ({1,0:T(8,128)}) inside a
    # nested tuple still match
    r"=\s+(\((?:[^()]|\((?:[^()]|\([^()]*\))*\))*\)|\S+)\s+"
    r"(all-reduce-start|all-reduce|all-gather-start|all-gather|"
    r"reduce-scatter-start|reduce-scatter|collective-permute-start|"
    r"collective-permute|all-to-all-start|all-to-all)\(")
_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\}|\{\{[0-9,{} ]*\}\}|"
    r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")
_IOTA_RE = re.compile(r"\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?$")


@dataclass
class CollectiveOp:
    op: str
    payload_bytes: int        # per-device buffer bytes
    groups: list[list[int]]   # partition ids per replica group
    crosses_slices: bool
    dcn_bytes: int            # cross-slice bytes under COST_MODEL (all
    #                           participants summed); 0 for intra-slice ops


@dataclass
class CollectiveStats:
    ops: list[CollectiveOp] = field(default_factory=list)
    cost_model: str = COST_MODEL
    # collective lines whose replica groups could not be resolved (so the
    # totals below UNDERCOUNT if this is non-zero — callers should surface
    # it instead of trusting a silently partial sum)
    skipped_ops: int = 0

    @property
    def dcn_bytes(self) -> int:
        return sum(op.dcn_bytes for op in self.ops)

    @property
    def dcn_ops(self) -> int:
        return sum(1 for op in self.ops if op.crosses_slices)


def _parse_groups(spec: str) -> list[list[int]] | None:
    if spec.startswith("{"):
        return [[int(v) for v in grp.split(",") if v.strip()]
                for grp in re.findall(r"\{([0-9, ]*)\}", spec) if grp.strip()]
    m = _IOTA_RE.match(spec)
    if not m:
        return None
    n_groups, group_size = int(m.group(1)), int(m.group(2))
    dims = [int(d) for d in m.group(3).split(",")]
    ids = np.arange(math.prod(dims)).reshape(dims)
    if m.group(4):
        ids = ids.transpose([int(p) for p in m.group(4).split(",")])
    return ids.reshape(n_groups, group_size).tolist()


def _call_args(line: str, start: int) -> str:
    """The operand list from ``start`` (just past the call's open paren) to
    its matching close paren. Depth-counted, not find(")"): TPU tiled
    layouts (``f32[8,128]{1,0:T(8,128)}``) put parens inside operands."""
    depth = 1
    for i in range(start, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                return line[start:i]
    return line[start:]


def _shape_bytes(result: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(result):
        if dtype not in _DTYPE_BYTES:
            continue
        n = math.prod(int(d) for d in dims.split(",") if d) if dims else 1
        total += _DTYPE_BYTES[dtype] * n
    return total


def collective_stats(hlo_text: str, slice_of,
                     n_partitions: int | None = None) -> CollectiveStats:
    """Parse a partitioned HLO module; ``slice_of(partition_id) -> slice``
    maps the module's partition ids onto slices (for a mesh built slice-major
    over N devices with P per slice this is ``pid // P``). ``n_partitions``
    resolves the ``replica_groups={}`` spelling ("one group of everyone");
    without it, such ops are counted in ``skipped_ops`` rather than silently
    dropped."""
    stats = CollectiveStats()
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        groups_m = _GROUPS_RE.search(line)
        if not groups_m:
            # collective-permute carries source_target_pairs instead.
            pairs_m = re.search(r"source_target_pairs=\{([0-9,{} ]*)\}", line)
            if not pairs_m:
                stats.skipped_ops += 1
                continue
            groups = [[int(v) for v in grp.split(",")]
                      for grp in re.findall(r"\{([0-9, ]+)\}",
                                            pairs_m.group(1))]
        elif groups_m.group(1) == "{}":
            # all participants in one group
            groups = ([list(range(n_partitions))] if n_partitions else None)
        else:
            groups = _parse_groups(groups_m.group(1))
        if not groups:
            stats.skipped_ops += 1
            continue
        payload = _shape_bytes(m.group(1))
        op = m.group(2)
        if op.endswith("-start") and m.group(1).startswith("("):
            # Async wrapper tuple: (operand aliases..., results..., ctx) —
            # price only the results, or the raw payload is double-counted.
            payload = max(payload - _shape_bytes(_call_args(line, m.end())),
                          0)
        op = op.removesuffix("-start")
        dcn = 0
        crosses = False
        for grp in groups:
            m_slices = len({slice_of(p) for p in grp})
            if m_slices < 2:
                continue
            crosses = True
            if op == "collective-permute":
                dcn += payload  # one buffer moves src -> dst
                continue
            frac = (m_slices - 1) / m_slices
            per_member = {
                "all-reduce": 2 * frac * payload,
                "all-gather": frac * payload,
                # reduce-scatter's result is the 1/group_size shard; the
                # ring moves (m-1)/m of the FULL input per member.
                "reduce-scatter": frac * payload * len(grp),
                "all-to-all": frac * payload,
            }[op]
            dcn += int(per_member * len(grp))
        stats.ops.append(CollectiveOp(op=op, payload_bytes=payload,
                                      groups=groups, crosses_slices=crosses,
                                      dcn_bytes=dcn))
    return stats


def mesh_slice_map(n_devices: int, num_slices: int):
    """slice_of for a slice-major mesh (hybrid_mesh's device layout):
    partition ids enumerate the mesh flat with the DCN axis outermost, so
    consecutive runs of ``n_devices // num_slices`` ids share a slice."""
    per_slice = n_devices // num_slices
    return lambda pid: pid // per_slice


# ---------------------------------------------------------------------------
# Peak-HBM estimation over scheduled HLO (buffer liveness sweep)
# ---------------------------------------------------------------------------
#
# Method: parse every computation of the module; for the entry computation
# walk instructions in schedule order keeping a running sum of live buffer
# bytes. A buffer becomes live at its defining instruction and dies after
# its last use. Aliasing ops (tuple / get-tuple-element / bitcast / while /
# optimization-barrier) allocate nothing but EXTEND the lifetimes of the
# buffers they forward — without this, every scan carry packed into a tuple
# would "die" at the tuple op and the sweep undercounts by 2-3x (measured).
# Control flow recurses: a while op's peak is the live set at the op plus
# the body/condition computation's own temp peak (the carry aliases the
# operand, so it is already in the live set); fusions materialize only
# their result (fused internals stay in registers/VMEM scratch).
#
# Accuracy: the sweep does NOT model XLA's in-place buffer sharing (an
# elementwise op reusing a dying operand's allocation), so it lands a
# consistent 8-15% ABOVE ``compiled.memory_analysis()`` on train-step
# modules (devbench/autotune_bench.py tracks this on recorded fixtures).
# Overestimating is the safe direction for the autotuner's OOM pruning.

# dtype[dims]{layout} — layout may carry TPU tiling with parens: {1,0:T(8,128)}
_HBM_SHAPE_RE = re.compile(
    r"([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{([^{}]*?(?:\([0-9,]*\)[^{}]*?)*)\})?")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\((.*)\)\s+->\s+(.+?)\s*\{\s*$")
_INSTR_RE = re.compile(r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*(.+)$")
_OPCODE_RE = re.compile(r"^\s*((?:\(.*?\)|\S+?))\s+([\w\-]+)\(")
_CALLED_RE = re.compile(r"(?:calls|body|condition|to_apply)=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")

# Ops whose result aliases (a subset of) their operands: no new allocation,
# but the forwarded buffers stay live as long as the alias does.
_ALIAS_OPS = frozenset({
    "bitcast", "bitcast-convert", "get-tuple-element", "tuple", "parameter",
    "while", "optimization-barrier",
})

_HBM_DTYPE_BYTES = dict(_DTYPE_BYTES)
_HBM_DTYPE_BYTES.update({"s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
                         "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
                         "token": 0, "opaque": 0})


def _padded_shape_bytes(text: str) -> int:
    """Total buffer bytes of every shape in ``text`` (tuples sum), honoring
    TPU tiled layouts: ``f32[130,260]{1,0:T(8,128)}`` pads the physical
    (minor-to-major-permuted) dims up to tile multiples — the padding is
    real HBM the buffer occupies."""
    total = 0
    for dtype, dims, layout in _HBM_SHAPE_RE.findall(text):
        if dtype not in _HBM_DTYPE_BYTES:
            continue
        dl = [int(d) for d in dims.split(",") if d]
        n = math.prod(dl) if dl else 1
        if layout and ":T(" in layout:
            tile_m = re.search(r"T\(([0-9,]+)\)", layout)
            perm = [int(p) for p in layout.split(":", 1)[0].split(",")
                    if p.strip().lstrip("-").isdigit()]
            if tile_m and dl and len(perm) == len(dl):
                tile = [int(t) for t in tile_m.group(1).split(",")]
                # physical order: minor-to-major list reversed = major-first
                phys = [dl[p] for p in reversed(perm)]
                for i in range(1, len(tile) + 1):
                    if i <= len(phys):
                        t = tile[-i]
                        phys[-i] = -(-phys[-i] // t) * t
                n = math.prod(phys) if phys else 1
        total += _HBM_DTYPE_BYTES[dtype] * n
    return total


@dataclass
class HbmStats:
    """Peak-HBM estimate for one scheduled HLO module (see module docs)."""
    parameter_bytes: int      # entry arguments (params + opt state + batch)
    peak_temp_bytes: int      # liveness-sweep peak over entry temporaries
    n_computations: int
    n_instructions: int
    # donated inputs (input_output_alias header entries): outputs reuse
    # argument buffers, so the total need not add outputs again
    aliased_outputs: int = 0

    @property
    def peak_bytes(self) -> int:
        """Estimated peak HBM: arguments stay resident for the whole run,
        temporaries peak on top; non-donated outputs are produced by entry
        instructions and therefore already ride in the temp sweep."""
        return self.parameter_bytes + self.peak_temp_bytes


def _parse_module(hlo_text: str):
    """computations: name -> {param_bytes, instrs:[{lhs, op, nbytes,
    operands, called, root}]}; returns (computations, entry_name,
    n_output_aliases)."""
    comps: dict[str, dict] = {}
    cur = None
    entry = None
    aliases = 0
    for line in hlo_text.splitlines():
        s = line.strip()
        if cur is None:
            if s.startswith("HloModule"):
                aliases = s.count("may-alias") + s.count("must-alias")
                continue
            if s.endswith("{"):
                m = _COMP_RE.match(s)
                if m:
                    cur = m.group(2)
                    comps[cur] = {
                        "param_bytes": _padded_shape_bytes(m.group(3)),
                        "instrs": [],
                    }
                    if m.group(1):
                        entry = cur
            continue
        if s == "}":
            cur = None
            continue
        m = _INSTR_RE.match(s)
        if not m:
            continue
        root, lhs, rest = bool(m.group(1)), m.group(2), m.group(3)
        om = _OPCODE_RE.match(rest)
        if not om:
            continue
        # operands live inside the call's parens (depth-counted: TPU tiled
        # layouts put parens inside shapes); computation refs in the attrs.
        start = om.end()
        depth, i = 1, start
        while i < len(rest) and depth:
            if rest[i] == "(":
                depth += 1
            elif rest[i] == ")":
                depth -= 1
            i += 1
        args, attrs = rest[start:i - 1], rest[i:]
        called = _CALLED_RE.findall(attrs)
        br = _BRANCHES_RE.search(attrs)
        if br:
            called += _OPERAND_RE.findall(br.group(1))
        comps[cur]["instrs"].append({
            "lhs": lhs,
            "op": om.group(2),
            "nbytes": _padded_shape_bytes(om.group(1)),
            "operands": _OPERAND_RE.findall(args),
            "called": called,
            "root": root,
        })
    return comps, entry, aliases


def _temp_peak(comps: dict, name: str, memo: dict) -> int:
    """Liveness-sweep peak of one computation's temporaries (parameters
    excluded — the caller's live set already holds them)."""
    if name in memo:
        return memo[name]
    memo[name] = 0  # cycle guard (malformed input); real value set below
    c = comps[name]
    instrs = c["instrs"]

    # Alias resolution: lhs -> underlying allocated buffer names. Defs
    # precede uses in valid HLO, so one forward pass suffices.
    alias: dict[str, frozenset] = {}

    def bufs(n: str) -> frozenset:
        return alias.get(n, frozenset((n,)))

    for ins in instrs:
        if ins["op"] in _ALIAS_OPS:
            s = frozenset()
            for o in ins["operands"]:
                s |= bufs(o)
            alias[ins["lhs"]] = s

    last_use: dict[str, int] = {}
    n = len(instrs)
    for idx, ins in enumerate(instrs):
        for o in ins["operands"]:
            for b in bufs(o):
                last_use[b] = idx
        if ins["root"]:  # computation output: live past the end
            for b in bufs(ins["lhs"]):
                last_use[b] = n

    live: dict[str, int] = {}
    peak = cur = 0
    for idx, ins in enumerate(instrs):
        alloc = 0 if ins["op"] in _ALIAS_OPS else ins["nbytes"]
        nested = 0
        if ins["op"] != "fusion":  # fused internals never hit HBM
            for cn in ins["called"]:
                if cn in comps:
                    nested = max(nested, _temp_peak(comps, cn, memo))
        cur += alloc
        live[ins["lhs"]] = alloc
        peak = max(peak, cur + nested)
        # free buffers whose last use was this instruction (alias-extended)
        for o in set(ins["operands"]) | {ins["lhs"]}:
            for b in bufs(o):
                if last_use.get(b, idx) <= idx and b in live:
                    cur -= live.pop(b)
    memo[name] = peak
    return peak


def hbm_stats(hlo_text: str) -> HbmStats:
    """Estimate peak HBM of a compiled (scheduled) HLO module from its text
    alone — nothing is executed or allocated. See the section comment above
    for method and accuracy; prefer :func:`compiled_hbm_bytes` when you
    hold the jax ``Compiled`` object (exact where the backend reports it)."""
    comps, entry, aliases = _parse_module(hlo_text)
    if entry is None:
        raise ValueError("no ENTRY computation found — is this HLO text "
                         "from jit(...).lower(...).compile().as_text()?")
    memo: dict[str, int] = {}
    return HbmStats(
        parameter_bytes=comps[entry]["param_bytes"],
        peak_temp_bytes=_temp_peak(comps, entry, memo),
        n_computations=len(comps),
        n_instructions=sum(len(c["instrs"]) for c in comps.values()),
        aliased_outputs=aliases,
    )


def compiled_hbm_bytes(compiled) -> tuple[int, str]:
    """Peak-HBM bytes for a jax ``Compiled`` object: XLA's own
    ``memory_analysis()`` when the backend provides it (arguments + temp +
    non-aliased outputs — outputs aliased to donated inputs reuse argument
    buffers), else the :func:`hbm_stats` text estimate. Returns
    ``(bytes, source)`` with source "memory_analysis" | "hlo_liveness"."""
    try:
        ma = compiled.memory_analysis()
        total = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                 + max(ma.output_size_in_bytes - ma.alias_size_in_bytes, 0))
        if total > 0:
            return int(total), "memory_analysis"
    except Exception:
        pass
    return hbm_stats(compiled.as_text()).peak_bytes, "hlo_liveness"
