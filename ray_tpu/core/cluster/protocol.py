"""RPC substrate: length-prefixed msgpack frames over asyncio TCP.

Fills the role of the reference's gRPC plumbing (reference: src/ray/rpc/ —
server/client wrappers, client pools with reconnect): a tiny asymmetric RPC
with request/response correlation, one-way notifications, and long-poll
support. Every daemon (head, node daemon, worker) runs an ``RpcServer`` with
named handlers; clients are ``RpcClient``s usable from sync or async code.

Binary payloads (serialized objects) ride as msgpack bin values — no base64,
no copies beyond the socket buffers.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import itertools
import logging
import socket
import struct
import threading
import time
from collections import Counter
from typing import Any, Awaitable, Callable

import msgpack

from ray_tpu.devtools.annotations import loop_confined
from ray_tpu.chaos import injector as _chaos

logger = logging.getLogger("ray_tpu.rpc")

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 31

# The event loop holds only weak references to tasks: a fire-and-forget
# create_task with no strong reference can be garbage-collected mid-await
# (observed as GeneratorExit in long-running handlers). Every detached task
# must be pinned here until done.
_pinned_tasks: set = set()


def spawn_task(coro) -> asyncio.Task:
    """create_task + strong reference until completion."""
    task = asyncio.get_running_loop().create_task(coro)
    _pinned_tasks.add(task)
    task.add_done_callback(_pinned_tasks.discard)
    return task


def _pack(msg: dict) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(body)) + body


def pack_reply(rid, result=None, err: str | None = None) -> bytes:
    """Pre-pack a response frame OFF the event loop (raw-handler fast path:
    execution threads serialize their own replies; the loop only writes)."""
    if err is not None:
        return _pack({"r": rid, "e": err})
    return _pack({"r": rid, "o": result})



@loop_confined
class _CoalescingWriter:
    """Batches frames written within one event-loop tick into a single
    transport write. asyncio's StreamWriter attempts a socket send per
    write() call; under bursty RPC traffic (task fan-out, batched actor
    calls) that is one syscall per frame and dominates single-core
    profiles. All methods must run on the owning loop.
    """

    __slots__ = ("_writer", "_buf", "_scheduled", "_loop")

    _HIGH_WATER = 1 << 20  # await transport drain beyond this many bytes

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._buf = bytearray()
        self._scheduled = False
        self._loop = asyncio.get_running_loop()

    def write(self, data: bytes) -> None:
        # Surface a dying connection synchronously: without the per-call
        # drain, callers would otherwise only learn of the death from the
        # read loop, which reports sent=True and burns retry budgets for
        # requests that never hit the wire.
        transport = self._writer.transport
        if transport is None or transport.is_closing():
            raise ConnectionResetError("transport is closing")
        self._buf += data
        if not self._scheduled:
            self._scheduled = True
            self._loop.call_soon(self._flush)

    def _flush(self) -> None:
        self._scheduled = False
        if self._buf:
            data = bytes(self._buf)
            self._buf.clear()
            try:
                self._writer.write(data)
            except Exception:
                pass  # connection death surfaces via the read loop

    async def maybe_drain(self) -> None:
        """Backpressure: only block when the transport buffer is deep."""
        transport = self._writer.transport
        if transport is not None and \
                transport.get_write_buffer_size() > self._HIGH_WATER:
            self._flush()
            await self._writer.drain()


async def _read_frame(reader: asyncio.StreamReader) -> dict | None:
    try:
        hdr = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    body = await reader.readexactly(n)
    return msgpack.unpackb(body, raw=False)


class RpcError(Exception):
    pass


class RpcConnectionLost(RpcError):
    """Connection died. ``sent`` is False when the request never hit the
    wire (callers may retry side-effect-free without consuming budgets)."""

    def __init__(self, *args, sent: bool = True):
        super().__init__(*args)
        self.sent = sent


class RpcServer:
    """Asyncio TCP server dispatching {"m": method, ...} frames to handlers.

    Handlers are ``async def handler(conn, **kwargs) -> Any``; the return
    value is sent back as the response. Raising sends an error frame.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: dict[str, Callable[..., Awaitable[Any]]] = {}
        # Per-method inbound frame odometer (multi-call frames count one per
        # carried payload). Written only from serve() on the loop thread;
        # readers take point-in-time snapshots — the compiled-graph bench
        # diffs head counts across N steps to prove the direct-channel data
        # plane issues ~0 control-plane RPCs per step.
        self.counts: Counter = Counter()
        # Per-method handler-latency odometer: method -> [calls, total_s,
        # max_s], recorded around the awaited handler in _dispatch (raw
        # handlers skip it — their work happens off-loop). The head's
        # self-metrics loop diffs snapshots of this into the per-method
        # rate/latency table `ray_tpu status` shows.
        self.stats: dict[str, list] = {}
        # Raw handlers: fn(conn, msg) invoked INLINE in the read loop — no
        # task spawn, no auto-reply. The handler owns correlation: it hands
        # the frame to an execution thread which packs the reply itself and
        # posts it back via conn.post (the actor/task dispatch fast path).
        self._raw_handlers: dict[str, Callable[..., Any]] = {}
        self._server: asyncio.AbstractServer | None = None
        self._conns: set["ServerConnection"] = set()
        self.on_disconnect: Callable[["ServerConnection"], None] | None = None
        # Invoked (on the loop) immediately before ANY response frame is
        # written. The head points this at its WAL group-commit flush so a
        # client can never observe an ACK whose mutation record hasn't
        # reached the OS — callback scheduling order alone cannot guarantee
        # that (a reply flush scheduled earlier in the tick would carry the
        # ACK first).
        self.pre_reply: Callable[[], None] | None = None

    def handler(self, name: str):
        def deco(fn):
            self._handlers[name] = fn
            return fn

        return deco

    def register(self, name: str, fn: Callable[..., Awaitable[Any]]):
        self._handlers[name] = fn

    def register_raw(self, name: str, fn: Callable[..., Any]):
        self._raw_handlers[name] = fn

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def _on_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        conn = ServerConnection(self, reader, writer)
        self._conns.add(conn)
        try:
            await conn.serve()
        finally:
            self._conns.discard(conn)
            if self.on_disconnect:
                try:
                    self.on_disconnect(conn)
                except Exception:
                    pass
            writer.close()

    async def stop(self):
        if self._server:
            self._server.close()
            for conn in list(self._conns):
                try:
                    conn.writer.close()
                except Exception:
                    pass
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2.0)
            except asyncio.TimeoutError:
                pass


class ServerConnection:
    """One accepted client connection; supports server-push notifications."""

    def __init__(self, server: RpcServer, reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.meta: dict[str, Any] = {}  # handler-attached identity (node id, etc.)
        self._cw = _CoalescingWriter(writer)

    async def serve(self):
        raw = self.server._raw_handlers
        while True:
            msg = await _read_frame(self.reader)
            if msg is None:
                return
            method = msg.get("m")
            if method is not None:
                calls = msg.get("c")
                self.server.counts[method] += \
                    len(calls) if calls is not None else 1
            if _chaos.ACTIVE:
                # Fault-injection probe (rpc.server): a matching rule drops
                # the request on the floor (caller sees a hang/timeout —
                # lost-datagram semantics) or delays its dispatch. Delay is
                # DELIBERATELY inline: frames queued behind the matched one
                # on this connection wait too, which is what real network
                # delay does to a TCP stream — and dispatching delayed
                # frames out of band would reorder actor calls (mailbox
                # FIFO = frame order). Scope delay rules' method regexes
                # accordingly: heartbeats sharing the connection stall with
                # it. The module-flag guard keeps the disarmed hot path at
                # one attribute read per frame.
                act = _chaos.rpc_server_action(msg.get("m"))
                if act is not None:
                    if act[0] == "drop":
                        continue
                    await asyncio.sleep(act[1])
            fn = raw.get(msg.get("m")) if raw else None
            if fn is not None:
                # Inline fast dispatch: enqueue-to-executor is non-blocking,
                # and skipping the per-frame task + reply future halves the
                # loop work of a small-call round trip.
                try:
                    fn(self, msg)
                except Exception as e:  # noqa: BLE001
                    rid = msg.get("i")
                    if rid is not None:
                        await self._reply(rid, err=f"{type(e).__name__}: {e}")
                continue
            spawn_task(self._dispatch(msg))

    async def _dispatch(self, msg: dict):
        method, rid = msg.get("m"), msg.get("i")
        fn = self.server._handlers.get(method)
        if fn is None:
            await self._reply(rid, err=f"no such method: {method}")
            return
        t0 = time.perf_counter()
        try:
            result = await fn(self, **msg.get("a", {}))
            err = None
        except Exception as e:  # noqa: BLE001
            result, err = None, f"{type(e).__name__}: {e}"
        dt = time.perf_counter() - t0
        st = self.server.stats.get(method)
        if st is None:
            st = self.server.stats[method] = [0, 0.0, 0.0]
        st[0] += 1
        st[1] += dt
        if dt > st[2]:
            st[2] = dt
        if rid is not None:
            if err is not None:
                await self._reply(rid, err=err)
            else:
                await self._reply(rid, ok=result)

    async def _reply(self, rid, ok=None, err=None):
        hook = self.server.pre_reply
        if hook is not None:
            hook()
        frame = {"r": rid, "e": err} if err is not None else {"r": rid, "o": ok}
        try:
            self._cw.write(_pack(frame))
            await self._cw.maybe_drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

    async def notify(self, method: str, **kwargs):
        """Server-initiated push (used by pubsub long-poll replacement)."""
        self._cw.write(_pack({"m": method, "a": kwargs}))
        await self._cw.maybe_drain()

    def post(self, frames) -> None:
        """Write pre-packed frame bytes (one blob or a list). Loop-thread
        only — execution threads schedule it via call_soon_threadsafe. The
        coalescing writer merges every frame posted this tick into one
        transport write."""
        try:
            if isinstance(frames, (bytes, bytearray)):
                self._cw.write(frames)
            else:
                for f in frames:
                    self._cw.write(f)
        except (ConnectionResetError, BrokenPipeError):
            pass  # peer gone; its client sees the loss from the read side


@loop_confined
class AsyncRpcClient:
    """Async client half: call(method, **kwargs) with correlation ids."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader = None
        self._writer = None
        self._seq = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._cw: _CoalescingWriter | None = None
        self._notify_handlers: dict[str, Callable[..., Awaitable[None]]] = {}
        self._closed = False
        # Chaos partition probe (ray_tpu/chaos partition point): when this
        # client carries head⇄node traffic, ``partition_node`` names the
        # node end and ``partition_send`` the direction its outbound frames
        # travel ("to_head" for a daemon's head link, "from_head" for the
        # head's per-daemon clients). Inbound frames probe the opposite
        # direction. None (the default) = no probe, zero hot-path cost
        # beyond the module ACTIVE flag read.
        self.partition_node: str | None = None
        self.partition_send: str | None = None

    def _partition_act(self, direction: str) -> tuple[str, float] | None:
        if not _chaos.ACTIVE or self.partition_node is None:
            return None
        return _chaos.partition_action(self.partition_node, direction)

    @property
    def _partition_recv_dir(self) -> str:
        return "from_head" if self.partition_send == "to_head" else "to_head"

    def on_notify(self, method: str, fn: Callable[..., Awaitable[None]]):
        self._notify_handlers[method] = fn

    async def connect(self):
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        sock = self._writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._cw = _CoalescingWriter(self._writer)
        spawn_task(self._read_loop())

    async def _read_loop(self):
        while True:
            msg = await _read_frame(self._reader)
            if msg is None:
                self._fail_all(RpcConnectionLost(f"connection to {self.host}:{self.port} lost"))
                return
            if _chaos.ACTIVE and self.partition_node is not None:
                # Inbound leg of a directional head⇄node partition: a
                # matched frame is silently discarded (the peer believes it
                # answered; the caller sees a hang — lost-datagram
                # semantics, the connection itself stays up) or stalled
                # inline (frames queued behind it wait too, like a
                # congested link).
                act = self._partition_act(self._partition_recv_dir)
                if act is not None:
                    if act[0] == "drop":
                        continue
                    await asyncio.sleep(act[1])
            if "r" in msg:
                fut = self._pending.pop(msg["r"], None)
                if fut is not None and not fut.done():
                    if msg.get("e") is not None:
                        fut.set_exception(RpcError(msg["e"]))
                    else:
                        fut.set_result(msg.get("o"))
            elif "m" in msg:
                fn = self._notify_handlers.get(msg["m"])
                if fn is not None:
                    # Sync handlers run inline; only coroutines get a task.
                    # A handler exception must not kill the read loop — that
                    # silently drops every later notify AND strands every
                    # in-flight call on this connection.
                    try:
                        res = fn(**msg.get("a", {}))
                        if asyncio.iscoroutine(res):
                            spawn_task(res)
                    except Exception:  # noqa: BLE001 - handler bug, log it
                        logger.exception("notify handler %r failed",
                                         msg["m"])

    def _fail_all(self, exc: Exception):
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    async def call(self, method: str, timeout: float | None = None, **kwargs) -> Any:
        if self._closed:
            raise RpcConnectionLost("client closed", sent=False)
        dropped = False
        if _chaos.ACTIVE and self.partition_node is not None:
            act = self._partition_act(self.partition_send or "to_head")
            if act is not None:
                if act[0] == "drop":
                    dropped = True  # register the future, never send: the
                    # caller waits out its timeout, as for a lost datagram.
                    # A caller WITHOUT a timeout gets a bounded one forced
                    # on it — an un-timed dropped frame would otherwise
                    # wedge its await forever, surviving even a heal (no
                    # retransmit exists at this layer), e.g. the head's
                    # PG 2PC task stuck past `chaos clear`.
                    if timeout is None:
                        timeout = 30.0
                else:
                    await asyncio.sleep(act[1])
        rid = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            if not dropped:
                self._cw.write(_pack({"m": method, "i": rid, "a": kwargs}))
                await self._cw.maybe_drain()
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            self._pending.pop(rid, None)
            raise RpcConnectionLost(f"send failed: {e}", sent=False)
        if timeout is None:
            # No wait_for wrapper: it costs a timer handle + an extra task
            # per call, and unbounded calls are the hot path (push_task,
            # push_actor_call ride with timeout=None).
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(rid, None)  # timed out: don't leak the slot

    def call_nowait(self, method: str, **kwargs) -> asyncio.Future:
        """Send a request and return its pending future WITHOUT awaiting —
        callers attach done-callbacks instead of spawning a task per call
        (the per-actor-call fast path). Loop-thread only."""
        fut = asyncio.get_running_loop().create_future()
        if self._closed:
            fut.set_exception(RpcConnectionLost("client closed", sent=False))
            return fut
        rid = next(self._seq)
        self._pending[rid] = fut
        try:
            self._cw.write(_pack({"m": method, "i": rid, "a": kwargs}))
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            self._pending.pop(rid, None)
            fut.set_exception(RpcConnectionLost(f"send failed: {e}",
                                                sent=False))
        return fut

    def call_many(self, method: str, payloads: list) -> list[asyncio.Future]:
        """N individually-correlated requests in ONE frame: the multi-call
        frame ``{"m": method, "c": [[rid, payload], ...]}`` amortizes
        pack/write across a burst while every payload keeps its own reply
        future (replies arrive as normal per-rid frames, in any order).
        Loop-thread only."""
        loop = asyncio.get_running_loop()
        futs = [loop.create_future() for _ in payloads]
        if self._closed:
            err = RpcConnectionLost("client closed", sent=False)
            for f in futs:
                f.set_exception(err)
            return futs
        calls = []
        for fut, payload in zip(futs, payloads):
            rid = next(self._seq)
            self._pending[rid] = fut
            calls.append((rid, payload))
        try:
            self._cw.write(_pack({"m": method, "c": calls}))
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            err = RpcConnectionLost(f"send failed: {e}", sent=False)
            for (rid, _), fut in zip(calls, futs):
                self._pending.pop(rid, None)
                if not fut.done():
                    fut.set_exception(err)
        return futs

    async def notify(self, method: str, **kwargs):
        if _chaos.ACTIVE and self.partition_node is not None:
            act = self._partition_act(self.partition_send or "to_head")
            if act is not None:
                if act[0] == "drop":
                    return  # one-way frame lost on the severed link
                await asyncio.sleep(act[1])
        self._cw.write(_pack({"m": method, "a": kwargs}))
        await self._cw.maybe_drain()

    async def close(self):
        self._closed = True
        if self._writer:
            self._writer.close()


class EventLoopThread:
    """A dedicated asyncio loop on a background thread, shared per process.

    Sync code (the user's driver / worker task code) calls ``run(coro)`` to
    execute on the loop and block for the result.
    """

    _singleton: "EventLoopThread | None" = None
    _lock = threading.Lock()

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._main, daemon=True, name="rtpu-io")
        self._thread.start()

    def _main(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    @classmethod
    def get(cls) -> "EventLoopThread":
        with cls._lock:
            if cls._singleton is None or not cls._singleton._thread.is_alive():
                cls._singleton = cls()
            return cls._singleton

    def run(self, coro, timeout: float | None = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)


class RpcClient:
    """Sync façade over AsyncRpcClient via the process's io loop thread.
    Reconnects once per call after a lost connection (a restarted server at
    the same address resumes service transparently — reference: gcs clients
    retry through GCS restarts)."""

    def __init__(self, host: str, port: int):
        self._io = EventLoopThread.get()
        self._async = AsyncRpcClient(host, port)
        self._io.run(self._async.connect(), timeout=10)
        self.on_reconnect = None  # hook: re-subscribe server-push channels

    @property
    def aio(self) -> AsyncRpcClient:
        return self._async

    def _reconnect(self) -> None:
        old = self._async
        fresh = AsyncRpcClient(old.host, old.port)
        fresh._notify_handlers = dict(old._notify_handlers)
        self._io.run(fresh.connect(), timeout=10)
        self._async = fresh
        if self.on_reconnect is not None:
            self.on_reconnect()

    def _call_once(self, method: str, timeout: float | None,
                   kwargs: dict) -> Any:
        """One request/response round trip, minimal hops: the frame is
        packed on the CALLER thread (serialization overlaps loop work), one
        call_soon_threadsafe registers the pending future and writes, and
        the caller blocks on a concurrent.futures.Future — no wrapper
        coroutine, no run_coroutine_threadsafe double-future, no wait_for
        timer per call. Profiled against the old path this roughly halves
        the non-wire cost of a sync control RPC (the 1_1_actor_calls_sync /
        single_client_tasks_sync flamegraphs were dominated by these
        allocations and thread handoffs)."""
        a = self._async
        if a._closed:
            raise RpcConnectionLost("client closed", sent=False)
        rid = next(a._seq)
        data = _pack({"m": method, "i": rid, "a": kwargs})
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def send():
            # Re-check closed ON THE LOOP: _fail_all (read loop) may have
            # drained _pending between the caller-thread check and this
            # callback — registering after it, against a dead transport
            # whose write raises nothing, would leave the future pending
            # FOREVER (a timeout=None caller would hang, not reconnect).
            if a._closed:
                if not fut.done():
                    fut.set_exception(
                        RpcConnectionLost("connection lost", sent=False))
                return
            a._pending[rid] = fut
            try:
                a._cw.write(data)
            except Exception as e:  # noqa: BLE001 - dying transport
                a._pending.pop(rid, None)
                if not fut.done():
                    fut.set_exception(
                        RpcConnectionLost(f"send failed: {e}", sent=False))

        self._io.loop.call_soon_threadsafe(send)
        try:
            return fut.result(timeout)
        except concurrent.futures.TimeoutError:
            a._pending.pop(rid, None)
            raise TimeoutError(
                f"rpc {method} timed out after {timeout}s") from None

    def call(self, method: str, timeout: float | None = None, **kwargs) -> Any:
        try:
            return self._call_once(method, timeout, kwargs)
        except RpcConnectionLost as e:
            if e.sent:
                # The request may have executed (only the reply was lost):
                # retrying would double-run non-idempotent RPCs. Surface the
                # failure; the NEXT call reconnects via the sent=False path.
                raise
            self._reconnect()
            return self._call_once(method, timeout, kwargs)

    # Per-attempt wait while retrying: long enough that a healthy server's
    # slowest control RPC answers, short enough that a partition-dropped
    # frame doesn't eat the whole retry budget on one attempt.
    RETRY_ATTEMPT_TIMEOUT_S = 10.0

    def call_retrying(self, method: str, timeout: float | None = None,
                      req_id: str | None = None, idempotent: bool = False,
                      budget_s: float | None = None, **kwargs) -> Any:
        """Head-session-aware call: survives server crashes, restarts, and
        partitions with full-jitter exponential backoff, capped by a total
        deadline (``budget_s``, default config ``head_retry_budget_s``).

        Safe-retry contract — a lost connection after the request was SENT
        means it may have executed, so blind re-sends double-run
        non-idempotent RPCs. This wrapper therefore retries sent/timed-out
        attempts only when the caller declares them safe:

        - ``req_id``: a client-stamped request id forwarded to the server,
          whose WAL-backed dedup table turns the retry into exactly-once
          (head mutations: register_actor, kv/fn puts, PG create/remove).
        - ``idempotent=True``: the RPC is a pure read or naturally
          idempotent (same-row register_worker, subscribe).

        With neither, sent-failures surface exactly like :meth:`call`.
        ``timeout`` bounds each ATTEMPT (default RETRY_ATTEMPT_TIMEOUT_S);
        the budget bounds the whole retry loop."""
        import random

        from ray_tpu.utils.config import get_config

        cfg = get_config()
        if budget_s is None:
            budget_s = cfg.head_retry_budget_s
        if req_id is not None:
            kwargs["req_id"] = req_id
        retry_sent = idempotent or req_id is not None
        deadline = time.monotonic() + budget_s
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            per_try = timeout if timeout is not None \
                else self.RETRY_ATTEMPT_TIMEOUT_S
            if attempt > 0:
                per_try = min(per_try, max(0.05, remaining))
            try:
                return self._call_once(method, per_try, dict(kwargs))
            except (RpcConnectionLost, TimeoutError, OSError) as e:
                sent = not isinstance(e, RpcConnectionLost) or e.sent
                if sent and not retry_sent:
                    raise
                if time.monotonic() >= deadline:
                    raise
                attempt += 1
                # Full jitter: sleep in [0, cap), cap doubling from base to
                # max — a head restart with hundreds of clients retrying
                # must see staggered re-registration, not a stampede.
                cap = min(cfg.head_retry_max_s,
                          cfg.head_retry_base_s * (2 ** min(attempt, 16)))
                time.sleep(random.random() *
                           min(cap, max(0.0, deadline - time.monotonic())))
                try:
                    self._reconnect()
                except Exception:  # noqa: BLE001 - still down: next attempt
                    pass

    def notify(self, method: str, **kwargs) -> None:
        data = _pack({"m": method, "a": kwargs})
        a = self._async

        def send():
            try:
                a._cw.write(data)
            except Exception:
                pass  # loss surfaces on the read side

        self._io.loop.call_soon_threadsafe(send)

    def close(self):
        try:
            self._io.run(self._async.close())
        except Exception:
            pass
