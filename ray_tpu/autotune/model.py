"""Analytic peak-HBM model for Llama train-step candidates.

This is the free tier of the autotuner's two-tier estimator: closed-form
accounting from the model config alone — params, gradients, optimizer
state (``optimizer_state_bytes`` over ``jax.eval_shape``, so ZeRO-1
sharding divides it without materializing anything), per-layer saved
activations per remat policy, and the fused-CE / update-phase transients.
Candidates whose prediction exceeds the device budget are pruned before
any compilation; the compile-time tier (``hlo_stats.hbm_stats`` /
``compiled_hbm_bytes`` on the AOT module) then records predicted-vs-actual
for the few candidates that actually get measured.

Accounting notes (why these terms, from the jax.checkpoint semantics in
models/llama.py and the scan structure in train/spmd.py):

- The layer input is ALWAYS saved (it is the checkpointed function's
  argument), on top of whatever the policy's save-list names.
- The backward has three distinct peaks that must be MAXed, not summed
  (their transients never overlap): (1) the fused-CE backward, when every
  saved activation is still live but the layer-grad accumulators are not
  yet allocated; (2) the layer-scan backward's start, when the full
  stacked gradient accumulators coexist with the full saved-activation
  set plus one layer's recompute workspace; (3) the optimizer update,
  when activations are dead and grads + the updates tree coexist (the
  f32 moment arithmetic fuses elementwise into the bf16 state writes and
  materializes nothing leaf-sized).

Accuracy: heuristic, not buffer assignment. The bench prunes with a
configurable safety margin above budget so a few-percent overestimate
cannot drop a config that actually fits (pruning errs toward keeping; a
kept-but-OOM candidate costs one failed AOT attempt, the pre-autotuner
status quo for every over-budget row). devbench/autotune_bench.py records
the model's error against AOT-compiled modules.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field

from ray_tpu.autotune.space import Candidate

# Known usable-HBM budgets by TPU generation (GB). Preferred source is the
# live backend's memory_stats()["bytes_limit"]; this table is the offline
# fallback (e.g. pricing for a chip from a CPU host). Ordered most-specific
# first: 'v5p' (95 GB) must match before the bare 'v5' (v5e/lite, 16 GB) —
# a 16 GB fallback on a v5p would wrongly prune every large-batch config.
_HBM_BY_GEN_GB = [
    ("v5p", 95), ("v5e", 16), ("v5", 16),   # bare v5 / "v5 lite" = v5e
    ("v6e", 32), ("v6", 32),
    ("v2", 8), ("v3", 16), ("v4", 32), ("v7", 192),
]


def device_hbm_budget_bytes(device=None) -> int | None:
    """Usable HBM of the accelerator the bench will run on, or None when
    unknown (CPU hosts without an override — callers then skip pruning).
    RTPU_HBM_BUDGET_GB always wins (float GB)."""
    env = os.environ.get("RTPU_HBM_BUDGET_GB")
    if env:
        try:
            return int(float(env) * (1 << 30))
        except ValueError:
            pass
    try:
        import jax

        d = device if device is not None else jax.devices()[0]
        if d.platform != "tpu":
            return None
        try:
            limit = d.memory_stats().get("bytes_limit")
            if limit:
                return int(limit)
        except Exception:
            pass
        kind = d.device_kind.lower()
        for gen, gb in _HBM_BY_GEN_GB:
            if gen in kind:
                return gb << 30
    except Exception:
        pass
    return None


@dataclass
class HbmPrediction:
    total_bytes: int
    components: dict = field(default_factory=dict)

    @property
    def total_gb(self) -> float:
        return round(self.total_bytes / (1 << 30), 3)


def _policy_layer_bytes(policy: str, mb: int, seq: int, cfg,
                        flash: bool) -> int:
    """Saved-activation bytes for ONE layer under one remat policy, at
    microbatch mb (see models/llama._remat_wrap for what each policy's
    save-list names)."""
    ab = cfg.jnp_dtype.itemsize          # activation dtype (bf16 = 2)
    h = cfg.hidden_size
    qd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    inter = cfg.intermediate_size
    tok = mb * seq

    x_in = tok * h * ab                  # checkpointed layer input
    q = tok * qd * ab                    # rope_out q
    k = tok * kvd * ab                   # rope_out k
    v = tok * kvd * ab                   # v_out
    attn_o = tok * qd * ab               # flash out (blockwise: same shape)
    lse = mb * cfg.num_heads * seq * 4 if flash else 0
    proj = tok * h * ab                  # attn_proj
    gate = tok * inter * ab              # mlp_gate (post-silu)
    up = tok * inter * ab
    down = tok * h * ab
    norm2 = 2 * tok * h * ab

    if policy in (False, "none"):
        # save-all: dots+ plus every elementwise intermediate; ~25% on top
        # of the named tensors in practice
        return int((x_in + 2 * q + 2 * k + v + attn_o + lse + proj + gate
                    + up + down + norm2) * 1.25)
    if policy in (True, "full"):
        return x_in
    if policy == "attn":
        return x_in + q + k + v + attn_o + lse + proj
    if policy == "attn+":
        return x_in + q + k + v + attn_o + lse + proj + gate
    if policy == "dots":
        # checkpoint_dots: every matmul output + the flash residuals
        return (x_in + q + k + v + attn_o + lse + proj + gate + up + down)
    if policy == "dots+":
        # dots + norm/rope outputs (rope_out ~ q+k again)
        return (x_in + 2 * q + 2 * k + v + attn_o + lse + proj + gate + up
                + down + norm2)
    raise ValueError(f"unknown remat policy {policy!r}")


def _expand_remat(spec, num_layers: int) -> list:
    from ray_tpu.models.llama import normalize_remat

    norm = normalize_remat(spec, num_layers)
    if isinstance(norm, tuple):
        return list(norm)
    return [norm] * num_layers


# Recompute-FLOPs multiplier per policy (vs no remat), used by the search
# ranking: 'attn' re-runs norms + SwiGLU (~18% extra step FLOPs, measured —
# see models/llama.py), 'attn+' halves the MLP recompute, 'dots' only
# re-runs elementwise, 'full' re-runs the whole forward (~1/3 extra).
POLICY_FLOPS_FACTOR = {
    "none": 1.0, False: 1.0, "dots+": 1.02, "dots": 1.05,
    "attn+": 1.11, "attn": 1.18, "full": 1.33, True: 1.33,
}


def remat_flops_factor(spec, num_layers: int) -> float:
    layers = _expand_remat(spec, num_layers)
    return sum(POLICY_FLOPS_FACTOR[p] for p in layers) / len(layers)


@functools.lru_cache(maxsize=16)
def _optimizer_state_bytes(cfg, opt_name: str) -> int:
    """Replicated optimizer-state bytes via eval_shape (nothing allocated).
    Cached per (cfg, opt_name) — LlamaConfig is frozen/hashable, and a
    70-candidate search would otherwise re-trace the same two values
    ~0.7 s worth per round."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import init_params
    from ray_tpu.train.optim import adamw_lowmem, optimizer_state_bytes

    if opt_name == "lowmem":
        opt = adamw_lowmem(3e-4, weight_decay=0.1)
    else:
        opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=jnp.bfloat16)
    shapes = jax.eval_shape(lambda k: init_params(cfg, k),
                            jax.random.PRNGKey(0))
    return optimizer_state_bytes(opt, shapes)


def predict_hbm(cfg, seq: int, cand: Candidate,
                data_shards: int = 1) -> HbmPrediction:
    """Peak-HBM prediction for one candidate on one device.

    ``data_shards``: devices the batch (and, under zero1, the optimizer
    state and weight update) shard over — 1 for the single-chip bench."""
    pb = cfg.jnp_dtype.itemsize
    n_params = cfg.num_params()
    mb = max(1, cand.batch // max(1, cand.grad_accum)) // max(1, data_shards)
    mb = max(1, mb)

    params = n_params * pb
    grads = n_params * pb                       # stacked scan accumulators
    opt_state = _optimizer_state_bytes(cfg, cand.opt)
    if cand.zero1 and data_shards > 1:
        opt_state //= data_shards

    flash = cand.attn == "flash"
    layers = _expand_remat(cand.remat, cfg.num_layers)
    acts = sum(_policy_layer_bytes(p, mb, seq, cfg, flash) for p in layers)
    # embedding output + final norm hidden (full batch lives outside the
    # per-layer checkpoint; under grad_accum only the microbatch slice is
    # in flight)
    embed = 2 * mb * seq * cfg.hidden_size * pb

    from ray_tpu.ops.loss import default_ce_chunk

    # The same resolution order the compiled step uses: explicit candidate
    # knob, else the process-level RTPU_CE_CHUNK override, else 512 — a
    # process override must be priced, not silently modeled at the default.
    chunk = cand.ce_chunk or default_ce_chunk()
    chunk = min(chunk, seq)
    if seq % chunk:
        chunk = seq                              # ops/loss.py fallback
    v = cfg.vocab_size
    # CE backward chunk workspace: recomputed logits + softmax p + dlogits
    # at f32 (~2.5 chunks at f32 after fusion), plus the f32 dhead
    # accumulator and the stacked dx output.
    ce = int(2.5 * mb * chunk * v * 4) + cfg.hidden_size * v * 4 \
        + mb * seq * cfg.hidden_size * 4
    # One layer's remat recompute workspace during the scan backward:
    # re-running the SwiGLU block keeps ~two f32 [mb, seq, inter] buffers
    # in flight for the recompute-heavy policies; the save-everything
    # policies recompute (almost) nothing.
    inter_f32 = mb * seq * cfg.intermediate_size * 4
    layer_tr = {
        "full": 2 * inter_f32, True: 2 * inter_f32, "attn": 2 * inter_f32,
        "attn+": inter_f32, "dots": inter_f32 // 4,
        "dots+": inter_f32 // 4, "none": 0, False: 0,
    }
    layer_transient = max(layer_tr.get(p, inter_f32) for p in layers)

    if cand.grad_accum > 1:
        # scan-carry accumulation: old + new grad trees live across the add
        grads += n_params * pb
    # optimizer update: grads + the updates tree (the f32 moment math fuses
    # into the bf16 state writes and materializes nothing leaf-sized)
    upd = n_params * pb

    # The three backward phases (module docstring) — max, not sum:
    backward_peak = max(
        acts + ce,                       # CE backward, grads not yet alloc'd
        acts + grads + layer_transient,  # layer-scan backward start
        grads + upd,                     # optimizer update, acts dead
    )
    total = params + opt_state + embed + backward_peak
    return HbmPrediction(
        total_bytes=int(total),
        components={
            "params": params, "grads": grads, "opt_state": opt_state,
            "activations": acts, "embed": embed, "ce_transient": ce,
            "layer_transient": layer_transient, "update_transient": upd,
            "backward_peak": backward_peak,
        },
    )
