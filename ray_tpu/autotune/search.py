"""Autotune search driver: predict -> prune -> rank -> measure -> bank.

The driver never compiles a pruned candidate: the analytic HBM model
(autotune/model.py) prices the whole space for free, candidates over the
device budget (times a safety margin) are dropped at analysis time, and
only the top few survivors — ranked by a throughput prior plus any cached
measurements — are handed to the caller's ``measure_fn``. Every decision
lands in the search trace (``SearchResult.trace``) so a bench round's
``tried`` list shows WHY each config was measured, skipped, or pruned.
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass, field

from ray_tpu.autotune.model import predict_hbm, remat_flops_factor
from ray_tpu.autotune.space import Candidate


class AutotuneCache:
    """Measured-throughput cache, keyed by device kind + geometry + label.

    A JSON file next to the bench (or RTPU_AUTOTUNE_CACHE): measurements
    from earlier rounds seed the ranking so the sweep spends its budget on
    the unexplored frontier instead of re-measuring known configs; the
    best cached config is still re-measured each round (it banks the
    headline number and keeps the cache honest against regressions).

    Per-machine state, gitignored: a fresh checkout starts empty and the
    bench re-seeds it from the committed BENCH_r*.json /
    PERF_TRAIN_TPU.json rows (bench._seed_cache) — measured `tried` rows
    are round artifacts the driver records, so the search frontier
    survives checkouts through them even when this file does not."""

    def __init__(self, path: str | None = None):
        self.path = path or os.environ.get("RTPU_AUTOTUNE_CACHE") or \
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "AUTOTUNE_CACHE.json")
        self._data: dict[str, dict] = {}
        try:
            with open(self.path) as f:
                self._data = json.load(f)
        except Exception:
            self._data = {}

    @staticmethod
    def key(device_kind: str, geometry: str, label: str) -> str:
        return f"{device_kind}|{geometry}|{label}"

    def get(self, device_kind: str, geometry: str, label: str) -> dict | None:
        return self._data.get(self.key(device_kind, geometry, label))

    def put(self, device_kind: str, geometry: str, label: str,
            record: dict, flush: bool = True) -> None:
        """``flush=False`` defers the file write (bulk seeding); call
        :meth:`flush` once afterwards."""
        rec = dict(record)
        rec["ts"] = time.time()
        self._data[self.key(device_kind, geometry, label)] = rec
        if flush:
            self.flush()

    def flush(self) -> None:
        try:
            with open(self.path, "w") as f:
                json.dump(self._data, f, indent=1, sort_keys=True)
        except Exception:
            pass  # cache is an optimization, never a failure


def geometry_sig(cfg, seq: int, n_devices: int = 1) -> str:
    return (f"L{cfg.num_layers}h{cfg.hidden_size}H{cfg.num_heads}"
            f"kv{cfg.num_kv_heads}d{cfg.head_dim}v{cfg.vocab_size}"
            f"s{seq}n{n_devices}")


@dataclass
class SearchResult:
    winner: str | None = None
    tokens_per_sec: float = 0.0
    trace: list[dict] = field(default_factory=list)
    space_size: int = 0
    pruned: int = 0
    measured: int = 0          # successful measurements only
    failed: int = 0            # measure attempts that raised
    analysis_seconds: float = 0.0

    def tried_rows(self) -> list[dict]:
        """The bench's ``tried`` spelling of the trace (one row per
        candidate, measured rows carrying throughput + HBM provenance)."""
        return self.trace


def _score(cand: Candidate, cfg, predicted_bytes: int,
           budget: int | None) -> float:
    """Throughput prior for ranking (NOT a prediction of tok/s): larger
    microbatches amortize per-step overhead with diminishing returns,
    recompute-heavy remat policies pay their FLOPs factor, grad
    accumulation adds per-microbatch launch overhead, and HBM pressure
    derates: configs predicted past ~82% of budget underperform on chip
    (r05: b8/attn 9% and b4/dots 3% slower than b4/attn while the lighter
    b4/attn+ was fastest — XLA trades speed for fit as headroom shrinks).
    The derate constants are fit to exactly that measured r05 ordering."""
    mb = max(1, cand.batch // max(1, cand.grad_accum))
    eff = mb / (mb + 0.35)
    flops = remat_flops_factor(cand.remat, cfg.num_layers)
    accum = 0.99 ** (cand.grad_accum - 1)
    zero1 = 0.995 if cand.zero1 else 1.0
    score = eff * accum * zero1 * cand.batch ** 0.02 / flops
    if budget:
        frac = predicted_bytes / budget
        if frac > 0.82:
            score *= max(0.6, 1.0 - 1.2 * (frac - 0.82))
    return score


def autotune_train_configs(
    cfg,
    seq: int,
    candidates: list[Candidate],
    *,
    hbm_budget_bytes: int | None,
    measure_fn=None,
    max_measure: int = 6,
    cache: AutotuneCache | None = None,
    device_kind: str = "unknown",
    n_devices: int = 1,
    prune_margin: float = 1.05,
) -> SearchResult:
    """Run the search. ``measure_fn(cand) -> dict`` measures one candidate
    (keys: ``tokens_per_sec`` and optionally ``measured_hbm_bytes``,
    ``hbm_source``; raise on failure) — pass None for analysis-only mode
    (CI smoke / CPU hosts): everything is predicted, pruned and ranked,
    nothing measured.

    ``prune_margin``: a candidate is pruned only when its prediction
    exceeds budget * margin — the analytic model overestimates by design
    (see autotune/model.py), and a kept-but-OOM candidate costs one failed
    AOT attempt while a wrongly pruned one silently loses the win."""
    t0 = time.monotonic()
    res = SearchResult(space_size=len(candidates))
    geo = geometry_sig(cfg, seq, n_devices)
    scored: list[tuple[float, Candidate, dict]] = []

    for cand in candidates:
        pred = predict_hbm(cfg, seq, cand, data_shards=n_devices)
        row: dict = {"config": cand.label,
                     "predicted_hbm_gb": pred.total_gb}
        if hbm_budget_bytes and \
                pred.total_bytes > hbm_budget_bytes * prune_margin:
            row["pruned"] = True
            res.pruned += 1
            res.trace.append(row)
            continue
        cached = cache.get(device_kind, geo, cand.label) if cache else None
        if cached and cached.get("tokens_per_sec"):
            row["cached_tokens_per_sec"] = cached["tokens_per_sec"]
        row["score"] = round(_score(cand, cfg, pred.total_bytes,
                                    hbm_budget_bytes), 4)
        scored.append((row["score"], cand, row))
        res.trace.append(row)
    res.analysis_seconds = round(time.monotonic() - t0, 3)

    if measure_fn is None:
        # analysis-only: rank by prior (cached measurements win first)
        scored.sort(key=lambda t: (t[2].get("cached_tokens_per_sec", 0.0),
                                   t[0]), reverse=True)
        if scored:
            res.winner = scored[0][1].label
            res.tokens_per_sec = scored[0][2].get("cached_tokens_per_sec",
                                                  0.0)
        return res

    # Measurement order: the best CACHED config first (banks a number
    # early — the r03 lesson: a tunnel outage mid-sweep must not leave the
    # round without a headline), then the unmeasured frontier by prior.
    cached_rows = [t for t in scored if "cached_tokens_per_sec" in t[2]]
    fresh_rows = [t for t in scored if "cached_tokens_per_sec" not in t[2]]
    cached_rows.sort(key=lambda t: t[2]["cached_tokens_per_sec"],
                     reverse=True)
    fresh_rows.sort(key=lambda t: t[0], reverse=True)
    order = cached_rows[:1] + fresh_rows + cached_rows[1:]

    best = (0.0, None)
    for _, cand, row in order[:max_measure]:
        try:
            m = measure_fn(cand)
        except Exception as e:  # noqa: BLE001 - one candidate, not the sweep
            row["error"] = str(e)[:160]
            res.failed += 1
            # surface live (the trace row is truncated and only lands in
            # the final record): an operator watching a TPU round needs
            # the OOM/compile error as it happens
            print(f"autotune candidate {cand.label} failed: {str(e)[:400]}",
                  file=sys.stderr)
            continue
        res.measured += 1
        row.update({k: v for k, v in m.items() if v is not None})
        tps = float(m.get("tokens_per_sec") or 0.0)
        if cache is not None and tps > 0:
            cache.put(device_kind, geo, cand.label, m)
        if tps > best[0]:
            best = (tps, cand.label)
    # provenance for rows that were in budget but not measured this round
    for _, _cand, row in order[max_measure:]:
        row.setdefault("skipped", "measure_budget")

    res.tokens_per_sec, res.winner = best
    if res.winner is None and cached_rows:
        # every measurement failed: fall back to the cached champion
        res.winner = cached_rows[0][1].label
        res.tokens_per_sec = cached_rows[0][2]["cached_tokens_per_sec"]
    return res
