"""Host (CPU) collective backend: rendezvous + reduction through a named
actor — the gloo-equivalent fallback for actors and tests.

Capability parity with the reference's CPU backend (reference:
python/ray/util/collective/collective_group/torch_gloo_collective_group.py,
rendezvous shape from nccl_collective_group.py Rendezvous :29 which exchanges
state through a named Ray actor): each rank calls the op with its local
array; a per-group coordination actor (async, so ranks interleave) gathers
world_size contributions, computes the result, and releases all waiters.
Correctness over speed — the fast path on TPU is the XLA backend.
"""

from __future__ import annotations

import asyncio

import numpy as np


class _GroupCoordinator:
    """Async actor: one instance per collective group (named actor)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._rounds: dict[str, dict] = {}
        self._lock = asyncio.Lock()

    def _round(self, key: str) -> dict:
        r = self._rounds.get(key)
        if r is None:
            r = {"parts": {}, "event": asyncio.Event(), "result": None}
            self._rounds[key] = r
        return r

    async def contribute(self, key: str, rank: int, data, op: str):
        async with self._lock:
            r = self._round(key)
            r["parts"][rank] = data
            if len(r["parts"]) == self.world_size:
                r["result"] = self._combine(r["parts"], op)
                r["event"].set()
        await r["event"].wait()
        result = r["result"]
        async with self._lock:
            r["waiters"] = r.get("waiters", 0) + 1
            if r["waiters"] == self.world_size:
                self._rounds.pop(key, None)  # round complete: free memory
        return result if not isinstance(result, dict) else result.get(rank)

    def _combine(self, parts: dict[int, object], op: str):
        ordered = [np.asarray(parts[r]) for r in sorted(parts)]
        if op == "sum":
            return sum(ordered[1:], ordered[0].copy())
        if op == "max":
            return np.maximum.reduce(ordered)
        if op == "min":
            return np.minimum.reduce(ordered)
        if op == "gather":
            return np.concatenate(ordered, axis=0)
        if op == "alltoall":
            # rank r receives chunk r of every rank's array, concatenated
            n = self.world_size
            out = {}
            for r in range(n):
                chunks = [np.array_split(p, n, axis=0)[r] for p in ordered]
                out[r] = np.concatenate(chunks, axis=0)
            return out
        if op == "barrier":
            return 0
        if op.startswith("broadcast"):
            src = int(op.split(":")[1])
            return np.asarray(parts[src])
        if op.startswith("reducescatter"):
            red = sum(ordered[1:], ordered[0].copy())
            return {r: np.array_split(red, self.world_size, axis=0)[r]
                    for r in range(self.world_size)}
        raise ValueError(f"unknown op {op}")

    async def p2p_put(self, key: str, data):
        async with self._lock:
            r = self._round(key)
            r["result"] = data
            r["event"].set()
        return True

    async def p2p_take(self, key: str):
        r = self._round(key)
        await r["event"].wait()
        async with self._lock:
            self._rounds.pop(key, None)
        return r["result"]


class HostCollectiveGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        import ray_tpu

        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._seq = 0
        self._p2p_seq: dict[tuple[int, int], int] = {}
        actor_name = f"_rtpu_collective:{group_name}"
        try:
            self._coord = ray_tpu.get_actor(actor_name)
        except ValueError:
            Coordinator = ray_tpu.remote(_GroupCoordinator)
            try:
                self._coord = Coordinator.options(
                    name=actor_name, num_cpus=0
                ).remote(world_size)
            except ValueError:
                self._coord = ray_tpu.get_actor(actor_name)  # lost the race

    def _key(self, op: str) -> str:
        self._seq += 1
        return f"{op}:{self._seq}"

    def _run(self, op_tag: str, data, op: str):
        import ray_tpu

        return ray_tpu.get(
            self._coord.contribute.remote(self._key(op_tag), self.rank, data, op),
            timeout=120,
        )

    def allreduce(self, x, op: str = "sum"):
        return self._run("ar", np.asarray(x), op)

    def allgather(self, x):
        return self._run("ag", np.asarray(x), "gather")

    def reducescatter(self, x, op: str = "sum"):
        return self._run("rs", np.asarray(x), f"reducescatter:{op}")

    def alltoall(self, x):
        return self._run("a2a", np.asarray(x), "alltoall")

    def broadcast(self, x, src_rank: int = 0):
        return self._run("bc", np.asarray(x), f"broadcast:{src_rank}")

    def reduce(self, x, dst_rank: int = 0, op: str = "sum"):
        return self._run("rd", np.asarray(x), op)

    def barrier(self):
        self._run("bar", 0, "barrier")

    def send(self, x, dst_rank: int):
        import ray_tpu

        pair = (self.rank, dst_rank)
        self._p2p_seq[pair] = self._p2p_seq.get(pair, 0) + 1
        key = f"p2p:{pair[0]}->{pair[1]}:{self._p2p_seq[pair]}"
        ray_tpu.get(self._coord.p2p_put.remote(key, np.asarray(x)), timeout=120)

    def recv(self, shape, dtype, src_rank: int):
        import ray_tpu

        pair = (src_rank, self.rank)
        self._p2p_seq[pair] = self._p2p_seq.get(pair, 0) + 1
        key = f"p2p:{pair[0]}->{pair[1]}:{self._p2p_seq[pair]}"
        return ray_tpu.get(self._coord.p2p_take.remote(key), timeout=120)

    def destroy(self):
        pass
