"""Per-worker training session: context + report API.

Capability parity with the reference's session (reference:
ray.train.get_context / ray.train.report — python/ray/train/v2/_internal/
execution/context.py shapes; report flows to the controller's checkpoint
manager, SURVEY.md §3.4 step 4).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

# Per-worker step-time window feeding straggler attribution (the head ranks
# workers from the decile summaries streamed with every telemetry push).
_STEP_WINDOW = 256


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = "train"
    storage_path: str | None = None
    trial_dir: str | None = None
    coordinator_addr: str | None = None
    restart_count: int = 0
    latest_checkpoint: str | None = None  # dir path, set on restore
    # Multi-slice topology (from JaxBackendConfig.num_slices): lets a
    # train_fn build its hybrid mesh / pick dcn_axes for the spmd step
    # without re-deriving the slice count from MEGASCALE env.
    num_slices: int = 1
    # Replica plane wiring from the controller (None = replication off):
    # {"run": store name prefix, "every": push every N steps,
    #  "num_slices": buddy-mapping slice count,
    #  "restore_step": step to restore from on a fast restart (None unless
    #  the controller chose the replica tier)}.
    replica: dict | None = None

    # filled by the worker harness
    dataset_shards: dict = field(default_factory=dict)  # name -> DataIterator
    _replica_writer: Any = None  # lazy ReplicaWriter (train/replica.py)
    # Goodput RankLedger (observability/goodput.py), attached by
    # set_context when the ledger gate is on; its snapshot rides this
    # rank's train-stats row with every telemetry push.
    _goodput: Any = None
    _reports: list[dict] = field(default_factory=list)
    _report_lock: threading.Lock = field(default_factory=threading.Lock)
    _last_report_ts: float = 0.0  # monotonic ts of the previous report()
    # Rolling per-step timing window: (step_time, sync_s, compute_s) per
    # report(); summarized into deciles for the head's straggler table.
    _step_window: deque = field(
        default_factory=lambda: deque(maxlen=_STEP_WINDOW))
    _steps_total: int = 0

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_num_slices(self) -> int:
        return self.num_slices

    def get_checkpoint(self) -> str | None:
        return self.latest_checkpoint

    def get_replica_state(self):
        """On a replica-tier fast restart: this rank's in-cluster state
        shard as a :class:`ray_tpu.train.replica.ReplicaState` (``.step``,
        ``.state``); None otherwise. Check it BEFORE get_checkpoint() —
        replicas are newer than (or equal to) the latest checkpoint
        whenever the controller picked this tier."""
        rep = self.replica
        if not rep or rep.get("restore_step") is None:
            return None
        from ray_tpu.train.replica import fetch_replica_state

        return fetch_replica_state(rep, self.world_rank, self.world_size)

    def get_dataset_shard(self, name: str = "train"):
        """This worker's streaming split of a Trainer dataset (reference:
        ray.train.get_dataset_shard — v2 DataParallelTrainer datasets= are
        streaming_split across the worker group)."""
        if name not in self.dataset_shards:
            raise KeyError(
                f"no dataset {name!r}; Trainer(datasets={{...}}) keys: "
                f"{sorted(self.dataset_shards)}")
        return self.dataset_shards[name]


_local = threading.local()

# rank -> its LIVE TrainContext (last-write-wins across restarts): the
# telemetry flusher reads step-stat summaries from here without holding a
# reference into any particular worker thread. Only live contexts are held
# strongly — a finished run is summarized into a plain row at
# set_context(None) time (below), never pinned (a TrainContext holds the
# run's dataset shards).
_stats_registry: dict[int, TrainContext] = {}
# rank -> (monotonic finish time, final summary row). The final window
# stays streamable for a bounded grace (a short run can end before the
# flusher's next tick — dropping it instantly would lose the run's stats
# entirely), then the rank is evicted so the telemetry idle-skip resumes
# and the head row ages out of the straggler report instead of being
# re-stamped forever.
_stats_final: dict[int, tuple[float, dict]] = {}
_FINISHED_GRACE_S = 60.0
_stats_lock = threading.Lock()


def _prune_final_locked(now_m: float) -> None:
    for rank, (t0, _row) in list(_stats_final.items()):
        if now_m - t0 > _FINISHED_GRACE_S:
            _stats_final.pop(rank)


def set_context(ctx: TrainContext | None) -> None:
    import time as _time

    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    # Goodput ledger lifecycle, BEFORE the final-row summarize below so a
    # finishing run's row carries its closed (tail → idle) ledger.
    try:
        from ray_tpu.observability import goodput as _goodput

        if prev is not None and prev is not ctx:
            _goodput.detach(prev)
        if ctx is not None and ctx is not prev and ctx._goodput is None:
            _goodput.attach(ctx)
    except Exception:
        pass  # the ledger must never break context setup
    now_m = _time.monotonic()
    with _stats_lock:
        _prune_final_locked(now_m)
        if ctx is not None:
            _stats_registry[ctx.world_rank] = ctx
            _stats_final.pop(ctx.world_rank, None)
        elif prev is not None and \
                _stats_registry.get(prev.world_rank) is prev:
            # Guarded so a restart that already took the rank
            # (last-write-wins) isn't evicted by the old run's cleanup.
            _stats_registry.pop(prev.world_rank)
            row = _summarize_steps(prev)
            if row is not None:
                _stats_final[prev.world_rank] = (now_m, row)


def get_context() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError("ray_tpu.train.get_context() called outside a train worker")
    return ctx


_train_metrics = None
_train_metrics_lock = threading.Lock()


def _get_train_metrics():
    """Lazy singletons: the gauges every report() updates. Created on the
    worker that actually trains, so the federated /metrics shows them under
    that worker's node_id (reference capability: the per-chip tokens/sec and
    MFU numbers papers headline — PAPERS.md Gemma-on-TPU — readable off one
    endpoint instead of living in code comments)."""
    global _train_metrics
    with _train_metrics_lock:
        if _train_metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _train_metrics = {
                "step_time": Gauge(
                    "train_step_time_s",
                    "seconds between consecutive session.report() calls "
                    "(the per-step wall time when reporting per step)",
                    tag_keys=("rank",)),
                "tokens_per_s": Gauge(
                    "train_tokens_per_s",
                    "training throughput: reported tokens / step time",
                    tag_keys=("rank",)),
                "mfu": Gauge(
                    "train_mfu",
                    "achieved model FLOPs utilization (0..1): reported "
                    "flops / step time / peak_flops",
                    tag_keys=("rank",)),
                "reports": Counter(
                    "train_reports_total", "session.report() calls",
                    tag_keys=("rank",)),
            }
        return _train_metrics


def _instrument_report(ctx: TrainContext, metrics: dict[str, Any]) -> None:
    """Derive step-time / tokens-per-sec / MFU gauges from a report.
    Recognized keys: ``tokens`` (or ``tokens_per_step``) per step, ``flops``
    (or ``flops_per_step``) per step, ``peak_flops`` (else the
    accelerators/flops.py registry: RTPU_PEAK_FLOPS override or the
    generation table keyed by the backend's device_kind), and direct
    ``tokens_per_s`` / ``mfu`` passthroughs. Goodput keys (all optional,
    seconds within this step): ``sync_time_s`` → collective_wait,
    ``compute_time_s`` → step_compute (remainder → idle),
    ``input_wait_s``, ``compile_time_s``, ``checkpoint_time_s``."""
    import time

    m = _get_train_metrics()
    rank = {"rank": str(ctx.world_rank)}
    m["reports"].inc(tags=rank)
    now = time.monotonic()
    last, ctx._last_report_ts = ctx._last_report_ts, now
    step_time = (now - last) if last else 0.0
    sync = metrics.get("sync_time_s")
    compute = metrics.get("compute_time_s")
    if step_time > 0:
        m["step_time"].set(step_time, tags=rank)
        # _report_lock: the telemetry flusher snapshots this window from
        # another thread, and list(deque) raises if an append lands
        # mid-iteration once the window is full.
        with ctx._report_lock:
            ctx._step_window.append((
                step_time,
                float(sync) if sync is not None else None,
                float(compute) if compute is not None else None,
            ))
            ctx._steps_total += 1
    if ctx._goodput is not None:
        # Close this report's ledger interval: explicit per-step keys
        # merge with seconds the hooks (compile listener, checkpoint
        # writer, replicate, input_wait) stamped since the last close.
        ctx._goodput.close_interval(parts={
            "collective_wait": sync,
            "step_compute": compute,
            "input_wait": metrics.get("input_wait_s"),
            "compile": metrics.get("compile_time_s"),
            "checkpoint": metrics.get("checkpoint_time_s"),
        })
    if "tokens_per_s" in metrics:
        m["tokens_per_s"].set(float(metrics["tokens_per_s"]), tags=rank)
    elif step_time > 0:
        tokens = metrics.get("tokens", metrics.get("tokens_per_step"))
        if tokens:
            m["tokens_per_s"].set(float(tokens) / step_time, tags=rank)
    if "mfu" in metrics:
        m["mfu"].set(float(metrics["mfu"]), tags=rank)
    elif step_time > 0:
        flops = metrics.get("flops", metrics.get("flops_per_step"))
        peak = metrics.get("peak_flops")
        if flops and not peak:
            from ray_tpu.accelerators.flops import resolve_peak_flops

            peak = resolve_peak_flops()
        if flops and peak:
            m["mfu"].set(float(flops) / step_time / float(peak), tags=rank)


def report(metrics: dict[str, Any], checkpoint: str | None = None) -> None:
    """Report metrics (and optionally a checkpoint directory the worker has
    already written) to the controller. Non-blocking; the controller collects
    reports when it polls. Also feeds the train gauges
    (train_step_time_s / train_tokens_per_s / train_mfu) so throughput is
    readable off /metrics, not just the report stream."""
    ctx = get_context()
    _maybe_chaos(ctx, metrics)
    try:
        _instrument_report(ctx, metrics)
    except Exception:
        pass  # metrics must never fail a training step
    with ctx._report_lock:
        # "ts" is the worker-stamped report instant: the controller closes
        # restart-downtime windows on it instead of its own observation
        # time, so poll/RPC delivery lag never inflates the attribution.
        ctx._reports.append({"metrics": dict(metrics), "checkpoint": checkpoint,
                             "ts": time.time()})


def _maybe_chaos(ctx: TrainContext, metrics: dict[str, Any]) -> None:
    """train.step fault-injection probe: every report() is a step boundary,
    so a scheduled worker/slice kill — or a delay rule, i.e. an injected
    straggler — lands here, mid-run, inside the target process. Attrs
    exposed to rule predicates: rank, slice, step, restart."""
    from ray_tpu.chaos import injector as _chaos

    if not _chaos.ACTIVE:
        return
    from ray_tpu.train.replica import slice_of

    _chaos.maybe_kill(
        "train.step",
        rank=ctx.world_rank,
        slice=slice_of(ctx.world_rank, ctx.world_size, ctx.num_slices),
        step=metrics.get("step", ctx._steps_total),
        restart=ctx.restart_count,
    )


def replicate(state: Any, step: int) -> bool:
    """Replicate this rank's training state to its buddy slice's
    :class:`~ray_tpu.train.replica.ReplicaStore` through the object plane.
    Cheap by construction: the state is snapshotted to host memory inline
    (donation-safe) and pushed from a background thread — the train step
    never waits on the wire. Honors the controller's ``replicate_every``
    cadence (CheckpointConfig.replicate_every; steps off-cadence are
    skipped). Under ZeRO-1 pass the optimizer/param shards this worker
    owns (e.g. ``spmd.replica_payload(state)``) — they are already 1/N of
    the run's state, so replication costs one buddy hop of the same bytes
    the DCN all-gather moves every step. Returns True when a push was
    queued."""
    ctx = get_context()
    rep = ctx.replica
    if not rep or int(rep.get("every", 0) or 0) <= 0:
        return False
    if int(step) % int(rep["every"]) != 0:
        return False
    if ctx._replica_writer is None:
        from ray_tpu.train.replica import ReplicaWriter

        ctx._replica_writer = ReplicaWriter(
            rep["run"], ctx.world_rank, ctx.world_size,
            int(rep.get("num_slices", ctx.num_slices)))
    # The push itself is async; only the inline host snapshot + queue
    # time is the step's replication cost — stamp it on the ledger.
    import time as _time

    t0 = _time.perf_counter()
    try:
        return ctx._replica_writer.put(state, step)
    finally:
        if ctx._goodput is not None:
            ctx._goodput.add_pending(
                "replication_push", _time.perf_counter() - t0)


def drain_reports(ctx: TrainContext) -> list[dict]:
    with ctx._report_lock:
        out, ctx._reports = ctx._reports, []
    return out


def collect_train_stats() -> dict:
    """Per-rank step-time/sync-time summaries for the head's straggler
    table, streamed with every telemetry push. Deciles are computed over
    the rolling window (p0..p100 inclusive, 11 values); sync/compute shares
    come from ``sync_time_s``/``compute_time_s`` keys passed to report()
    when the train loop measures them (None when it doesn't)."""
    import time as _time

    out: dict[str, dict] = {}
    now_m = _time.monotonic()
    with _stats_lock:
        _prune_final_locked(now_m)
        contexts = dict(_stats_registry)
        finals = {rank: row for rank, (_t0, row) in _stats_final.items()}
    for rank, ctx in contexts.items():
        row = _summarize_steps(ctx)
        if row is not None:
            out[str(rank)] = row
    for rank, row in finals.items():
        out.setdefault(str(rank), row)
    return out


def _summarize_steps(ctx: TrainContext) -> dict | None:
    """One rank's summary row from its rolling step window (None when the
    run never reported a timed step)."""
    import time as _time

    with ctx._report_lock:  # pairs with the append in _instrument_report
        window = list(ctx._step_window)
    if not window:
        return None
    ts = sorted(t for t, _, _ in window)
    n = len(ts)
    deciles = [ts[min(n - 1, round(q * (n - 1) / 10))]
               for q in range(11)]
    # Shares are ratios over only the steps that REPORTED the numerator
    # — a loop that instruments sync_time_s every Nth step must not get
    # its share diluted by the uninstrumented steps' time (which would
    # misattribute a collective-wait victim as compute-bound).
    syncs = [(t, s) for t, s, _ in window if s is not None]
    computes = [(t, c) for t, _, c in window if c is not None]

    def share(pairs):
        denom = sum(t for t, _ in pairs)
        return (sum(v for _, v in pairs) / denom) if denom else None

    total = sum(ts)
    row = {
        "world_size": ctx.world_size,
        "steps": ctx._steps_total,
        "mean_step_s": total / n,
        "median_step_s": deciles[5],
        "deciles": deciles,
        "sync_share": share(syncs),
        "compute_share": share(computes),
        "run": ctx.experiment_name,
        "ts": _time.time(),
    }
    # Goodput piggyback: the rank's cumulative ledger snapshot rides the
    # same row (no new RPC — the head's train-stats table carries it to
    # the GoodputStore rollup).
    if ctx._goodput is not None:
        try:
            row["goodput"] = ctx._goodput.snapshot()
        except Exception:  # noqa: BLE001 - accounting never breaks stats
            pass
    return row


def get_dataset_shard(name: str = "train"):
    """Module-level alias (reference: ray.train.get_dataset_shard)."""
    return get_context().get_dataset_shard(name)
