"""Chaos engineering: declarative fault injection for tests, devbench, and
live clusters (see :mod:`ray_tpu.chaos.injector` for the rule schema)."""

from ray_tpu.chaos.injector import (
    ChaosKilled,
    ChaosRule,
    clear,
    decide,
    fired,
    install,
    maybe_kill,
    reset_for_tests,
    status,
)

__all__ = [
    "ChaosKilled", "ChaosRule", "clear", "decide", "fired", "install",
    "maybe_kill", "reset_for_tests", "status",
]
