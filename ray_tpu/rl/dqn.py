"""DQN: off-policy Q-learning with replay and a target network, in pure JAX.

Capability parity with the reference's DQN family (reference:
rllib/algorithms/dqn/dqn.py + torch learner — replay buffer (optionally
prioritized), epsilon-greedy exploration schedule, target network sync,
double-DQN targets; Algorithm is a Tune Trainable): rollouts come from the
same EnvRunnerGroup as PPO, the update is a jitted JAX step, and the
Algorithm plugs into ray_tpu.tune unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.ppo import init_mlp, mlp_apply
from ray_tpu.rl.replay import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.tune.trainable import Trainable


@jax.jit
def _greedy_q(params, obs):
    return mlp_apply(params, obs)


@partial(jax.jit, static_argnums=(0, 1))
def dqn_update(optimizer, double_dqn, params, target_params, opt_state,
               batches, gamma):
    """K SGD steps on Huber TD error in ONE dispatch (lax.scan over stacked
    [K, B, ...] minibatches); returns per-sample |TD| for PER."""

    def one(carry, batch):
        p, os_ = carry

        def loss_fn(p):
            q = mlp_apply(p, batch["obs"])
            q_sa = jnp.take_along_axis(q, batch["actions"][:, None], 1)[:, 0]
            q_next_t = mlp_apply(target_params, batch["next_obs"])
            if double_dqn:
                # Online net picks the argmax, target net evaluates it.
                a_star = mlp_apply(p, batch["next_obs"]).argmax(-1)
                q_next = jnp.take_along_axis(q_next_t, a_star[:, None],
                                             1)[:, 0]
            else:
                q_next = q_next_t.max(-1)
            target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
                jax.lax.stop_gradient(q_next)
            td = q_sa - target
            w = batch.get("weights", jnp.ones_like(td))
            return (w * optax.huber_loss(q_sa, target)).mean(), td

        (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        updates, os_ = optimizer.update(grads, os_, p)
        return (optax.apply_updates(p, updates), os_), (loss, jnp.abs(td))

    (params, opt_state), (losses, tds) = jax.lax.scan(
        one, (params, opt_state), batches)
    return params, opt_state, losses[-1], tds


@dataclass
class DQNConfig:
    env: str = "CartPole-v1"
    num_env_runners: int = 0
    num_envs_per_runner: int = 8
    rollout_len: int = 16
    lr: float = 2.5e-3
    gamma: float = 0.99
    buffer_size: int = 50_000
    batch_size: int = 128
    learning_starts: int = 500        # env steps before SGD begins
    train_batches_per_step: int = 32  # SGD minibatches per step()
    target_update_freq: int = 2       # in step() iterations
    double_dqn: bool = True
    prioritized_replay: bool = False
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_steps: int = 2_000  # env steps to anneal over
    hidden: int = 64
    seed: int = 0
    extra: dict = field(default_factory=dict)

    def build(self) -> "DQN":
        return DQN({"dqn_config": self})


class DQN(Trainable):
    """EnvRunnerGroup sampling with epsilon-greedy exploration + replay +
    jitted TD updates (reference: dqn.py training_step shape)."""

    def setup(self, config: dict) -> None:
        cfg = config.get("dqn_config") or DQNConfig(
            **{k: v for k, v in config.items()
               if k in DQNConfig.__dataclass_fields__})
        self.cfg = cfg
        probe = make_env(cfg.env, seed=cfg.seed)
        obs_size, num_actions = probe.observation_size, probe.num_actions
        self.num_actions = num_actions
        key = jax.random.PRNGKey(cfg.seed)
        self.params = init_mlp(key, [obs_size, cfg.hidden, cfg.hidden,
                                     num_actions], scale_last=1.0)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        buf_cls = (PrioritizedReplayBuffer if cfg.prioritized_replay
                   else ReplayBuffer)
        self.buffer = buf_cls(cfg.buffer_size, obs_size, seed=cfg.seed)
        self.env_steps = 0

        num_actions_ = num_actions

        def policy_factory(params=None):
            # act params are (q_params, epsilon): runner actors receive the
            # annealed epsilon with each weight sync.
            def act(p, obs, seed):
                q_params, eps = p
                q = np.asarray(_greedy_q(q_params, jnp.asarray(obs)))
                greedy = q.argmax(-1)
                rng = np.random.default_rng(seed)
                explore = rng.random(len(greedy)) < eps
                rand = rng.integers(0, num_actions_, len(greedy))
                a = np.where(explore, rand, greedy)
                zeros = np.zeros(len(greedy), np.float32)
                return a.astype(np.int32), zeros, zeros
            return act, None

        self.runners = EnvRunnerGroup(
            cfg.env, num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_runner,
            rollout_len=cfg.rollout_len, policy_factory=policy_factory,
            seed=cfg.seed)
        self._return_window: list[float] = []

    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self.env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def step(self) -> dict:
        cfg = self.cfg
        samples = self.runners.sample((self.params, self._epsilon()))
        for s in samples:
            T, N = s["rewards"].shape
            # next_obs carries the TRUE pre-reset successors (truncation
            # bootstrapping must target V(final state), not V(reset state)).
            self.buffer.add_batch(
                s["obs"].reshape(T * N, -1), s["actions"].reshape(-1),
                s["rewards"].reshape(-1),
                s["next_obs"].reshape(T * N, -1),
                # True terminations only: TD targets bootstrap through
                # time-limit truncations (term/trunc split).
                s["terminals"].reshape(-1).astype(np.float32))
            self.env_steps += T * N
            self._return_window.extend(s["episode_returns"])

        loss = 0.0
        if self.env_steps >= cfg.learning_starts:
            raw = [self.buffer.sample(cfg.batch_size)
                   for _ in range(cfg.train_batches_per_step)]
            idxs = [b.pop("idx", None) for b in raw]
            batches = {k: jnp.asarray(np.stack([b[k] for b in raw]))
                       for k in raw[0]}
            self.params, self.opt_state, loss_j, tds = dqn_update(
                self.optimizer, cfg.double_dqn, self.params,
                self.target_params, self.opt_state, batches, cfg.gamma)
            loss = float(loss_j)
            if idxs[0] is not None:
                tds_np = np.asarray(tds)
                for idx, td in zip(idxs, tds_np):
                    self.buffer.update_priorities(idx, td)
            if self.iteration % cfg.target_update_freq == 0:
                self.target_params = jax.tree.map(jnp.copy, self.params)

        self._return_window = self._return_window[-100:]
        mean_ret = (float(np.mean(self._return_window))
                    if self._return_window else 0.0)
        return {
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled": self.env_steps,
            "epsilon": self._epsilon(),
            "td_loss": loss,
            "buffer_size": len(self.buffer),
        }

    def save_checkpoint(self) -> Any:
        return {"params": jax.tree.map(np.asarray, self.params),
                "target": jax.tree.map(np.asarray, self.target_params),
                "env_steps": self.env_steps, "iteration": self.iteration}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, checkpoint["params"])
        self.target_params = jax.tree.map(jnp.asarray, checkpoint["target"])
        self.env_steps = checkpoint["env_steps"]
        self.iteration = checkpoint["iteration"]

    def cleanup(self) -> None:
        self.runners.shutdown()
