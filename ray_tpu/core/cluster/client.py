"""Driver-side cluster bootstrap.

Capability parity with the reference's driver connect path (reference:
python/ray/_private/worker.py connect :2476 + node.py start_ray_processes
:1351 for standalone `ray.init()` which launches gcs + raylet): connecting
with ``address="local-cluster"`` boots an in-process head + node daemon (the
daemon still forks real worker subprocesses); ``address="host:port"``
attaches to a running head and adopts one of its nodes as the local lease
target.
"""

from __future__ import annotations

import uuid

from ray_tpu.core.cluster.head import HeadServer
from ray_tpu.core.cluster.node_daemon import NodeDaemon
from ray_tpu.core.cluster.protocol import EventLoopThread, RpcClient
from ray_tpu.core.cluster.runtime import ClusterRuntime


class _LocalClusterHandles:
    """Keeps head/daemon alive for a driver-embedded cluster; torn down on
    runtime.shutdown()."""

    def __init__(self, head: HeadServer, daemons: list[NodeDaemon]):
        self.head = head
        self.daemons = daemons


def start_head(host: str = "127.0.0.1", port: int = 0,
               persist_path: str | None = None) -> HeadServer:
    io = EventLoopThread.get()
    head = HeadServer(host, port, persist_path=persist_path)
    io.run(head.start())
    return head


def start_node(head_host: str, head_port: int, resources: dict[str, float],
               labels: dict[str, str] | None = None,
               node_id: str | None = None) -> NodeDaemon:
    io = EventLoopThread.get()
    daemon = NodeDaemon(head_host, head_port, node_id or uuid.uuid4().hex,
                        resources, labels)
    io.run(daemon.start())
    return daemon


def connect_cluster(address: str, num_cpus: float | None = None,
                    resources: dict[str, float] | None = None) -> ClusterRuntime:
    if address == "local-cluster":
        totals = {"CPU": float(num_cpus if num_cpus is not None else 8)}
        totals.update(resources or {})
        head = start_head()
        daemon = start_node(head.rpc.host, head.rpc.port, totals)
        rt = ClusterRuntime(head.rpc.host, head.rpc.port,
                            node_daemon_addr=(daemon.rpc.host, daemon.rpc.port),
                            shm_name=daemon.shm_name)
        rt._local_cluster = _LocalClusterHandles(head, [daemon])
        _wrap_shutdown(rt)
        return rt
    host, port = address.rsplit(":", 1)
    # Adopt the first alive node as the local lease target. Retrying: a
    # driver attaching while the head is mid-restart (or briefly
    # partitioned) should ride the blip out, not fail `init()`.
    probe = RpcClient(host, int(port))
    nodes = probe.call_retrying("list_nodes", idempotent=True)
    probe.close()
    daemon_addr = None
    for info in nodes.values():
        if info["alive"]:
            daemon_addr = tuple(info["addr"])
            break
    shm_name = None
    if daemon_addr is not None:
        try:
            dprobe = RpcClient(*daemon_addr)
            shm_name = dprobe.call("node_info").get("shm_name")
            dprobe.close()
        except Exception:
            shm_name = None
    rt = ClusterRuntime(host, int(port), node_daemon_addr=daemon_addr,
                        shm_name=shm_name)
    return rt


def _wrap_shutdown(rt: ClusterRuntime):
    io = EventLoopThread.get()
    handles: _LocalClusterHandles = rt._local_cluster
    orig = rt.shutdown

    def shutdown():
        orig()
        for d in handles.daemons:
            try:
                io.run(d.stop())
            except Exception:
                pass
        try:
            io.run(handles.head.stop())
        except Exception:
            pass

    rt.shutdown = shutdown
