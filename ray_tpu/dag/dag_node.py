"""Lazy DAGs of actor-method calls.

Capability parity with the reference's ray.dag (reference: python/ray/dag/
dag_node.py:32 DAGNode — bind() builds the graph lazily; InputNode marks the
per-execution input, MultiOutputNode fans multiple leaves out;
``experimental_compile`` (dag_node.py:279) turns the graph into a CompiledDAG
with static per-actor schedules instead of per-call RPC).

Uncompiled execution (``dag.execute(x)``) walks the graph submitting ordinary
actor tasks — same semantics, per-call overhead. Compiling is the fast path.
"""

from __future__ import annotations

import itertools
from typing import Any

_node_counter = itertools.count()


class DAGNode:
    """Base: a lazily-bound computation with upstream dependencies."""

    # Optional per-actor execution order. When EVERY op bound to an actor
    # carries a rank, CompiledDAG._compile sorts that actor's op list by it
    # (ties broken by graph walk order); otherwise walk order stands. This
    # is how pipeline schedules (ray_tpu/dag/schedule.py) interleave
    # microbatch forwards/backwards instead of running chains serially.
    schedule_rank: int | None = None

    def __init__(self):
        self.node_id = next(_node_counter)

    # -- graph structure ---------------------------------------------------
    def upstream(self) -> list["DAGNode"]:
        return []

    def walk(self) -> list["DAGNode"]:
        """All reachable nodes, deduped, topologically ordered (deps first)."""
        seen: dict[int, DAGNode] = {}
        order: list[DAGNode] = []

        def visit(node: DAGNode):
            if node.node_id in seen:
                return
            seen[node.node_id] = node
            for up in node.upstream():
                visit(up)
            order.append(node)

        visit(self)
        return order

    # -- execution ---------------------------------------------------------
    def execute(self, *input_values):
        """Eager (uncompiled) execution: submits regular actor tasks."""
        import ray_tpu

        results: dict[int, Any] = {}
        for node in self.walk():
            results[node.node_id] = node._eval(results, input_values)
        out = results[self.node_id]
        if isinstance(out, list):
            return ray_tpu.get(out) if any(
                hasattr(r, "id") for r in out) else out
        return ray_tpu.get(out) if hasattr(out, "id") else out

    def _eval(self, results: dict, input_values: tuple):
        raise NotImplementedError

    def experimental_compile(self, **kwargs) -> "CompiledDAG":
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Placeholder for the per-execution input (reference: InputNode).

    Usable as a context manager for parity with the reference idiom:
        with InputNode() as inp:
            dag = actor.fwd.bind(inp)
    """

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc):
        return False

    def _eval(self, results, input_values):
        if len(input_values) == 1:
            return input_values[0]
        return input_values


class ClassMethodNode(DAGNode):
    """One bound actor-method call (reference: ClassMethodNode)."""

    def __init__(self, handle, method_name: str, args: tuple, kwargs: dict):
        super().__init__()
        self.handle = handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def upstream(self) -> list[DAGNode]:
        ups = [a for a in self.args if isinstance(a, DAGNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def _eval(self, results, input_values):
        import ray_tpu

        def mat(v):
            if isinstance(v, DAGNode):
                r = results[v.node_id]
                return ray_tpu.get(r) if hasattr(r, "id") else r
            return v

        args = tuple(mat(a) for a in self.args)
        kwargs = {k: mat(v) for k, v in self.kwargs.items()}
        return getattr(self.handle, self.method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Fans out several leaf nodes as the DAG's output list."""

    def __init__(self, outputs: list[DAGNode]):
        super().__init__()
        self.outputs = list(outputs)

    def upstream(self) -> list[DAGNode]:
        return list(self.outputs)

    def _eval(self, results, input_values):
        import ray_tpu

        out = []
        for node in self.outputs:
            r = results[node.node_id]
            out.append(ray_tpu.get(r) if hasattr(r, "id") else r)
        return out
