"""Serve layer tests (reference test model: python/ray/serve/tests/ —
test_deploy, test_autoscaling_policy, test_batching, test_proxy)."""

import json
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(autouse=True)
def _rt():
    ray_tpu.init()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_basic_deploy_and_call():
    @serve.deployment
    class Echo:
        def __call__(self, x):
            return f"echo:{x}"

        def shout(self, x):
            return f"ECHO:{x}"

    handle = serve.run(Echo.bind(), route_prefix=None)
    assert handle.remote("hi").result() == "echo:hi"
    assert handle.shout.remote("hi").result() == "ECHO:hi"


def test_function_deployment_and_init_args():
    @serve.deployment
    class Adder:
        def __init__(self, base):
            self.base = base

        def __call__(self, x):
            return self.base + x

    handle = serve.run(Adder.bind(10), route_prefix=None)
    assert handle.remote(5).result() == 15


def test_composition_handle_passing():
    @serve.deployment
    class Tokenizer:
        def __call__(self, text):
            return text.split()

    @serve.deployment
    class Pipeline:
        def __init__(self, tok):
            self.tok = tok

        def __call__(self, text):
            toks = self.tok.remote(text).result()
            return len(toks)

    handle = serve.run(Pipeline.bind(Tokenizer.bind()), route_prefix=None)
    assert handle.remote("a b c d").result() == 4


def test_multiple_replicas_spread_load():
    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __init__(self):
            import uuid
            self.id = uuid.uuid4().hex

        def __call__(self):
            return self.id

    handle = serve.run(WhoAmI.bind(), route_prefix=None)
    ids = {handle.remote().result() for _ in range(40)}
    assert len(ids) >= 2  # pow-2 routing reaches multiple replicas


def test_status_and_delete():
    @serve.deployment(num_replicas=2)
    class D:
        def __call__(self):
            return "ok"

    serve.run(D.bind(), route_prefix=None)
    st = serve.status()
    assert st["D"].status == "HEALTHY"
    assert st["D"].replica_states.get("RUNNING") == 2
    serve.delete()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and serve.status():
        time.sleep(0.05)
    assert serve.status() == {}


def test_rolling_update_version_change():
    def make(version_tag):
        @serve.deployment(name="V", version=version_tag)
        class V:
            def __call__(self):
                return version_tag

        return V

    h = serve.run(make("v1").bind(), route_prefix=None)
    assert h.remote().result() == "v1"
    h = serve.run(make("v2").bind(), route_prefix=None)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if h.remote().result() == "v2":
            break
        time.sleep(0.05)
    assert h.remote().result() == "v2"


def test_batching():
    @serve.deployment(max_ongoing_requests=16)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        def __call__(self, xs):
            self.batch_sizes.append(len(xs))
            return [x * 2 for x in xs]

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), route_prefix=None)
    results = [None] * 8
    threads = []

    def call(i):
        results[i] = handle.remote(i).result()

    for i in range(8):
        t = threading.Thread(target=call, args=(i,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    assert results == [i * 2 for i in range(8)]
    sizes = handle.sizes.remote().result()
    assert max(sizes) > 1  # batching actually coalesced concurrent calls


def test_autoscaling_up_and_down():
    @serve.deployment(
        max_ongoing_requests=4,
        autoscaling_config=dict(min_replicas=1, max_replicas=3,
                                target_ongoing_requests=1.0,
                                upscale_delay_s=0.2, downscale_delay_s=0.5,
                                metrics_interval_s=0.1),
        health_check_period_s=10.0,
    )
    class Slow:
        def __call__(self):
            time.sleep(0.4)
            return "done"

    handle = serve.run(Slow.bind(), route_prefix=None)
    st = serve.status()
    assert st["Slow"].replica_states.get("RUNNING") == 1

    stop = time.monotonic() + 4.0
    threads = [threading.Thread(
        target=lambda: [handle.remote().result() for _ in
                        iter(lambda: time.monotonic() < stop, False)])
        for _ in range(6)]
    for t in threads:
        t.start()
    peak = 1
    while time.monotonic() < stop:
        st = serve.status()
        peak = max(peak, st["Slow"].replica_states.get("RUNNING", 0))
        time.sleep(0.1)
    for t in threads:
        t.join()
    assert peak >= 2  # scaled up under load

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st = serve.status()
        if st["Slow"].replica_states.get("RUNNING") == 1 and \
                st["Slow"].status == "HEALTHY":
            break
        time.sleep(0.1)
    assert serve.status()["Slow"].replica_states.get("RUNNING") == 1


def test_replica_failure_recovers():
    @serve.deployment(num_replicas=1, health_check_period_s=0.1,
                      max_ongoing_requests=4)
    class Flaky:
        def __init__(self):
            self.healthy = True

        def poison(self):
            self.healthy = False

        def check_health(self):
            if not self.healthy:
                raise RuntimeError("poisoned")

        def __call__(self):
            return "alive"

    handle = serve.run(Flaky.bind(), route_prefix=None)
    assert handle.remote().result() == "alive"
    handle.poison.remote().result()
    # Controller must detect the failing health check and replace the
    # replica; the new one answers again.
    deadline = time.monotonic() + 15
    ok = False
    while time.monotonic() < deadline:
        try:
            if handle.remote().result(timeout=5) == "alive":
                st = serve.status()
                if st["Flaky"].status == "HEALTHY":
                    ok = True
                    break
        except Exception:
            pass
        time.sleep(0.1)
    assert ok


def test_http_ingress():
    @serve.deployment
    class App:
        def __call__(self, request: serve.Request):
            if request.method == "POST":
                data = request.json()
                return {"sum": data["a"] + data["b"]}
            return {"path": request.path,
                    "q": request.query_params.get("q")}

    serve.run(App.bind(), route_prefix="/", http=True)
    port = serve.http_port()

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/x/y?q=hello", timeout=30) as r:
        body = json.loads(r.read())
    assert body == {"path": "/x/y", "q": "hello"}

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", method="POST",
        data=json.dumps({"a": 2, "b": 3}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert json.loads(r.read()) == {"sum": 5}


def test_handle_streaming():
    """handle.options(stream=True) yields chunks as the replica produces
    them (reference: DeploymentResponseGenerator)."""
    @serve.deployment
    class Streamer:
        def chunks(self, n):
            for i in range(n):
                yield f"c{i}"

        def whole(self):
            return "complete"

    h = serve.run(Streamer.bind())
    gen = h.options(method_name="chunks", stream=True).remote(3)
    assert gen.streaming
    assert list(gen) == ["c0", "c1", "c2"]
    gen2 = h.options(method_name="whole", stream=True).remote()
    assert not gen2.streaming
    assert next(gen2) == "complete"


def test_http_sse_streaming():
    """An ingress generator method streams chunks over HTTP as SSE
    (reference: proxy.py:481 streaming response path)."""
    @serve.deployment
    class SSE:
        def __call__(self, request: serve.Request):
            def gen():
                for i in range(4):
                    yield f"data: tick{i}\n\n"
                    time.sleep(0.05)
            return gen()

    serve.run(SSE.bind(), route_prefix="/", http=True)
    port = serve.http_port()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/events", timeout=30) as r:
        assert r.headers.get("Content-Type", "").startswith("text/event-stream")
        first_at = None
        t0 = time.monotonic()
        body = b""
        while True:
            chunk = r.read1(256)  # read1: return as data arrives, no refill
            if not chunk:
                break
            if first_at is None:
                first_at = time.monotonic() - t0
            body += chunk
    text = body.decode()
    assert all(f"tick{i}" in text for i in range(4))
    # Incremental delivery: the first chunk must arrive well before the
    # ~0.2s it takes to produce all four.
    assert first_at is not None and first_at < 0.15


def test_model_multiplexing():
    """Many models share a replica pool: per-replica LRU + model-affinity
    routing (reference: serve.multiplexed / multiplexed_model_id)."""
    @serve.deployment(num_replicas=2, max_ongoing_requests=8)
    class MuxServer:
        def __init__(self):
            self.load_counts = {}

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            self.load_counts[model_id] = \
                self.load_counts.get(model_id, 0) + 1
            return {"id": model_id, "weights": model_id.upper()}

        def predict(self, x):
            model = self.get_model()
            return f"{model['weights']}:{x}"

        def loads(self):
            return dict(self.load_counts)

    h = serve.run(MuxServer.bind())
    h1 = h.options(method_name="predict", multiplexed_model_id="m1")
    h2 = h.options(method_name="predict", multiplexed_model_id="m2")
    assert h1.remote("a").result() == "M1:a"
    assert h2.remote("b").result() == "M2:b"
    # repeat calls reuse the cached model (affinity => same replica)
    for _ in range(4):
        assert h1.remote("c").result() == "M1:c"
    counts = h.options(method_name="loads",
                       multiplexed_model_id="m1").remote().result()
    assert counts.get("m1") == 1  # loaded exactly once on its home replica


def test_multiplex_lru_eviction():
    @serve.deployment(num_replicas=1)
    class Evicting:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            return model_id

        def which(self):
            from ray_tpu.serve.multiplex import get_multiplexed_model_id

            self.get_model()
            return get_multiplexed_model_id()

    h = serve.run(Evicting.bind())
    for mid in ("a", "b", "c", "a"):  # c evicts a; reloading a evicts b
        got = h.options(method_name="which",
                        multiplexed_model_id=mid).remote().result()
        assert got == mid


def test_route_hint_affinity():
    """The same route hint lands on the same replica while it has capacity
    (reference: prefix-aware routing policy shape)."""
    @serve.deployment(num_replicas=3, max_ongoing_requests=8)
    class Who:
        def __init__(self):
            import os

            self.pid_tag = f"{os.getpid()}-{id(self)}"

        def __call__(self, _req=None):
            return self.pid_tag

    h = serve.run(Who.bind())
    tags = {h.options(route_hint="prefix-xyz").remote().result()
            for _ in range(6)}
    assert len(tags) == 1  # all six routed to one replica


def test_grpc_ingress(rt_start):
    """gRPC data plane: proto-agnostic generic handler routes any method to
    the app ingress; unary and server-streaming both work (reference:
    _private/proxy.py gRPCProxy + grpc_servicer_functions)."""
    import grpc
    import json as _json

    from ray_tpu import serve

    @serve.deployment
    class Echo:
        def __call__(self, req):
            if req.metadata.get("streaming") == "1":
                def gen():
                    for i in range(3):
                        yield f"chunk{i}".encode()
                return gen()
            body = req.json() or {}
            return _json.dumps({"method": req.method,
                                "echo": body.get("x")}).encode()

    serve.run(Echo.bind(), route_prefix="/", grpc=True)
    try:
        port = serve.grpc_port()
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        unary = chan.unary_unary(
            "/test.Echo/Predict",
            request_serializer=None, response_deserializer=None)
        out = unary(_json.dumps({"x": 42}).encode(), timeout=30)
        parsed = _json.loads(out)
        assert parsed == {"method": "/test.Echo/Predict", "echo": 42}

        streamer = chan.unary_stream(
            "/test.Echo/Stream",
            request_serializer=None, response_deserializer=None)
        chunks = list(streamer(b"", metadata=(("streaming", "1"),),
                               timeout=30))
        assert chunks == [b"chunk0", b"chunk1", b"chunk2"]
        chan.close()
    finally:
        serve.shutdown()


def test_replica_placement_group(rt_start):
    """placement_group_bundles gives each replica a gang PG; the replica
    actor runs in bundle 0 and the PG is removed when the replica stops
    (reference: serve placement_group_bundles / ray.llm replica PGs)."""
    from ray_tpu import serve

    @serve.deployment(placement_group_bundles=[{"CPU": 1.0}, {"CPU": 1.0}],
                      placement_group_strategy="PACK")
    class Gang:
        def __call__(self, req):
            return "ok"

    serve.run(Gang.bind(), route_prefix="/")
    try:
        h = serve.get_app_handle()
        assert h.remote(None).result(timeout=30) == "ok"
        # a PG exists for the replica
        from ray_tpu.util.state.api import list_placement_groups
        pgs = list_placement_groups()
        assert any(p["state"] == "CREATED" for p in pgs), pgs
    finally:
        serve.shutdown()
    # after shutdown the replica PG is released
    from ray_tpu.util.state.api import list_placement_groups
    pgs = [p for p in list_placement_groups() if p["state"] == "CREATED"]
    assert not pgs, pgs


def test_grpc_only_app_no_http_route(rt_start):
    """A gRPC-only application (route_prefix=None) stays routable via the
    controller's app-ingress map (grpc_proxy.py update_routes)."""
    import grpc

    from ray_tpu import serve

    @serve.deployment
    class G:
        def __call__(self, req):
            return b"grpc-only"

    serve.run(G.bind(), name="gonly", route_prefix=None, grpc=True)
    try:
        chan = grpc.insecure_channel(f"127.0.0.1:{serve.grpc_port()}")
        unary = chan.unary_unary("/x.Y/Z", request_serializer=None,
                                 response_deserializer=None)
        assert unary(b"", metadata=(("application", "gonly"),),
                     timeout=30) == b"grpc-only"
        # single-app default routing works without metadata too
        assert unary(b"", timeout=30) == b"grpc-only"
        chan.close()
    finally:
        serve.shutdown()


def test_pg_options_validated_at_declaration():
    from ray_tpu import serve

    with pytest.raises(ValueError, match="strategy"):
        serve.deployment(placement_group_bundles=[{"CPU": 1}],
                         placement_group_strategy="pack")(object)
    with pytest.raises(ValueError, match="bundles"):
        serve.deployment(placement_group_bundles=[{}])(object)


def test_infeasible_pg_does_not_wedge_controller(rt_start):
    """An unsatisfiable gang PG must not block reconciliation: a healthy
    app deployed afterwards still comes up while the infeasible one stays
    pending (controller.py non-blocking PG startup)."""
    from ray_tpu import serve

    @serve.deployment(placement_group_bundles=[{"CPU": 512.0}])
    class Huge:
        def __call__(self, req):
            return "huge"

    @serve.deployment
    class Small:
        def __call__(self, req):
            return "small"

    import pytest as _pytest

    with _pytest.raises(TimeoutError):
        serve.run(Huge.bind(), name="huge", route_prefix="/huge",
                  _blocking_timeout=3.0)
    # the controller is still responsive: a normal app deploys fine
    serve.run(Small.bind(), name="small", route_prefix="/small")
    try:
        h = serve.get_deployment_handle("Small", app_name="small")
        assert h.remote(None).result(timeout=30) == "small"
    finally:
        serve.shutdown()


class TestRouterUnit:
    """Router-level tests without a cluster: load-aware hint affinity and
    event-driven admission (reference: _private/router.py assign loop wakes
    on events; prefix-aware policy's balance threshold)."""

    @staticmethod
    def _replicas(n, cap=4):
        from ray_tpu.serve.config import ReplicaInfo

        return [ReplicaInfo(replica_id=f"r{i}", deployment_name="d",
                            actor_name=f"a{i}", max_ongoing_requests=cap)
                for i in range(n)]

    def test_hint_yields_to_balance_when_overloaded(self):
        """A shared hint must not pin all traffic to one replica while its
        siblings idle: once the hinted replica is HINT_BALANCE_DELTA above
        the least-loaded, the router balances instead (ADVICE r3 medium)."""
        from ray_tpu.serve.router import Router

        router = Router("d", lambda: [])
        reps = self._replicas(3, cap=100)
        # Find which replica the hint prefers, then overload it.
        hinted = router._choose_locked(reps, route_hint="shared-prefix")
        router._inflight[hinted.replica_id] = \
            Router.HINT_BALANCE_DELTA + 1  # siblings at 0
        got = router._choose_locked(reps, route_hint="shared-prefix")
        assert got.replica_id != hinted.replica_id
        # Within the balance window the hint keeps its locality.
        router._inflight[hinted.replica_id] = Router.HINT_BALANCE_DELTA
        got = router._choose_locked(reps, route_hint="shared-prefix")
        assert got.replica_id == hinted.replica_id

    def test_saturated_assign_wakes_on_release(self, monkeypatch):
        """Admission is event-driven: a request parked on saturation is
        admitted promptly (condition notify, not a sleep-poll) when a
        slot frees."""
        import ray_tpu as _rt
        from ray_tpu.serve.router import Router

        reps = self._replicas(1, cap=2)
        router = Router("d", lambda: reps)
        router._inflight["r0"] = 2  # saturated

        class _FakeRef:
            pass

        class _FakeMethod:
            def remote(self, *a, **k):
                return _FakeRef()

        class _FakeHandle:
            handle_request = _FakeMethod()

        monkeypatch.setattr(_rt, "get_actor", lambda *a, **k: _FakeHandle())
        monkeypatch.setattr(_rt, "wait",
                            lambda *a, **k: ([], []))

        admitted = threading.Event()

        def _assign():
            router.assign_request("m", (), {}, timeout=10.0)
            admitted.set()

        t = threading.Thread(target=_assign, daemon=True)
        t.start()
        time.sleep(0.2)
        assert not admitted.is_set()  # genuinely parked
        t0 = time.perf_counter()
        router._release("r0")  # a request completed
        admitted.wait(timeout=2.0)
        dt = time.perf_counter() - t0
        assert admitted.is_set()
        assert dt < 0.1, f"wake took {dt*1e3:.1f} ms (poll, not notify?)"
