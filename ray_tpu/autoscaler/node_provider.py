"""Node providers: how the autoscaler obtains and releases machines.

Capability parity with the reference's provider layer (reference:
python/ray/autoscaler/node_provider.py NodeProvider ABC + cloud
implementations; the test workhorse FakeMultiNodeProvider
python/ray/autoscaler/_private/fake_multi_node/node_provider.py:237 fakes
node provisioning in-process): ``FakeMultiNodeProvider`` here launches REAL
in-process node daemons against a running head — scale-up genuinely adds
schedulable capacity — and ``TpuSliceProvider`` models GCE/GKE TPU slices as
atomic multi-host groups (whole-slice create/delete; the cloud API call is an
injectable hook so tests and air-gapped environments stub it).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import uuid
from typing import Callable


class NodeProvider:
    """Minimal provider surface the autoscaler drives."""

    def launch_node(self, node_type: str, resources: dict[str, float],
                    labels: dict[str, str] | None = None) -> str:
        """Begin provisioning one node; returns a cloud id."""
        raise NotImplementedError

    def terminate_node(self, cloud_id: str) -> None:
        raise NotImplementedError

    def node_status(self, cloud_id: str) -> str:
        """'pending' | 'running' | 'terminated' | 'failed'."""
        raise NotImplementedError

    def runtime_node_id(self, cloud_id: str) -> str | None:
        """The cluster node id once the node joined, else None."""
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches real in-process node daemons against the local head."""

    def __init__(self, head_addr: tuple[str, int]):
        self._head_addr = head_addr
        self._nodes: dict[str, dict] = {}

    def launch_node(self, node_type: str, resources: dict[str, float],
                    labels: dict[str, str] | None = None) -> str:
        from ray_tpu.core.cluster.client import start_node

        cloud_id = f"fake-{uuid.uuid4().hex[:8]}"
        daemon = start_node(self._head_addr[0], self._head_addr[1],
                            dict(resources), labels=labels)
        self._nodes[cloud_id] = {"daemon": daemon, "status": "running",
                                 "node_id": daemon.node_id}
        return cloud_id

    def terminate_node(self, cloud_id: str) -> None:
        rec = self._nodes.get(cloud_id)
        if rec is None or rec["status"] == "terminated":
            return
        from ray_tpu.core.cluster.protocol import EventLoopThread

        daemon = rec["daemon"]
        io = EventLoopThread.get()
        try:
            io.run(daemon._head.call("drain_node", node_id=daemon.node_id),
                   timeout=5)
        except Exception:
            pass
        try:
            io.run(daemon.stop(), timeout=5)
        except Exception:
            pass
        rec["status"] = "terminated"

    def node_status(self, cloud_id: str) -> str:
        rec = self._nodes.get(cloud_id)
        return rec["status"] if rec else "terminated"

    def runtime_node_id(self, cloud_id: str) -> str | None:
        rec = self._nodes.get(cloud_id)
        return rec["node_id"] if rec and rec["status"] == "running" else None


class SubprocessNodeProvider(NodeProvider):
    """Provisions 'machines' as real detached OS processes via the
    ``ray_tpu start`` bootstrap path (reference:
    fake_multi_node/node_provider.py:237, which boots real raylet
    processes). This is the e2e stand-in for cloud bootstrap: the provider
    allocates capacity, then a CommandRunner joins it to the cluster
    exactly the way a GCE startup script or SSH setup would — so the test
    exercises demand → provision → ``start`` → join → schedule."""

    def __init__(self, head_addr: str, base_temp_dir: str,
                 runner=None, python: str | None = None):
        from ray_tpu.autoscaler.command_runner import LocalCommandRunner

        self.head_addr = head_addr
        self.base_temp_dir = base_temp_dir
        self.runner = runner or LocalCommandRunner()
        self.python = python or sys.executable
        self._nodes: dict[str, dict] = {}  # cloud_id -> {node_id, temp_dir}

    def _pid(self, rec: dict) -> int | None:
        # Through the runner (not the local filesystem) so the same
        # provider works when the runner targets a remote host over SSH.
        # The pid is fixed for the node's lifetime — cache it so status
        # polls don't re-read the file (an SSH round-trip per poll).
        if rec.get("pid") is not None:
            return rec["pid"]
        path = os.path.join(rec["temp_dir"], f"node-{rec['node_id']}.pid")
        try:
            rec["pid"] = int(
                self.runner.run(["cat", path], timeout=20).strip())
        except Exception:
            return None
        return rec["pid"]

    def launch_node(self, node_type: str, resources: dict[str, float],
                    labels: dict[str, str] | None = None) -> str:
        node_id = f"sub-{uuid.uuid4().hex[:8]}"
        temp_dir = os.path.join(self.base_temp_dir, node_id)
        cmd = [self.python, "-m", "ray_tpu", "start",
               "--address", self.head_addr,
               "--node-id", node_id,
               "--temp-dir", temp_dir,
               "--num-cpus", str(resources.get("CPU", 1)),
               "--resources", json.dumps(
                   {k: v for k, v in resources.items() if k != "CPU"})]
        if labels:
            cmd += ["--labels", json.dumps(labels)]
        self.runner.run(cmd)
        cloud_id = f"subproc-{node_id}"
        self._nodes[cloud_id] = {"node_id": node_id, "temp_dir": temp_dir}
        return cloud_id

    def terminate_node(self, cloud_id: str) -> None:
        rec = self._nodes.get(cloud_id)
        if rec is None:
            return
        try:
            self.runner.run([self.python, "-m", "ray_tpu", "stop",
                             "--temp-dir", rec["temp_dir"]])
        except Exception:
            # Best effort fallback: signal the daemon directly rather than
            # leaking a detached process; keep going either way (matches
            # FakeMultiNodeProvider's swallow-errors contract so one bad
            # node can't abort the autoscaler round).
            pid = self._pid(rec)
            if pid is not None:
                try:
                    self.runner.run(["kill", str(pid)], timeout=20)
                except Exception:
                    pass
        self._nodes.pop(cloud_id, None)

    def node_status(self, cloud_id: str) -> str:
        rec = self._nodes.get(cloud_id)
        if rec is None:
            return "terminated"
        pid = self._pid(rec)
        if pid is None:
            return "pending"
        try:
            # Liveness + identity in one: a recycled pid whose cmdline no
            # longer says ray_tpu must read as failed, not running
            # (same hazard as scripts/start.py _is_ray_tpu_proc).
            self.runner.run(
                ["grep", "-q", "ray_tpu", f"/proc/{pid}/cmdline"],
                timeout=20)
            return "running"
        except Exception:
            return "failed"

    def runtime_node_id(self, cloud_id: str) -> str | None:
        rec = self._nodes.get(cloud_id)
        return rec["node_id"] if rec else None


class TpuSliceProvider(NodeProvider):
    """GCE/GKE TPU slices as atomic units (reference: a TPU cloud provider
    launches whole multi-host slices, not single VMs — SURVEY.md §8.8).

    ``create_slice_fn(slice_name, accelerator_type, topology) -> None`` and
    ``delete_slice_fn(slice_name) -> None`` perform the cloud calls (queued
    resources / GKE nodepool create); injectable so environments without GCP
    egress stub them. One launched "node" = one slice; its hosts join the
    cluster with slice-name labels and the TPU-head marker resource
    (reference: python/ray/_private/accelerators/tpu.py reserve_tpu_slice).
    """

    _counter = itertools.count()

    def __init__(self, accelerator_type: str, topology: str,
                 create_slice_fn: Callable[[str, str, str], None],
                 delete_slice_fn: Callable[[str], None],
                 status_fn: Callable[[str], str] | None = None,
                 node_id_fn: Callable[[str], str | None] | None = None):
        self.accelerator_type = accelerator_type
        self.topology = topology
        self._create = create_slice_fn
        self._delete = delete_slice_fn
        self._status = status_fn or (lambda name: "running")
        self._node_id = node_id_fn or (lambda name: None)
        self._slices: dict[str, str] = {}  # cloud_id -> slice name

    def launch_node(self, node_type: str, resources: dict[str, float],
                    labels: dict[str, str] | None = None) -> str:
        name = f"rtpu-slice-{self.accelerator_type}-{next(self._counter)}"
        self._create(name, self.accelerator_type, self.topology)
        cloud_id = f"slice-{name}"
        self._slices[cloud_id] = name
        return cloud_id

    def terminate_node(self, cloud_id: str) -> None:
        name = self._slices.pop(cloud_id, None)
        if name is not None:
            self._delete(name)

    def node_status(self, cloud_id: str) -> str:
        name = self._slices.get(cloud_id)
        return self._status(name) if name else "terminated"

    def runtime_node_id(self, cloud_id: str) -> str | None:
        name = self._slices.get(cloud_id)
        return self._node_id(name) if name else None
