"""In-process multi-node test cluster.

Capability parity with the reference's workhorse test fixture (reference:
python/ray/cluster_utils.py:135 ``class Cluster``, add_node :202 — N raylets
+ 1 GCS as local processes with fake resource specs, no device checks): here
the head and node daemons run on this process's io loop (cheap on a 1-core
box) while workers are real subprocesses, so scheduling/spillback/failure
paths cross true process boundaries.
"""

from __future__ import annotations

import uuid

from ray_tpu.core.cluster.client import start_head, start_node
from ray_tpu.core.cluster.node_daemon import NodeDaemon
from ray_tpu.core.cluster.protocol import EventLoopThread
from ray_tpu.core.cluster.runtime import ClusterRuntime


class Cluster:
    def __init__(self, persist_path: str | None = None):
        self._io = EventLoopThread.get()
        self._persist_path = persist_path
        self.head = start_head(persist_path=persist_path)
        self.nodes: list[NodeDaemon] = []

    def restart_head(self) -> None:
        """Chaos: kill the control plane and bring it back on the SAME
        address — daemons/drivers reconnect, state reloads from the
        persistence snapshot (reference: GCS restart backed by Redis,
        redis_store_client.cc + HandleNotifyGCSRestart)."""
        host, port = self.head.rpc.host, self.head.rpc.port
        self._io.run(self.head.stop())
        self.head = start_head(host=host, port=port,
                               persist_path=self._persist_path)

    def kill_head(self) -> None:
        """Chaos: hard-kill the control plane — NO final snapshot/WAL
        flush beyond what group commit already ACKed (kill -9 semantics) —
        and leave it DOWN. The cluster runs headless until
        :meth:`revive_head`; daemons/drivers ride it out on their
        reconnect/retry paths. (Same death the chaos plane's ``head.tick``
        kill rule delivers.)"""
        self._down_addr = (self.head.rpc.host, self.head.rpc.port)
        self._io.run(self.head._chaos_die())

    def revive_head(self) -> tuple[float, "object"]:
        """Bring a killed head back on the SAME address. Returns
        ``(restart_seconds, head)`` — the wall time of snapshot load + WAL
        replay + socket bind, the number the headft bench gates at 3 s."""
        import time as _time

        host, port = getattr(self, "_down_addr",
                             (self.head.rpc.host, self.head.rpc.port))
        t0 = _time.monotonic()
        self.head = start_head(host=host, port=port,
                               persist_path=self._persist_path)
        return _time.monotonic() - t0, self.head

    def crash_head(self) -> None:
        """Chaos: hard-kill the control plane — NO final snapshot flush
        (kill -9 semantics) — and bring it back on the same address. State
        must come back from the per-mutation WAL (reference: GCS persists
        each mutation to Redis, so a crash between snapshots loses
        nothing)."""
        self.kill_head()
        self.revive_head()

    def partition_from_head(self, node_regex: str,
                            direction: str = "both",
                            action: str = "drop",
                            delay_s: float = 0.5) -> None:
        """Chaos: sever head⇄node traffic for daemons matching
        ``node_regex`` by installing a ``partition`` rule in this
        process's injector (in-process clusters share one interpreter, so
        one install covers both ends). Directional: "to_head",
        "from_head", or "both". Heal with :meth:`heal_partition`."""
        from ray_tpu.chaos import injector

        injector.install([{
            "point": "partition", "action": action,
            "match": {"node": node_regex}, "direction": direction,
            "delay_s": delay_s, "count": -1,
        }])

    def heal_partition(self) -> None:
        """Remove only the partition rules — a composed drill's other
        chaos rules (kills, rpc delays) stay armed."""
        from ray_tpu.chaos import injector

        injector.remove_point("partition")

    @property
    def address(self) -> str:
        return f"{self.head.rpc.host}:{self.head.rpc.port}"

    def add_node(self, num_cpus: float = 1, resources: dict | None = None,
                 labels: dict | None = None, node_id: str | None = None) -> NodeDaemon:
        totals = {"CPU": float(num_cpus)}
        totals.update(resources or {})
        daemon = start_node(self.head.rpc.host, self.head.rpc.port, totals,
                            labels, node_id or uuid.uuid4().hex)
        self.nodes.append(daemon)
        return daemon

    def remove_node(self, daemon: NodeDaemon, graceful: bool = True):
        """Kill a node (chaos testing — reference: RayletKiller
        test_utils.py:1365)."""
        self._io.run(daemon.stop())
        if daemon in self.nodes:
            self.nodes.remove(daemon)

    def kill_workers(self, node: NodeDaemon | None = None) -> int:
        """Chaos: SIGKILL every worker process on a node (reference:
        WorkerKillerActor, test_utils.py:1279). Returns the kill count —
        objects held only by those workers become reconstruction fodder."""
        import signal

        targets = [node] if node else list(self.nodes)
        n = 0
        for d in targets:
            for w in list(d.workers.values()) + list(d._unregistered):
                if w.proc is not None and w.proc.poll() is None:
                    try:
                        w.proc.send_signal(signal.SIGKILL)
                        n += 1
                    except OSError:
                        pass
        return n

    def connect(self, node: NodeDaemon | None = None) -> ClusterRuntime:
        target = node or (self.nodes[0] if self.nodes else None)
        rt = ClusterRuntime(
            self.head.rpc.host, self.head.rpc.port,
            node_daemon_addr=(target.rpc.host, target.rpc.port) if target else None,
            shm_name=target.shm_name if target else None,
        )
        return rt

    def shutdown(self):
        for d in list(self.nodes):
            try:
                self._io.run(d.stop())
            except Exception:
                pass
        self.nodes.clear()
        try:
            self._io.run(self.head.stop())
        except Exception:
            pass
