"""MPMD pipeline parallelism over compiled graphs (ray_tpu/dag/mpmd.py).

The SPMD pipeline (parallel/pipeline.py) runs ONE jitted program over a pp
mesh axis; the MPMD executor runs one jitted program PER STAGE over stage
actors wired with compiled-graph channels. Same math, different plane —
the parity test here pins the two to each other on identical batches.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.dag


class TestSchedules:
    def test_gpipe_ranks(self):
        from ray_tpu.dag.schedule import GPipeSchedule

        s = GPipeSchedule()
        M, P = 4, 3
        for stage in range(P):
            fwd = [s.forward_rank(m, stage, P, M) for m in range(M)]
            bwd = [s.backward_rank(m, stage, P, M) for m in range(M)]
            # Fill/drain: every forward before every backward, apply last.
            assert fwd == sorted(fwd) and bwd == sorted(bwd)
            assert max(fwd) < min(bwd)
            assert s.apply_rank(stage, P, M) > max(bwd)

    def test_1f1b_ranks_feasible_and_bounded(self):
        from ray_tpu.dag.schedule import OneFOneBSchedule

        s = OneFOneBSchedule()
        M, P = 6, 4
        for stage in range(P):
            fwd = [s.forward_rank(m, stage, P, M) for m in range(M)]
            bwd = [s.backward_rank(m, stage, P, M) for m in range(M)]
            ranks = fwd + bwd
            # A schedule is a strict per-stage order: no rank collisions,
            # microbatch order preserved within forwards and backwards,
            # and each microbatch's forward precedes its backward.
            assert len(set(ranks)) == len(ranks)
            assert fwd == sorted(fwd) and bwd == sorted(bwd)
            for m in range(M):
                assert fwd[m] < bwd[m]
            assert s.apply_rank(stage, P, M) > max(ranks)
            # The stash bound: at any prefix of the stage's op order, the
            # number of forwards minus backwards never exceeds the warmup
            # depth (this IS the 1F1B memory win over GPipe).
            order = sorted(range(2 * M), key=lambda i: ranks[i])
            live, peak = 0, 0
            for i in order:
                live += 1 if i < M else -1
                peak = max(peak, live)
            assert peak <= min(M, P - stage), (stage, peak)

    def test_registry(self):
        from ray_tpu.dag.schedule import get_schedule

        assert get_schedule("gpipe").name == "gpipe"
        assert get_schedule("1f1b").name == "1f1b"
        with pytest.raises(ValueError, match="unknown pipeline schedule"):
            get_schedule("zigzag")

    def test_pipeline_requires_two_stages(self):
        from ray_tpu.dag.mpmd import build_pipeline_dag

        with pytest.raises(ValueError, match="at least 2 stages"):
            build_pipeline_dag([object()], num_microbatches=2)


class TestToyPipeline:
    def test_toy_training_loss_decreases(self, rt_start):
        from ray_tpu.dag.mpmd import MPMDPipeline, make_toy_stage_factory

        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 16), dtype=np.float32)
        t = rng.standard_normal((8, 16), dtype=np.float32)
        pipe = MPMDPipeline(make_toy_stage_factory(width=16),
                            num_stages=3, num_microbatches=4)
        try:
            losses = [pipe.step(x, t, timeout=60)["loss"] for _ in range(5)]
        finally:
            pipe.shutdown()
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(losses))

    def test_1f1b_matches_gpipe_losses(self, rt_start):
        """Schedules reorder the per-stage ops but must not change the
        math: the step still accumulates every microbatch's gradients and
        applies once."""
        from ray_tpu.dag.mpmd import MPMDPipeline, make_toy_stage_factory

        rng = np.random.default_rng(1)
        x = rng.standard_normal((8, 16), dtype=np.float32)
        t = rng.standard_normal((8, 16), dtype=np.float32)
        by_schedule = {}
        for sched in ("gpipe", "1f1b"):
            pipe = MPMDPipeline(make_toy_stage_factory(width=16, seed=3),
                                num_stages=3, num_microbatches=4,
                                schedule=sched)
            try:
                by_schedule[sched] = [
                    pipe.step(x, t, timeout=60)["loss"] for _ in range(3)]
            finally:
                pipe.shutdown()
        np.testing.assert_allclose(by_schedule["1f1b"], by_schedule["gpipe"],
                                   rtol=1e-5)

    def test_step_async_pipelines_steps(self, rt_start):
        from ray_tpu.dag.mpmd import MPMDPipeline, make_toy_stage_factory

        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 16), dtype=np.float32)
        t = rng.standard_normal((4, 16), dtype=np.float32)
        pipe = MPMDPipeline(make_toy_stage_factory(width=16),
                            num_stages=2, num_microbatches=2,
                            _max_inflight=3)
        try:
            futs = [pipe.step_async(x, t) for _ in range(6)]
            metrics = [pipe.parse_result(f.result(60)) for f in futs]
        finally:
            pipe.shutdown()
        # Steps applied in submission order, once each.
        assert [m["step"] for m in metrics] == list(range(1, 7))
        assert metrics[-1]["loss"] < metrics[0]["loss"]


class TestLlamaParity:
    @pytest.mark.multidevice
    def test_mpmd_matches_spmd_pipeline(self, rt_start):
        """2-stage MPMD llama vs parallel/pipeline.py's GPipe train step:
        identical losses on identical batches (same init seed, same
        optimizer, same microbatching). The MPMD partition (stage 0 owns
        embed + its layers, the last stage owns its layers + final_norm +
        lm_head) is exactly where the SPMD psum reduces shared-param
        grads, so trajectories agree to float tolerance."""
        from functools import partial

        import jax
        import optax

        from ray_tpu.dag.mpmd import MPMDPipeline, make_llama_stage_factory
        from ray_tpu.models.llama import LlamaConfig
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.parallel.pipeline import make_pp_train_step

        cfg = LlamaConfig.tiny()  # 2 layers, untied embeddings
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, cfg.vocab_size, (4, 16), dtype=np.int32)
        targets = np.roll(tokens, -1, axis=1)
        opt_f = partial(optax.sgd, 0.1)

        mesh = build_mesh(MeshSpec(pp=2), jax.devices()[:2])
        pstep, pinit, pshard = make_pp_train_step(
            cfg, mesh, num_microbatches=2, optimizer=opt_f(),
            attn_impl="blockwise")
        state = pinit()
        spmd_losses = []
        for _ in range(3):
            state, m = pstep(state, pshard(tokens), pshard(targets))
            spmd_losses.append(float(m["loss"]))

        pipe = MPMDPipeline(
            make_llama_stage_factory(cfg, optimizer_factory=opt_f),
            num_stages=2, num_microbatches=2)
        try:
            mpmd_losses = [pipe.step(tokens, targets)["loss"]
                           for _ in range(3)]
        finally:
            pipe.shutdown()
        np.testing.assert_allclose(mpmd_losses, spmd_losses,
                                   rtol=2e-3, atol=2e-3)
