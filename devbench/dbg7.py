import jax, jax.numpy as jnp, numpy as np
from ray_tpu.ops.attention import blockwise_attention
rng = np.random.default_rng(0)
def chk(name, S, H, HK, kv_block=512, dt=jnp.bfloat16):
    q = jnp.asarray(rng.standard_normal((2,H,S,64)), dt)
    k = jnp.asarray(rng.standard_normal((2,HK,S,64)), dt)
    v = jnp.asarray(rng.standard_normal((2,HK,S,64)), dt)
    f = lambda q,k,v: blockwise_attention(q,k,v,causal=True,kv_block=kv_block).astype(jnp.float32).sum()
    _, grads = jax.jit(jax.value_and_grad(f, argnums=(0,1,2)))(q,k,v)
    nan = [bool(jnp.isnan(g.astype(jnp.float32)).any()) for g in grads]
    print(f"{name}: S={S} H={H} HK={HK} blk={kv_block} {dt.__name__} nan={nan}", flush=True)

chk("a", 512, 32, 8)
chk("b", 2048, 4, 4)
chk("c", 2048, 32, 8)
chk("d", 2048, 4, 4, kv_block=2048)
chk("e", 512, 4, 4)
chk("f", 1024, 4, 4)
chk("g", 2048, 4, 4, dt=jnp.float32)
