"""Goodput ledger: attribute every wall-clock second — and chip-second —
of a run to exactly one phase.

The headline bench measures steady-state step throughput, but production
runs lose chips to everything *around* the step: compile, input stalls,
collective skew, checkpoint stalls, restarts, head outages. This module
classifies every interval of every rank's wall clock into an exhaustive,
non-overlapping phase taxonomy and rolls it up head-side into goodput %
and a badput breakdown per run and per fleet, with chip-seconds as the
unit (the denominator is chips × time, not steps — PAPERS.md
"Automatic Cross-Replica Sharding" framing; the serve side emits
request-goodput per the Gemma-on-TPU SLO-attainment comparison).

Design constraints honored here:

- **No new RPCs on the hot loop.** Rank ledgers ride the per-rank rows
  ``session.collect_train_stats()`` already streams with every telemetry
  push; run-level events (restart downtime, head outages) piggyback the
  same ``report_telemetry`` pushes as an optional ``goodput`` leg; the
  head stamps its own outages locally.
- **Exhaustive by construction.** ``classify_interval`` decomposes each
  report-to-report interval so the parts always sum to the interval —
  the property test asserts sum == wall across restart boundaries, and
  ``snapshot()`` publishes the residual (always 0) so the bench's
  "0 unattributed" gate is measured, not assumed.
- **Self-metered.** Ledger bookkeeping time accumulates into
  ``goodput_ledger_seconds`` (same duty-cycle discipline as the watchdog
  sampler) so the <0.5 % overhead gate is readable off /metrics.

Worker side: :class:`RankLedger` (one per live TrainContext, attached by
``train.session.set_context``). Head side: :class:`GoodputStore`
(constructed by the HeadServer when ``goodput_enabled``), which ingests
event legs, rolls up the fleet, exports ``goodput_*`` federated gauges
and opens a ``badput_over_threshold`` watchdog incident when a run burns
more than ``goodput_badput_pct`` % of its chip-seconds in one badput
phase.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque

# The exhaustive phase taxonomy. Every classified second lands in exactly
# one of these; `step_compute` is the only goodput phase.
PHASES = (
    "init",             # worker bring-up before the first step (fresh run)
    "compile",          # jit compile/recompile (jax.monitoring hooks or
                        # a compile_time_s report key)
    "input_wait",       # dataset iterator stall (data plane can't feed)
    "step_compute",     # the goodput: device compute inside steps
    "collective_wait",  # waiting on peers (sync_time_s share, PR-5)
    "checkpoint",       # sync portion of AsyncCheckpointWriter.save
    "replication_push", # inline snapshot cost of session.replicate
    "restart_downtime", # failure detection + tier + time-to-first-step
                        # (PR-6 restart records)
    "head_outage",      # control-plane downtime (PR-14 incarnation bumps)
    "idle",             # attributed-but-unproductive remainder
                        # (straggler-induced wait when compute_time_s is
                        # reported, post-run tail otherwise)
)
GOOD_PHASE = "step_compute"
# Phases measured inside a step interval; the interval remainder goes to
# step_compute (steady state), init (first interval), or idle.
_MEASURED = ("compile", "input_wait", "collective_wait", "checkpoint",
             "replication_push")


def _enabled() -> bool:
    try:
        from ray_tpu.utils.config import get_config

        return bool(get_config().goodput_enabled)
    except Exception:  # noqa: BLE001 - config not importable: stay off
        return False


def classify_interval(dur: float, parts: dict | None,
                      first: bool = False,
                      first_phase: str = "init",
                      remainder: str | None = None) -> dict[str, float]:
    """Decompose one wall interval into phases. Exhaustive and
    non-overlapping BY CONSTRUCTION: measured parts are clamped into the
    interval in a fixed priority order and the remainder goes to exactly
    one bucket, so the returned values always sum to ``dur``.

    ``parts`` carries measured seconds for any of the ``_MEASURED``
    phases plus an optional ``step_compute`` (from a ``compute_time_s``
    report key); when present, the remainder beyond measured compute is
    ``idle`` — the straggler-induced wait the PR-5 share stream exposes.
    ``first`` intervals (context start → first report) put their
    remainder in ``first_phase`` (``init`` for a fresh run,
    ``restart_downtime`` for a restarted context — that time exists
    because of the failure, and classifying it here keeps it out of the
    fresh-run init bucket). An explicit ``remainder`` phase overrides
    both (the finish() tail is idle, not compute)."""
    dur = max(0.0, float(dur))
    out: dict[str, float] = {}
    budget = dur
    for phase in _MEASURED:
        v = parts.get(phase) if parts else None
        if not v:
            continue
        v = min(budget, max(0.0, float(v)))
        if v > 0.0:
            out[phase] = out.get(phase, 0.0) + v
            budget -= v
    if budget <= 0.0:
        return out
    if remainder is not None:
        out[remainder] = out.get(remainder, 0.0) + budget
        return out
    if first:
        out[first_phase] = out.get(first_phase, 0.0) + budget
        return out
    compute = parts.get("step_compute") if parts else None
    if compute is None:
        out[GOOD_PHASE] = out.get(GOOD_PHASE, 0.0) + budget
        return out
    c = min(budget, max(0.0, float(compute)))
    if c > 0.0:
        out[GOOD_PHASE] = out.get(GOOD_PHASE, 0.0) + c
    if budget - c > 0.0:
        out["idle"] = out.get("idle", 0.0) + (budget - c)
    return out


class RankLedger:
    """One rank's goodput ledger: anchored when its TrainContext attaches,
    closed interval-by-interval from ``session.report()`` (no extra clock
    reads on the step path beyond the two perf_counter stamps of the
    self-meter). Thread-safe: the telemetry flusher snapshots from its
    own thread while the train thread closes intervals."""

    def __init__(self, run: str, rank: int, chips: float = 1.0,
                 restarted: bool = False):
        self.run = run or "train"
        self.rank = int(rank)
        self.chips = max(1.0, float(chips))
        self._first_phase = "restart_downtime" if restarted else "init"
        self._lock = threading.Lock()
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()
        self._mark = self._t0_mono  # last classified boundary (monotonic)
        self.phase_s: dict[str, float] = {}
        self._pending: dict[str, float] = {}
        self._closed_any = False
        self._finished = False
        self.spent_s = 0.0  # ledger self-cost (duty-cycle numerator)
        self._unmetered_s = 0.0

    # ------------------------------------------------------------ hooks
    def add_pending(self, phase: str, seconds: float) -> None:
        """Stamp measured seconds (compile / input_wait / checkpoint /
        replication_push hooks) to be consumed by the next interval
        close. Unknown phases are dropped, not raised — instrumentation
        must never fail a training step."""
        if phase not in PHASES or not seconds or seconds < 0:
            return
        with self._lock:
            self._pending[phase] = self._pending.get(phase, 0.0) \
                + float(seconds)

    # ---------------------------------------------------------- closing
    def close_interval(self, parts: dict | None = None,
                       remainder: str | None = None) -> dict | None:
        """Classify [last boundary → now]. Called from
        ``_instrument_report`` on every report (and from ``finish()`` for
        the tail). Returns the classified parts (tests/trace lane)."""
        t0 = time.perf_counter()
        try:
            with self._lock:
                if self._finished:
                    return None
                now = time.monotonic()
                dur = max(0.0, now - self._mark)
                start_mono, self._mark = self._mark, now
                merged = self._pending
                self._pending = {}
                first = not self._closed_any
                self._closed_any = True
            if parts:
                for k, v in parts.items():
                    if v:
                        merged[k] = merged.get(k, 0.0) + max(0.0, float(v))
            classified = classify_interval(dur, merged, first=first,
                                           first_phase=self._first_phase,
                                           remainder=remainder)
            with self._lock:
                for phase, v in classified.items():
                    self.phase_s[phase] = self.phase_s.get(phase, 0.0) + v
            self._trace(classified, start_mono)
            return classified
        finally:
            dt = time.perf_counter() - t0
            self.spent_s += dt
            self._unmetered_s += dt
            self._meter()

    def finish(self, phase: str = "idle") -> None:
        """Close the tail [last boundary → now] as ``phase`` and freeze
        the ledger; its final snapshot rides the finished-rank grace row
        session.collect_train_stats keeps streaming."""
        self.close_interval(remainder=phase)
        with self._lock:
            self._finished = True

    # --------------------------------------------------------- exports
    def snapshot(self) -> dict:
        """The wire row that rides this rank's train-stats summary. The
        ``unattributed_s`` residual is computed here, worker-side, so the
        head (and the bench's zero-unattributed gate) reads a measured
        number: classified + open tail vs. the elapsed monotonic clock."""
        with self._lock:
            total = sum(self.phase_s.values())
            open_s = 0.0 if self._finished \
                else max(0.0, time.monotonic() - self._mark)
            elapsed = (self._mark if self._finished
                       else time.monotonic()) - self._t0_mono
            return {
                "run": self.run,
                "rank": self.rank,
                "chips": self.chips,
                "t0": self._t0_wall,
                "ts": time.time(),
                "phase_s": dict(self.phase_s),
                "open_s": open_s,
                "unattributed_s": max(0.0, elapsed - total - open_s),
                "spent_s": self.spent_s,
                "finished": self._finished,
            }

    # -------------------------------------------------------- internals
    def _meter(self) -> None:
        """Move accumulated self-cost into the registry counter. Only on
        interval closes (which already mutate the train gauges), so an
        idle process's snapshot stays byte-identical and the flushers'
        idle skip survives — same discipline as the watchdog sampler."""
        try:
            _ledger_metrics()["seconds"].inc(self._unmetered_s)
            self._unmetered_s = 0.0
        except Exception:  # noqa: BLE001 - metrics must never fail a step
            pass

    def _trace(self, classified: dict, start_mono: float) -> None:
        """Goodput lane in the chrome-trace timeline: one span per phase
        chunk, laid sequentially inside the closed interval (sub-phase
        ordering within an interval is not observed, only its total).
        Only when tracing is on, and only chunks big enough to see."""
        from ray_tpu.util import tracing

        if not tracing.tracing_enabled():
            return
        wall = self._t0_wall + (start_mono - self._t0_mono)
        for phase, v in classified.items():
            if v < 0.005:
                wall += v
                continue
            tracing.record_span(
                f"goodput.{phase}", wall, wall + v, kind="goodput",
                attributes={"run": self.run, "rank": self.rank,
                            "phase": phase})
            wall += v


_ledger_metrics_obj = None
_ledger_metrics_lock = threading.Lock()


def _ledger_metrics():
    global _ledger_metrics_obj
    with _ledger_metrics_lock:
        if _ledger_metrics_obj is None:
            from ray_tpu.util.metrics import Counter

            _ledger_metrics_obj = {
                "seconds": Counter(
                    "goodput_ledger_seconds",
                    "cumulative wall time this process spent classifying "
                    "goodput intervals (duty-cycle numerator for the "
                    "<0.5% overhead gate)"),
            }
        return _ledger_metrics_obj


# ------------------------------------------------------- worker-side glue
# The active ledger is thread-local (same thread that runs train_fn /
# session.report); hooks called from other threads no-op, by design.

_active = threading.local()


def set_active(ledger: RankLedger | None) -> None:
    _active.ledger = ledger


def get_active() -> RankLedger | None:
    return getattr(_active, "ledger", None)


def add_active_pending(phase: str, seconds: float) -> None:
    """Hook entry for the checkpoint / replicate / input instrumentation:
    stamp seconds on the calling thread's ledger, if any."""
    led = get_active()
    if led is not None:
        led.add_pending(phase, seconds)


@contextlib.contextmanager
def input_wait():
    """Time a block as dataset-iterator stall::

        with goodput.input_wait():
            batch = next(it)

    No-op (one thread-local read) when no ledger is active."""
    led = get_active()
    if led is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        led.add_pending("input_wait", time.perf_counter() - t0)


def attach(ctx) -> None:
    """Create this context's RankLedger and make it the thread's active
    one (called by ``train.session.set_context``). Chips = this process's
    local device count when a jax backend is ALREADY up (never trigger a
    backend init from bookkeeping), else 1."""
    if not _enabled():
        return
    led = RankLedger(
        run=getattr(ctx, "experiment_name", "train"),
        rank=getattr(ctx, "world_rank", 0),
        chips=_local_chips(),
        restarted=bool(getattr(ctx, "restart_count", 0)))
    ctx._goodput = led
    set_active(led)
    install_compile_listener()


def detach(ctx) -> None:
    """Finalize the context's ledger (tail → idle) at teardown; the final
    snapshot rides the finished-rank grace row."""
    led = getattr(ctx, "_goodput", None)
    if led is not None:
        led.finish()
    if get_active() is led:
        set_active(None)


def _local_chips() -> float:
    try:
        from ray_tpu.profiling.memory import jax_backend_ready

        if not jax_backend_ready():
            return 1.0
        import jax

        return float(max(1, jax.local_device_count()))
    except Exception:  # noqa: BLE001
        return 1.0


_compile_listener_installed = False
_compile_listener_lock = threading.Lock()


def install_compile_listener() -> None:
    """Route jax compile durations (jit cache misses, AOT backend
    compiles) into the active ledger's ``compile`` bucket via
    jax.monitoring — the hook jax itself uses for compile-time telemetry.
    Gated: once per process, tolerant of jax versions without the API
    (train loops can still pass ``compile_time_s`` to report())."""
    global _compile_listener_installed
    with _compile_listener_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True
    try:
        from jax import monitoring as _mon

        def _on_event(event: str, duration: float, **kw) -> None:
            # backend_compile is the innermost compile event; matching it
            # alone avoids double counting nested lower/compile spans.
            if "backend_compile" in event:
                add_active_pending("compile", float(duration))

        _mon.register_event_duration_secs_listener(_on_event)
    except Exception:  # noqa: BLE001 - no jax.monitoring: report-key only
        pass


# ------------------------------------------------ run-level event buffer
# restart_downtime (controller) and head_outage (head) are process-level
# facts, not rank intervals. They buffer here and piggyback the process's
# existing telemetry flush as an optional `goodput` leg — requeued on
# push failure, deduplicated head-side by event id, so exactly-once lands
# without a new RPC.

_events_lock = threading.Lock()
_events: deque = deque(maxlen=256)
_event_seq = 0


def record_event(kind: str, run: str | None, seconds: float,
                 chips: float = 0.0, detail: dict | None = None,
                 start_ts: float | None = None) -> dict:
    """Buffer one run-level badput event for the next telemetry flush.
    ``kind`` is a PHASES member (restart_downtime / head_outage);
    ``chips`` scales seconds into chip-seconds head-side (0 = unknown,
    the rollup falls back to 1)."""
    global _event_seq
    with _events_lock:
        _event_seq += 1
        ev = {
            "id": f"{os.getpid():x}-{_event_seq:x}-{os.urandom(4).hex()}",
            "kind": kind,
            "run": run,
            "seconds": max(0.0, float(seconds)),
            "chips": max(0.0, float(chips)),
            "ts": time.time(),
            "start_ts": float(start_ts) if start_ts else None,
            "detail": dict(detail or {}),
        }
        _events.append(ev)
        return ev


def collect_for_flush() -> dict | None:
    """One flush tick's goodput leg: drains buffered events (None when
    idle or the gate is off). The flusher passes the result straight to
    report_telemetry's ``goodput`` kwarg and hands it back to
    :func:`flush_failed` when the push raised."""
    if not _enabled():
        return None
    with _events_lock:
        if not _events:
            return None
        out = list(_events)
        _events.clear()
    return {"events": out}


def flush_failed(payload: dict | None) -> None:
    """Requeue a drained leg whose push never reached the head (bounded:
    the deque cap sheds oldest first — same loss discipline as spans)."""
    if not payload:
        return
    with _events_lock:
        for ev in reversed(payload.get("events") or []):
            _events.appendleft(ev)


def _reset_for_tests() -> None:
    global _event_seq, _compile_listener_installed
    with _events_lock:
        _events.clear()
        _event_seq = 0
    set_active(None)


# ------------------------------------------------------- head-side store
class GoodputStore:
    """Head-side aggregator: ingests event legs (dedup by id), stamps the
    head's own outages, rolls the fleet up from the train-stats table the
    head already keeps, and runs the badput-over-threshold rule."""

    MAX_EVENTS = 512

    def __init__(self):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.MAX_EVENTS)
        self._seen: deque = deque(maxlen=2 * self.MAX_EVENTS)
        self._seen_set: set[str] = set()
        self._last_check = 0.0
        self._badput_fired: dict[str, float] = {}  # run -> monotonic ts
        self._gauges = None

    # --------------------------------------------------------- ingest
    def ingest(self, source: str, node_id: str, payload: dict) -> None:
        for ev in (payload or {}).get("events") or ():
            eid = ev.get("id")
            with self._lock:
                if eid in self._seen_set:
                    continue  # flusher retry after a half-landed push
                if len(self._seen) == self._seen.maxlen:
                    self._seen_set.discard(self._seen[0])
                self._seen.append(eid)
                self._seen_set.add(eid)
                self._events.append({**ev, "source": source,
                                     "node_id": node_id})

    def stamp(self, kind: str, run: str | None, seconds: float,
              chips: float = 0.0, detail: dict | None = None,
              start_ts: float | None = None) -> None:
        """The head's own events (head_outage at boot) — no transport."""
        with self._lock:
            self._events.append({
                "id": f"head-{os.urandom(6).hex()}", "kind": kind,
                "run": run, "seconds": max(0.0, float(seconds)),
                "chips": max(0.0, float(chips)), "ts": time.time(),
                "start_ts": start_ts, "detail": dict(detail or {}),
                "source": "head", "node_id": "",
            })

    def events(self, run: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if run:
            evs = [e for e in evs if e.get("run") in (run, None)]
        return evs

    # --------------------------------------------------------- rollup
    def rollup(self, train_stats: dict, run: str | None = None,
               series_store=None) -> dict:
        """Fleet goodput: per-run chip-second phase totals from every
        rank-ledger snapshot in the train-stats table (entries are
        cumulative per context incarnation, keyed (source, rank) — a
        restarted rank's old and new incarnations both count, which is
        exactly the run's history), plus the run-level events. The
        restart_downtime phase takes max(rank-side, event-side): the
        event window [detection → first post-restart report] CONTAINS the
        restarted context's first interval, so summing both would double
        count; max() keeps the fuller measure."""
        runs: dict[str, dict] = {}

        def bucket(r: str) -> dict:
            return runs.setdefault(r, {
                "phase_s": {}, "chip_s": {}, "ranks": set(),
                "entries": 0, "open_s": 0.0, "unattributed_s": 0.0,
                "spent_s": 0.0, "chips_live": {},
            })

        for source, row in (train_stats or {}).items():
            for rank_key, stats in (row.get("stats") or {}).items():
                gp = (stats or {}).get("goodput")
                if not gp:
                    continue
                b = bucket(gp.get("run") or "train")
                chips = max(1.0, float(gp.get("chips") or 1.0))
                b["entries"] += 1
                b["ranks"].add(int(gp.get("rank", rank_key)))
                b["chips_live"][int(gp.get("rank", rank_key))] = chips
                for phase, v in (gp.get("phase_s") or {}).items():
                    b["phase_s"][phase] = b["phase_s"].get(phase, 0.0) + v
                    b["chip_s"][phase] = b["chip_s"].get(phase, 0.0) \
                        + v * chips
                b["open_s"] += float(gp.get("open_s") or 0.0)
                b["unattributed_s"] += float(gp.get("unattributed_s") or 0.0)
                b["spent_s"] += float(gp.get("spent_s") or 0.0)

        fleet_events: dict[str, float] = {}   # kind -> seconds (run=None)
        fleet_event_chip: dict[str, float] = {}
        for ev in self.events():
            kind = ev.get("kind") or "idle"
            secs = float(ev.get("seconds") or 0.0)
            chips = float(ev.get("chips") or 0.0) or 1.0
            r = ev.get("run")
            if r is None:
                fleet_events[kind] = fleet_events.get(kind, 0.0) + secs
                fleet_event_chip[kind] = fleet_event_chip.get(kind, 0.0) \
                    + secs * chips
                continue
            b = bucket(r)
            ev_s = b.setdefault("event_s", {})
            ev_c = b.setdefault("event_chip_s", {})
            ev_s[kind] = ev_s.get(kind, 0.0) + secs
            ev_c[kind] = ev_c.get(kind, 0.0) + secs * chips

        out_runs: dict[str, dict] = {}
        fleet = {"phase_chip_s": dict(fleet_event_chip),
                 "phase_s": dict(fleet_events)}
        for r, b in runs.items():
            chip_s = dict(b["chip_s"])
            phase_s = dict(b["phase_s"])
            # Event-vs-rank overlap resolution (see docstring). Both run
            # domains are PER-RANK seconds summed across ranks, so the
            # event window (one wall interval) enters as seconds x chips
            # — the controller's chips proxy is one chip per rank — in
            # phase_s too, or a 2-rank outage would compare half-sized
            # against the two rank ledgers it contains.
            for kind in ("restart_downtime", "head_outage"):
                ev_c = (b.get("event_chip_s") or {}).get(kind, 0.0)
                if ev_c:
                    chip_s[kind] = max(chip_s.get(kind, 0.0), ev_c)
                    phase_s[kind] = max(phase_s.get(kind, 0.0), ev_c)
            total = sum(chip_s.values())
            good = chip_s.get(GOOD_PHASE, 0.0)
            badput = {p: v for p, v in sorted(
                chip_s.items(), key=lambda kv: -kv[1]) if p != GOOD_PHASE}
            out_runs[r] = {
                "ranks": len(b["ranks"]),
                "entries": b["entries"],
                "chips": sum(b["chips_live"].values()),
                "wall_s": sum(phase_s.values()),
                "chip_seconds": total,
                "good_chip_s": good,
                "goodput_pct": (100.0 * good / total) if total else None,
                "phase_s": phase_s,
                "phase_chip_s": chip_s,
                "badput_chip_s": badput,
                "open_s": b["open_s"],
                "unattributed_s": b["unattributed_s"],
                "ledger_spent_s": b["spent_s"],
                "events": [e for e in self.events(r) if e.get("run") == r],
            }
            for p, v in chip_s.items():
                fleet["phase_chip_s"][p] = \
                    fleet["phase_chip_s"].get(p, 0.0) + v
            for p, v in phase_s.items():
                fleet["phase_s"][p] = fleet["phase_s"].get(p, 0.0) + v
        ftotal = sum(fleet["phase_chip_s"].values())
        fgood = fleet["phase_chip_s"].get(GOOD_PHASE, 0.0)
        fleet["chip_seconds"] = ftotal
        fleet["goodput_pct"] = (100.0 * fgood / ftotal) if ftotal else None
        fleet["unattributed_s"] = sum(
            b["unattributed_s"] for b in runs.values())
        fleet["events"] = [e for e in self.events() if e.get("run") is None]
        if run is not None:
            out_runs = {r: v for r, v in out_runs.items() if r == run}
        return {"enabled": True, "runs": out_runs, "fleet": fleet,
                "serve": self._serve_goodput(series_store)}

    def _serve_goodput(self, series_store) -> dict:
        """Request-goodput per deployment: SLO-attained tokens / chip-
        second, from the ``serve_slo_tokens_total:rate`` series the
        replicas' samplers already stream (PR-8 SLO counters). Chips per
        deployment = distinct reporting replica processes (1 chip per
        replica on dev rigs; TPU deployments pin one replica per chip
        set, same proxy the serve bench uses)."""
        if series_store is None:
            return {}
        try:
            series = series_store.query(name="serve_slo_tokens_total:rate",
                                        max_age_s=120.0)
        except Exception:  # noqa: BLE001
            return {}
        per_dep: dict[str, dict] = {}
        for s in series:
            dep = (s.get("tags") or {}).get("deployment", "")
            pts = s.get("points") or []
            if not dep or not pts:
                continue
            d = per_dep.setdefault(dep, {"rate": 0.0, "replicas": 0})
            # Windowed mean, not the last point: a counter that just went
            # quiet leaves one trailing-zero rate sample (sampler contract),
            # which would read an active deployment as zero goodput.
            vals = [float(v) for _, v in pts]
            d["rate"] += sum(vals) / len(vals)
            d["replicas"] += 1
        return {
            dep: {
                "slo_tokens_per_s": d["rate"],
                "replicas": d["replicas"],
                "request_goodput": d["rate"] / max(1, d["replicas"]),
            } for dep, d in per_dep.items()
        }

    # ------------------------------------------------- badput watchdog
    def maybe_check(self, train_stats: dict, watchdog) -> None:
        """Throttled ingest-path check: refresh the ``goodput_*``
        federated gauges and open a badput-over-threshold incident for
        any run burning more than ``goodput_badput_pct`` % of its
        chip-seconds in one badput phase (cooldown-limited; the incident
        detail carries the run's ledger window so the post-mortem starts
        with the breakdown, not a metric name)."""
        from ray_tpu.utils.config import get_config

        cfg = get_config()
        now = time.monotonic()
        if now - self._last_check < max(0.5, cfg.goodput_check_interval_s):
            return
        self._last_check = now
        rolled = self.rollup(train_stats)
        g = self._goodput_gauges()
        for r, row in rolled["runs"].items():
            tags = {"run": r}
            if row["goodput_pct"] is not None:
                g["pct"].set(row["goodput_pct"], tags=tags)
            g["unattributed"].set(row["unattributed_s"], tags=tags)
            for phase, v in row["phase_chip_s"].items():
                g["chip_seconds"].set(v, tags={"run": r, "phase": phase})
            self._check_run(r, row, cfg, watchdog, now)
        if rolled["fleet"]["goodput_pct"] is not None:
            g["pct"].set(rolled["fleet"]["goodput_pct"],
                         tags={"run": "__fleet__"})

    def _check_run(self, run: str, row: dict, cfg, watchdog,
                   now: float) -> None:
        if watchdog is None or not row["chip_seconds"]:
            return
        if row["wall_s"] < cfg.goodput_badput_min_wall_s:
            return
        last = self._badput_fired.get(run, 0.0)
        if last and now - last < cfg.goodput_badput_cooldown_s:
            return
        worst_phase, worst = None, 0.0
        for phase, v in row["badput_chip_s"].items():
            if v > worst:
                worst_phase, worst = phase, v
        share = 100.0 * worst / row["chip_seconds"]
        if worst_phase is None or share <= cfg.goodput_badput_pct:
            return
        self._badput_fired[run] = now
        try:
            watchdog.record_event(
                "badput_over_threshold",
                f"run {run!r} burned {share:.0f}% of its chip-seconds in "
                f"{worst_phase} (> {cfg.goodput_badput_pct:.0f}% "
                "threshold)",
                detail={"run": run, "phase": worst_phase,
                        "share_pct": share,
                        "goodput_pct": row["goodput_pct"],
                        "phase_chip_s": row["phase_chip_s"],
                        "unattributed_s": row["unattributed_s"],
                        "events": row["events"][-8:]})
        except Exception:  # noqa: BLE001 - accounting never breaks ingest
            pass

    def _goodput_gauges(self):
        if self._gauges is None:
            from ray_tpu.util.metrics import Gauge

            self._gauges = {
                "pct": Gauge(
                    "goodput_pct",
                    "goodput: step_compute chip-seconds as a percentage "
                    "of all attributed chip-seconds (per run; "
                    "run=__fleet__ is the cluster total)",
                    tag_keys=("run",)),
                "chip_seconds": Gauge(
                    "goodput_chip_seconds",
                    "cumulative attributed chip-seconds per run and "
                    "ledger phase",
                    tag_keys=("run", "phase")),
                "unattributed": Gauge(
                    "goodput_unattributed_s",
                    "wall seconds the ledger failed to classify "
                    "(healthy: 0)",
                    tag_keys=("run",)),
            }
        return self._gauges
