"""Router: assigns requests to replicas (power-of-two-choices).

Capability parity with the reference's router (reference:
python/ray/serve/_private/router.py:510 Router.assign_request :1028 →
request_router/pow_2_router.py:27 PowerOfTwoChoicesRequestRouter
.choose_replicas :52 — sample two replicas, pick the one with the smaller
queue; requests queue router-side when all replicas are saturated).
"""

from __future__ import annotations

import random
import threading
from typing import Callable

import ray_tpu
from ray_tpu.serve.config import ReplicaInfo


class Router:
    def __init__(self, deployment_name: str,
                 get_replicas: Callable[[], list[ReplicaInfo]]):
        self._deployment = deployment_name
        self._get_replicas = get_replicas
        self._inflight: dict[str, int] = {}  # replica_id -> local in-flight
        self._lock = threading.Lock()
        self._not_saturated = threading.Condition(self._lock)
        self._rng = random.Random()

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       timeout: float = 30.0, stream: bool = False,
                       route_hint: str | None = None):
        """Pick a replica (pow-2 on local in-flight counts), submit, and
        return the result ObjectRef. Blocks while every replica is at
        max_ongoing_requests (router-side queuing, reference behavior).

        ``route_hint`` biases placement for cache locality: the same hint
        routes to the same replica while it has capacity (reference:
        multiplexed-model routing, request_router/multiplex + the
        prefix-aware policy in llm routing_policies/prefix_aware — both are
        affinity-by-key over the replica set)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while True:
            replicas = self._get_replicas()
            if replicas:
                chosen = self._choose(replicas, route_hint=route_hint)
                if chosen is not None:
                    break
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"no available replica for {self._deployment!r} "
                    f"within {timeout}s")
            _time.sleep(0.01)

        handle = ray_tpu.get_actor(chosen.actor_name, namespace="serve")
        with self._lock:
            self._inflight[chosen.replica_id] = \
                self._inflight.get(chosen.replica_id, 0) + 1
        if stream:
            gen = handle.handle_request_streaming.options(
                num_returns="streaming").remote(method_name, args, kwargs)

            done = threading.Event()

            def on_stream_done():
                # In-flight until the consumer exhausts/abandons the stream
                # (keeps max_ongoing_requests honest for long-lived SSE).
                if not done.is_set():
                    done.set()
                    with self._lock:
                        self._inflight[chosen.replica_id] -= 1

            return gen, on_stream_done
        ref = handle.handle_request.remote(method_name, args, kwargs)

        def _done():
            try:
                ray_tpu.wait([ref], num_returns=1, timeout=None,
                             fetch_local=False)
            finally:
                with self._lock:
                    self._inflight[chosen.replica_id] -= 1
        threading.Thread(target=_done, daemon=True).start()
        return ref

    def _choose(self, replicas: list[ReplicaInfo],
                route_hint: str | None = None) -> ReplicaInfo | None:
        with self._lock:
            if route_hint is not None:
                # Rendezvous hashing: every router maps the same hint to the
                # same replica without coordination; saturation falls back
                # to load-based choice (losing only cache locality).
                import zlib

                ranked = sorted(
                    replicas,
                    key=lambda r: zlib.crc32(
                        f"{route_hint}:{r.replica_id}".encode()),
                )
                for r in ranked:
                    if self._inflight.get(r.replica_id, 0) < \
                            r.max_ongoing_requests:
                        return r
                return None
            candidates = (self._rng.sample(replicas, 2)
                          if len(replicas) >= 2 else list(replicas))
            best, best_load = None, None
            for r in candidates:
                load = self._inflight.get(r.replica_id, 0)
                if load >= r.max_ongoing_requests:
                    continue
                if best_load is None or load < best_load:
                    best, best_load = r, load
            return best

    def metrics(self) -> dict[str, int]:
        with self._lock:
            return dict(self._inflight)
