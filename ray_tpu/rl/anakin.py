"""Anakin: the whole PPO actor-learner loop as one jitted program.

The first Podracer shape (PAPERS.md "Podracer architectures for scalable
Reinforcement Learning"): environments live ON the accelerator next to
the learner, so an entire training iteration — act, step thousands of
envs, GAE, minibatched multi-epoch PPO update — is a single XLA program
with no host round-trips:

    pmap over devices
      └─ scan over train iterations (cfg.iters_per_step fused per call)
           └─ scan over unroll steps
                └─ vmap over envs (vec_env protocol)
           └─ scan over epochs x minibatches (grads pmean'd across devices)

Per-env episode returns are tracked inside the program (an accumulator
carried through the rollout scan; completed-episode sums emitted per
iteration), so metrics cost no extra device<->host traffic.

This is the ``PPOConfig(vectorized=True)`` fast path; the Python
``EnvRunnerGroup`` remains the fallback for envs only the Python registry
knows (rl/ppo.py dispatches). The distributed sibling is rl/sebulba.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.ppo import compute_gae, init_policy, mlp_apply
from ray_tpu.rl.vec_env import make_jax_env

_AXIS = "anakin_devices"


def pick_num_devices(num_envs: int, requested: int = 0) -> int:
    """Largest usable device count: envs shard evenly across devices."""
    avail = requested or jax.local_device_count()
    d = min(avail, jax.local_device_count())
    while d > 1 and num_envs % d:
        d -= 1
    return max(d, 1)


def _update(optimizer, cfg_static, params, opt_state, batch, key):
    """Minibatched multi-epoch clipped-PPO update with cross-device grad
    averaging — rl/ppo.py's ``ppo_update`` body plus ``lax.pmean`` (it
    runs inside the pmap, so the jit wrapper there does not apply)."""
    clip, vf_coef, ent_coef, num_mb, epochs = cfg_static
    B = batch["obs"].shape[0]
    mb = B // num_mb

    def loss_fn(p, mb_batch):
        logits = mlp_apply(p["pi"], mb_batch["obs"])
        values = mlp_apply(p["vf"], mb_batch["obs"])[..., 0]
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, mb_batch["actions"][..., None], axis=-1)[..., 0]
        ratio = jnp.exp(logp - mb_batch["logp"])
        adv = mb_batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = -jnp.minimum(ratio * adv,
                          jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
        vf = 0.5 * ((values - mb_batch["returns"]) ** 2).mean()
        ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        return pg + vf_coef * vf - ent_coef * ent, (pg, vf, ent)

    def mb_step(carry, idx):
        p, os_ = carry
        mb_batch = jax.tree.map(lambda x: x[idx], batch)
        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            p, mb_batch)
        grads = jax.lax.pmean(grads, _AXIS)
        updates, os_ = optimizer.update(grads, os_, p)
        p = optax.apply_updates(p, updates)
        return (p, os_), aux

    def epoch(carry, ekey):
        # Strided minibatch assignment with a random rotation instead of
        # jax.random.permutation: the full-batch sort behind permutation
        # costs more than the grad steps themselves at these batch sizes
        # (and sorts are no friendlier on TPU). Striding spreads each
        # minibatch evenly across the [T, N] samples; the roll varies the
        # partition across epochs and iterations.
        shift = jax.random.randint(ekey, (), 0, B)
        idxs = jnp.roll(jnp.arange(num_mb * mb), shift)
        idxs = idxs.reshape(mb, num_mb).T
        return jax.lax.scan(mb_step, carry, idxs)

    keys = jax.random.split(key, epochs)
    (params, opt_state), aux = jax.lax.scan(epoch, (params, opt_state),
                                            keys)
    pg, vf, ent = jax.tree.map(lambda a: a[-1, -1], aux)
    return params, opt_state, {"policy_loss": pg, "vf_loss": vf,
                               "entropy": ent}


def make_rollout_fn(env, params_apply_pi, params_apply_vf, unroll_len: int):
    """scan(unroll) x vmap(envs) trajectory collection; shared by Anakin
    (inside pmap) and Sebulba runners (jitted on the actor's host).

    carry: (env_states, obs, ep_ret, key) with [N]-batched leaves.
    Returns the new carry, a [T, N, ...] trajectory dict, and per-rollout
    episode stats (sum of completed-episode returns, completion count).
    """

    def rollout(params, env_states, obs, ep_ret, key):
        def rollout_step(rc, _):
            env_states, obs, ep_ret, key = rc
            key, ka = jax.random.split(key)
            logits = params_apply_pi(params, obs)
            value = params_apply_vf(params, obs)
            action = jax.random.categorical(ka, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, action[..., None], axis=-1)[..., 0]
            env_states, next_obs, reward, done = jax.vmap(env.step)(
                env_states, action)
            ep_ret = ep_ret + reward
            done_f = done.astype(jnp.float32)
            trans = {"obs": obs, "actions": action, "logp": logp,
                     "values": value, "rewards": reward, "dones": done,
                     "ep_ret_done": ep_ret * done_f, "ep_done": done_f}
            ep_ret = jnp.where(done, 0.0, ep_ret)
            return (env_states, next_obs, ep_ret, key), trans

        (env_states, obs, ep_ret, key), traj = jax.lax.scan(
            rollout_step, (env_states, obs, ep_ret, key), None, unroll_len)
        ep_stats = {"ret_sum": traj.pop("ep_ret_done").sum(),
                    "count": traj.pop("ep_done").sum()}
        return (env_states, obs, ep_ret, key), traj, ep_stats

    return rollout


class AnakinPPO:
    """Drives the fused program; rl/ppo.py's PPO delegates here when
    ``vectorized=True`` and the env has a JAX implementation."""

    def __init__(self, cfg):
        self.cfg = cfg
        env = make_jax_env(cfg.env)
        self.env = env
        self.unroll_len = cfg.unroll_len or cfg.rollout_len
        self.num_envs = cfg.num_envs or (
            max(1, cfg.num_env_runners) * cfg.num_envs_per_runner)
        self.num_devices = pick_num_devices(
            self.num_envs, int(cfg.extra.get("anakin_devices", 0)))
        self.n_local = self.num_envs // self.num_devices
        local_batch = self.n_local * self.unroll_len
        if local_batch % cfg.num_minibatches:
            raise ValueError(
                f"per-device batch {local_batch} (= {self.n_local} envs x "
                f"{self.unroll_len} unroll) must divide num_minibatches="
                f"{cfg.num_minibatches}")
        self.iters_per_step = int(cfg.extra.get("iters_per_step", 1))

        self.optimizer = optax.adam(cfg.lr)
        params = init_policy(jax.random.PRNGKey(cfg.seed),
                             env.observation_size, env.num_actions,
                             cfg.hidden)
        opt_state = self.optimizer.init(params)
        devices = jax.local_devices()[: self.num_devices]
        self.params = jax.device_put_replicated(params, devices)
        self.opt_state = jax.device_put_replicated(opt_state, devices)

        static = (cfg.clip, cfg.vf_coef, cfg.ent_coef, cfg.num_minibatches,
                  cfg.num_epochs)
        apply_pi = lambda p, o: mlp_apply(p["pi"], o)
        apply_vf = lambda p, o: mlp_apply(p["vf"], o)[..., 0]
        rollout = make_rollout_fn(env, apply_pi, apply_vf, self.unroll_len)
        gamma, lam = cfg.gamma, cfg.gae_lambda
        n_local = self.n_local

        def one_iter(carry, _):
            params, opt_state, env_states, obs, ep_ret, key = carry
            (env_states, obs, ep_ret, key), traj, ep_stats = rollout(
                params, env_states, obs, ep_ret, key)
            last_values = apply_vf(params, obs)
            adv, ret = compute_gae(traj["rewards"], traj["values"],
                                   traj["dones"], last_values, gamma, lam)
            flat = lambda x: x.reshape((x.shape[0] * x.shape[1],)
                                       + x.shape[2:])
            batch = {"obs": flat(traj["obs"]),
                     "actions": flat(traj["actions"]),
                     "logp": flat(traj["logp"]),
                     "advantages": adv.reshape(-1),
                     "returns": ret.reshape(-1)}
            key, ku = jax.random.split(key)
            params, opt_state, stats = _update(self.optimizer, static,
                                               params, opt_state, batch, ku)
            stats.update(ep_stats)
            return (params, opt_state, env_states, obs, ep_ret, key), stats

        def train(params, opt_state, env_states, obs, ep_ret, key,
                  num_iters):
            (params, opt_state, env_states, obs, ep_ret, key), stats = (
                jax.lax.scan(one_iter,
                             (params, opt_state, env_states, obs, ep_ret,
                              key), None, num_iters))
            return params, opt_state, env_states, obs, ep_ret, key, stats

        def init_envs(key):
            states, obs = jax.vmap(env.reset)(jax.random.split(key, n_local))
            return states, obs

        self._train = jax.pmap(
            partial(train, num_iters=self.iters_per_step), axis_name=_AXIS)
        dev_keys = jax.random.split(jax.random.PRNGKey(cfg.seed + 1),
                                    self.num_devices)
        self.env_states, self.obs = jax.pmap(init_envs)(dev_keys)
        self.ep_ret = jnp.zeros((self.num_devices, self.n_local))
        self.key = jax.random.split(jax.random.PRNGKey(cfg.seed + 2),
                                    self.num_devices)
        self._return_window: list[float] = []

    def step(self) -> dict:
        (self.params, self.opt_state, self.env_states, self.obs,
         self.ep_ret, self.key, stats) = self._train(
            self.params, self.opt_state, self.env_states, self.obs,
            self.ep_ret, self.key)
        stats = jax.tree.map(np.asarray, stats)  # [devices, iters]
        count = float(stats["count"].sum())
        if count:
            # One aggregate per fused call keeps the same smoothed-window
            # metric shape as the EnvRunner path's per-episode list.
            self._return_window.append(float(stats["ret_sum"].sum()) / count)
            self._return_window = self._return_window[-100:]
        mean_ret = (float(np.mean(self._return_window))
                    if self._return_window else 0.0)
        steps = self.iters_per_step * self.num_envs * self.unroll_len
        return {
            "episode_return_mean": mean_ret,
            "episodes_completed": int(count),
            "num_env_steps_sampled": steps,
            "policy_loss": float(stats["policy_loss"].mean()),
            "vf_loss": float(stats["vf_loss"].mean()),
            "entropy": float(stats["entropy"].mean()),
        }

    # -- checkpoint plumbing (PPO.save/load_checkpoint delegate) ----------
    def host_params(self):
        return jax.tree.map(lambda x: np.asarray(x[0]), self.params)

    def set_params(self, params) -> None:
        devices = jax.local_devices()[: self.num_devices]
        self.params = jax.device_put_replicated(
            jax.tree.map(jnp.asarray, params), devices)
