"""Dataset: lazy transformation chain over blocks-as-refs (reference
capability: python/ray/data/dataset.py:186 — map/map_batches/filter/sort/
groupby/iter_batches/materialize/streaming_split on a logical plan executed
by the streaming executor)."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterator

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, concat_blocks
from ray_tpu.data.context import DataContext
from ray_tpu.data.executor import ActorPoolStrategy, execute_plan
from ray_tpu.data.plan import (
    AllToAll,
    InputData,
    LimitOp,
    LogicalOp,
    MapBlocks,
    make_filter_fn,
    make_flat_map_fn,
    make_map_batches_fn,
    make_map_rows_fn,
    plan_stages,
)
from ray_tpu.data import shuffle as _shuffle
from ray_tpu.data.shuffle import AggregateFn


def _api():
    import ray_tpu

    return ray_tpu


class Dataset:
    def __init__(self, ops: list[LogicalOp]):
        self._ops = ops

    # -- transforms (lazy) --------------------------------------------------

    def _with(self, op: LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op])

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._with(MapBlocks(make_map_rows_fn(fn), label="Map"))

    def flat_map(self, fn: Callable[[dict], list]) -> "Dataset":
        return self._with(MapBlocks(make_flat_map_fn(fn), label="FlatMap"))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._with(MapBlocks(make_filter_fn(fn), label="Filter"))

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: int | None = None,
        batch_format: str = "numpy",
        compute: ActorPoolStrategy | None = None,
        fn_args: tuple = (),
        fn_kwargs: dict | None = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: dict | None = None,
    ) -> "Dataset":
        if isinstance(fn, type):
            # Class-based UDF → stateful actor-pool map: each pool actor
            # instantiates the class once and reuses it across blocks.
            compute = compute or ActorPoolStrategy()
            cls = fn
            ctor_kwargs = fn_constructor_kwargs or {}
            inst_holder: dict = {}

            def call(batch, *a, **kw):
                if "inst" not in inst_holder:
                    inst_holder["inst"] = cls(*fn_constructor_args,
                                              **ctor_kwargs)
                return inst_holder["inst"](batch, *a, **kw)

            fn = call
        return self._with(
            MapBlocks(
                make_map_batches_fn(
                    fn, batch_size=batch_size, batch_format=batch_format,
                    fn_args=fn_args, fn_kwargs=fn_kwargs,
                ),
                label="MapBatches",
                compute=compute,
            )
        )

    def select_columns(self, cols: list[str]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            return {k: block[k] for k in cols}

        return self._with(MapBlocks(block_fn, label="SelectColumns"))

    def drop_columns(self, cols: list[str]) -> "Dataset":
        drop = set(cols)

        def block_fn(block: Block) -> Block:
            return {k: v for k, v in block.items() if k not in drop}

        return self._with(MapBlocks(block_fn, label="DropColumns"))

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return out

        return self._with(MapBlocks(block_fn, label="AddColumn"))

    def rename_columns(self, mapping: dict[str, str]) -> "Dataset":
        def block_fn(block: Block) -> Block:
            return {mapping.get(k, k): v for k, v in block.items()}

        return self._with(MapBlocks(block_fn, label="RenameColumns"))

    def limit(self, n: int) -> "Dataset":
        return self._with(LimitOp(n))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(
            AllToAll(_shuffle.make_sort_fn(key, descending, _api()),
                     label="Sort")
        )

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._with(
            AllToAll(_shuffle.make_random_shuffle_fn(seed, _api()),
                     label="RandomShuffle")
        )

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(
            AllToAll(_shuffle.make_repartition_fn(num_blocks, _api()),
                     label="Repartition")
        )

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def join(self, other: "Dataset", on: str, how: str = "inner",
             ) -> "Dataset":
        """Hash join on column ``on`` (reference: Dataset.join, join.py —
        both sides hash-partition on the key, partitions join pairwise).
        ``how``: "inner" or "left". Right-side column collisions get an
        ``_r`` suffix."""
        if how not in ("inner", "left"):
            raise ValueError(f"how must be 'inner' or 'left', got {how!r}")
        return self._with(
            AllToAll(_shuffle.make_join_fn(other, on, how, _api()),
                     label=f"Join({how})"))

    def union(self, *others: "Dataset") -> "Dataset":
        mats = [self.materialize()] + [o.materialize() for o in others]
        refs = list(itertools.chain.from_iterable(m._refs_meta for m in mats))
        return Dataset([InputData(block_refs=refs)])

    def zip(self, other: "Dataset") -> "Dataset":
        left = self.materialize()
        right = other.materialize()
        lb = concat_blocks([_api().get(r) for r, _ in left._refs_meta])
        rb = concat_blocks([_api().get(r) for r, _ in right._refs_meta])
        ln, rn = BlockAccessor(lb).num_rows(), BlockAccessor(rb).num_rows()
        if ln != rn:
            raise ValueError(f"zip requires equal row counts ({ln} vs {rn})")
        merged = dict(lb)
        for k, v in rb.items():
            merged[k if k not in merged else f"{k}_1"] = v
        from ray_tpu.data import from_blocks

        return from_blocks([merged])

    # -- execution ----------------------------------------------------------

    def _execute(self) -> Iterator[tuple[Any, dict]]:
        return execute_plan(plan_stages(self._ops), api=_api())

    def iter_block_refs(self) -> Iterator[tuple[Any, dict]]:
        return self._execute()

    def materialize(self) -> "MaterializedDataset":
        refs = list(self._execute())
        return MaterializedDataset(refs)

    def iter_rows(self) -> Iterator[dict]:
        api = _api()
        for ref, _meta in self._execute():
            yield from BlockAccessor(api.get(ref)).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: int | None = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: int | None = None,
        local_shuffle_seed: int | None = None,
    ) -> Iterator[Any]:
        from ray_tpu.data.iterator import batches_from_refs

        yield from batches_from_refs(
            self._execute(), _api(),
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last,
            shuffle_buffer_size=local_shuffle_buffer_size,
            shuffle_seed=local_shuffle_seed,
        )

    def iter_torch_batches(
        self,
        *,
        batch_size: int | None = 256,
        dtypes=None,
        device: str = "cpu",
        drop_last: bool = False,
        local_shuffle_buffer_size: int | None = None,
        local_shuffle_seed: int | None = None,
    ) -> Iterator[Any]:
        """Batches as dicts of torch tensors (reference:
        Dataset.iter_torch_batches — dataset.py:5650 family). ``dtypes``
        maps column name → torch dtype (or one dtype for all columns)."""
        import torch

        for batch in self.iter_batches(
                batch_size=batch_size, batch_format="numpy",
                drop_last=drop_last,
                local_shuffle_buffer_size=local_shuffle_buffer_size,
                local_shuffle_seed=local_shuffle_seed):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(v)
                dt = dtypes.get(k) if isinstance(dtypes, dict) else dtypes
                if dt is not None:
                    t = t.to(dt)
                out[k] = t.to(device) if device != "cpu" else t
            yield out

    def iter_jax_batches(
        self,
        *,
        batch_size: int | None = 256,
        sharding=None,
        prefetch: int = 2,
        drop_last: bool = False,
        local_shuffle_buffer_size: int | None = None,
        local_shuffle_seed: int | None = None,
    ) -> Iterator[Any]:
        """numpy batches moved onto device ahead of consumption (TPU-native
        analogue of the reference's iter_torch_batches: host→device transfer
        overlaps the consumer's compute via a ``prefetch``-deep pipeline).

        ``sharding``: a jax.sharding.Sharding (e.g. NamedSharding over the
        dp axis); None puts batches on the default device.
        """
        from ray_tpu.data.iterator import device_prefetch

        batches = self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)
        yield from device_prefetch(batches, sharding=sharding,
                                   depth=prefetch)

    def take(self, n: int = 20) -> list[dict]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        total = 0
        api = _api()
        for ref, meta in self._execute():
            n = meta.get("num_rows", -1)
            if n < 0:
                n = BlockAccessor(api.get(ref)).num_rows()
            total += n
        return total

    def schema(self) -> dict[str, str] | None:
        for ref, _meta in self._execute():
            block = _api().get(ref)
            if BlockAccessor(block).num_rows() >= 0 and block:
                return BlockAccessor(block).schema()
        return None

    def columns(self) -> list[str]:
        s = self.schema()
        return list(s.keys()) if s else []

    def num_blocks(self) -> int:
        return sum(1 for _ in self._execute())

    # -- aggregates ---------------------------------------------------------

    def aggregate(self, *aggs: AggregateFn) -> dict:
        ds = self._with(
            AllToAll(_shuffle.make_global_aggregate_fn(list(aggs), _api()),
                     label="Aggregate")
        )
        rows = ds.take_all()
        return rows[0] if rows else {}

    def sum(self, col: str):
        return self.aggregate(_shuffle.Sum(col)).get(f"sum({col})")

    def min(self, col: str):
        return self.aggregate(_shuffle.Min(col)).get(f"min({col})")

    def max(self, col: str):
        return self.aggregate(_shuffle.Max(col)).get(f"max({col})")

    def mean(self, col: str):
        return self.aggregate(_shuffle.Mean(col)).get(f"mean({col})")

    def std(self, col: str):
        return self.aggregate(_shuffle.Std(col)).get(f"std({col})")

    # -- splits / conversion ------------------------------------------------

    def split(self, n: int) -> list["MaterializedDataset"]:
        mat = self.materialize()
        api = _api()
        blocks = [api.get(r) for r, _ in mat._refs_meta]
        merged = concat_blocks(blocks)
        from ray_tpu.data.block import split_block

        parts = split_block(merged, n)
        return [MaterializedDataset([(api.put(p),
                                      {"num_rows": BlockAccessor(p).num_rows()})])
                for p in parts]

    def streaming_split(self, n: int, *, equal: bool = False):
        from ray_tpu.data.iterator import make_streaming_split

        return make_streaming_split(self, n, equal=equal)

    def to_pandas(self):
        api = _api()
        blocks = [api.get(r) for r, _ in self.materialize()._refs_meta]
        return BlockAccessor(concat_blocks(blocks)).to_pandas()

    def to_numpy_refs(self) -> list:
        return [r for r, _ in self.materialize()._refs_meta]

    # -- writes -------------------------------------------------------------

    def _write(self, path: str, write_fn) -> list[str]:
        import os

        os.makedirs(path, exist_ok=True)
        api = _api()
        ctx = DataContext.get_current()
        write_remote = api.remote(num_cpus=ctx.task_num_cpus)(write_fn)
        refs = [
            write_remote.remote(ref, path, i)
            for i, (ref, _m) in enumerate(self._execute())
        ]
        return api.get(refs)

    def write_parquet(self, path: str) -> list[str]:
        from ray_tpu.data.datasource import write_block_parquet

        return self._write(path, write_block_parquet)

    def write_csv(self, path: str) -> list[str]:
        from ray_tpu.data.datasource import write_block_csv

        return self._write(path, write_block_csv)

    def write_json(self, path: str) -> list[str]:
        from ray_tpu.data.datasource import write_block_json

        return self._write(path, write_block_json)

    def write_sql(self, sql: str, connection_factory) -> int:
        """Insert every row through a DB-API 2.0 connection (reference:
        Dataset.write_sql). ``sql`` is an INSERT with positional
        placeholders matching the block's column order; each block runs
        one executemany in its own remote task. Returns rows written."""
        from ray_tpu.data.datasource import write_block_sql

        api = _api()
        ctx = DataContext.get_current()
        write_remote = api.remote(num_cpus=ctx.task_num_cpus)(write_block_sql)
        refs = [write_remote.remote(ref, sql, connection_factory)
                for ref, _m in self._execute()]
        return sum(api.get(refs))

    def __repr__(self) -> str:
        labels = [getattr(op, "label", type(op).__name__) for op in self._ops]
        return f"Dataset({' -> '.join(labels)})"


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are already in the object store (reference
    capability: Dataset.materialize :6493)."""

    def __init__(self, refs_meta: list[tuple[Any, dict]]):
        super().__init__([InputData(block_refs=list(refs_meta))])
        self._refs_meta = list(refs_meta)

    def materialize(self) -> "MaterializedDataset":
        return self

    def num_blocks(self) -> int:
        return len(self._refs_meta)

    def count(self) -> int:
        total = 0
        api = _api()
        for ref, meta in self._refs_meta:
            n = meta.get("num_rows", -1)
            if n < 0:
                n = BlockAccessor(api.get(ref)).num_rows()
            total += n
        return total


class GroupedData:
    """Result of Dataset.groupby (reference capability:
    python/ray/data/grouped_data.py)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return self._ds._with(
            AllToAll(_shuffle.make_groupby_fn(self._key, list(aggs), _api()),
                     label=f"GroupBy({self._key})")
        )

    def count(self) -> Dataset:
        return self.aggregate(_shuffle.Count())

    def sum(self, col: str) -> Dataset:
        return self.aggregate(_shuffle.Sum(col))

    def min(self, col: str) -> Dataset:
        return self.aggregate(_shuffle.Min(col))

    def max(self, col: str) -> Dataset:
        return self.aggregate(_shuffle.Max(col))

    def mean(self, col: str) -> Dataset:
        return self.aggregate(_shuffle.Mean(col))

    def std(self, col: str) -> Dataset:
        return self.aggregate(_shuffle.Std(col))

    def map_groups(self, fn: Callable[[Block], Any]) -> Dataset:
        """Shuffle by key, then apply fn per group within each partition."""
        key = self._key

        def per_partition(block: Block) -> Block:
            acc = BlockAccessor(block)
            if acc.num_rows() == 0:
                return block
            keys = block[key]
            if keys.dtype.kind == "O":
                uniq = {}
                for i, k in enumerate(keys):
                    uniq.setdefault(str(k), []).append(i)
                groups = [np.asarray(v) for v in uniq.values()]
            else:
                vals, inverse = np.unique(keys, return_inverse=True)
                groups = [np.nonzero(inverse == g)[0]
                          for g in range(len(vals))]
            outs = []
            for idx in groups:
                from ray_tpu.data.block import batch_to_block

                outs.append(batch_to_block(fn(acc.take_rows(idx))))
            return concat_blocks(outs)

        shuffled = self._ds._with(
            AllToAll(
                _shuffle.make_groupby_shuffle_only_fn(key, _api()),
                label=f"ShuffleBy({key})",
            )
        )
        return shuffled._with(MapBlocks(per_partition, label="MapGroups"))
