from ray_tpu.util.state.api import (
    list_actors,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    summarize_tasks,
)

__all__ = [
    "list_actors",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_tasks",
    "list_workers",
    "summarize_tasks",
]
