"""R0 fixture: unused module-scope import (pyflakes F401 subset)."""

import json
import os  # noqa — the noqa marker must suppress THIS one
import textwrap  # BUG: never referenced again

used = json.dumps({"ok": True})
