"""Metrics / tracing / task events / state API / dashboard.

Mirrors the reference's observability test surface (reference:
python/ray/tests/test_metrics_agent.py, test_state_api.py, tracing tests):
everything runs against the in-process runtime.
"""

import json
import urllib.error
import urllib.request

import pytest

from ray_tpu.core import events
from ray_tpu.util import metrics, tracing


@pytest.fixture(autouse=True)
def _clean_buffers():
    events.global_event_buffer().clear()
    tracing.clear()
    tracing.disable_tracing()
    yield
    tracing.disable_tracing()


class TestMetrics:
    def test_counter_gauge(self):
        c = metrics.Counter("test_requests_total", "reqs", tag_keys=("route",))
        c.inc(tags={"route": "/a"})
        c.inc(2, tags={"route": "/a"})
        c.inc(tags={"route": "/b"})
        g = metrics.Gauge("test_queue_depth", "depth")
        g.set(7)
        text = metrics.registry().export_prometheus()
        assert 'test_requests_total{route="/a"} 3.0' in text
        assert 'test_requests_total{route="/b"} 1.0' in text
        assert "test_queue_depth 7.0" in text
        assert "# TYPE test_requests_total counter" in text

    def test_histogram_buckets(self):
        h = metrics.Histogram("test_latency_s", "lat", boundaries=[0.1, 1.0])
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = metrics.registry().export_prometheus()
        assert 'test_latency_s_bucket{le="0.1"} 1' in text
        assert 'test_latency_s_bucket{le="1.0"} 2' in text
        assert 'test_latency_s_bucket{le="+Inf"} 3' in text
        assert "test_latency_s_count 3" in text

    def test_counter_rejects_negative_and_unknown_tags(self):
        c = metrics.Counter("test_neg", "", tag_keys=("a",))
        with pytest.raises(ValueError):
            c.inc(-1)
        with pytest.raises(ValueError):
            c.inc(tags={"bogus": "x"})


class TestTaskEventsAndTimeline:
    def test_events_recorded(self, rt_start):
        rt = rt_start

        @rt.remote
        def f():
            return 1

        assert rt.get(f.remote()) == 1
        states = {e.state for e in events.global_event_buffer().events()}
        assert {"SUBMITTED", "RUNNING", "FINISHED"} <= states

    def test_failed_task_event(self, rt_start):
        rt = rt_start

        @rt.remote(max_retries=0)
        def boom():
            raise ValueError("x")

        with pytest.raises(Exception):
            rt.get(boom.remote())
        states = [e.state for e in events.global_event_buffer().events()]
        assert "FAILED" in states

    def test_timeline_chrome_trace(self, rt_start, tmp_path):
        rt = rt_start

        @rt.remote
        def g():
            return 2

        rt.get([g.remote() for _ in range(3)])
        trace = rt.timeline()
        assert len(trace) >= 3
        assert all(ev["ph"] == "X" and ev["dur"] >= 0 for ev in trace)
        path = rt.timeline(str(tmp_path / "trace.json"))
        with open(path) as f:
            assert json.load(f)


class TestTracing:
    def test_span_propagation_into_task(self, rt_start):
        rt = rt_start
        tracing.enable_tracing()

        @rt.remote
        def traced():
            return 42

        with tracing.span("driver-op") as root:
            ref = traced.remote()
            assert rt.get(ref) == 42
        spans = tracing.spans()
        names = [s.name for s in spans]
        assert "driver-op" in names
        assert "traced" in names
        worker_span = next(s for s in spans if s.name == "traced")
        assert worker_span.trace_id == root.trace_id
        assert worker_span.parent_id == root.span_id

    def test_disabled_is_noop(self, rt_start):
        rt = rt_start

        @rt.remote
        def f():
            return 1

        rt.get(f.remote())
        assert tracing.spans() == []

    def test_span_error_status(self):
        tracing.enable_tracing()
        with pytest.raises(RuntimeError):
            with tracing.span("bad"):
                raise RuntimeError("no")
        assert tracing.spans()[-1].status.startswith("ERROR")


class TestStateApi:
    def test_list_entities(self, rt_start):
        rt = rt_start
        from ray_tpu.util import state

        @rt.remote
        class A:
            def ping(self):
                return "pong"

        a = A.remote()
        assert rt.get(a.ping.remote()) == "pong"
        nodes = state.list_nodes()
        assert len(nodes) == 1 and nodes[0]["alive"]
        actors = state.list_actors()
        assert len(actors) == 1 and actors[0]["state"] == "ALIVE"
        tasks = state.list_tasks(filters=[("state", "=", "FINISHED")])
        assert any(t["name"] == "ping" for t in tasks)
        summary = state.summarize_tasks()
        assert summary["ping"]["FINISHED"] == 1
        objs = state.list_objects()
        assert objs[0]["num_objects"] >= 0

    def test_filters(self, rt_start):
        rt = rt_start

        @rt.remote
        def ok():
            return 1

        rt.get(ok.remote())
        from ray_tpu.util import state

        assert state.list_tasks(filters=[("state", "=", "NOPE")]) == []
        with pytest.raises(ValueError):
            state.list_tasks(filters=[("state", ">", "x")])


class TestClusterEvents:
    def test_worker_events_reach_driver(self):
        """Worker-side RUNNING/FINISHED events flush to the head and appear in
        the driver's list_tasks and timeline (reference: TaskEventBuffer →
        GcsTaskManager → state API)."""
        import time

        import ray_tpu
        from ray_tpu.util import state

        ray_tpu.shutdown()
        ray_tpu.init(address="local-cluster", num_cpus=2)
        try:
            @ray_tpu.remote
            def traced_task():
                return 7

            assert ray_tpu.get(traced_task.remote()) == 7
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                rows = state.list_tasks(filters=[("name", "=", "traced_task")])
                if rows and rows[0]["state"] == "FINISHED":
                    break
                time.sleep(0.2)
            else:
                raise AssertionError(f"worker events never arrived: {rows}")
            trace = ray_tpu.timeline()
            assert any(ev["name"] == "traced_task" for ev in trace)
        finally:
            ray_tpu.shutdown()


class TestDashboard:
    def test_http_endpoints(self, rt_start):
        rt = rt_start
        from ray_tpu.dashboard.http_server import DashboardServer

        @rt.remote
        def h():
            return 1

        rt.get(h.remote())
        srv = DashboardServer()
        host, port = srv.start()
        try:
            def get(path):
                with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=5) as r:
                    body = r.read()
                    return r.headers.get_content_type(), body

            ctype, body = get("/api/version")
            assert ctype == "application/json"
            assert json.loads(body)["version"]
            _, body = get("/api/nodes")
            assert json.loads(body)[0]["alive"]
            _, body = get("/api/tasks")
            assert any(t["name"] == "h" for t in json.loads(body))
            _, body = get("/api/cluster_status")
            assert "cluster_resources" in json.loads(body)
            ctype, body = get("/metrics")
            assert ctype == "text/plain"
            _, body = get("/api/timeline")
            assert isinstance(json.loads(body), list)
            # web UI at the root: an SPA shell that loads the app module
            ctype, body = get("/")
            assert ctype == "text/html"
            page = body.decode()
            assert "/app.js" in page and "</html>" in page
            ctype, body = get("/app.js")
            assert ctype == "text/javascript"
            app = body.decode()
            # the client drives the same JSON API surface
            for ep in ("/api/cluster_status", "/api/nodes", "/api/actors",
                       "/api/tasks", "/api/placement_groups",
                       "/api/jobs/list", "/api/logs"):
                assert ep in app, ep
            ctype, _ = get("/app.css")
            assert ctype == "text/css"
            # per-node log endpoints exist (cluster mode returns data; the
            # in-process runtime yields an empty listing)
            _, body = get("/api/logs")
            assert json.loads(body) == []
        finally:
            srv.stop()

    def test_unknown_route_404(self, rt_start):
        from ray_tpu.dashboard.http_server import DashboardServer

        srv = DashboardServer()
        host, port = srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://{host}:{port}/nope", timeout=5)
        finally:
            srv.stop()


def test_otlp_export_shape(rt_start):
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable_tracing()
    try:
        with tracing.span("outer", kind="client"):
            with tracing.span("inner"):
                pass
        otlp = tracing.export_otlp()
        spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
        names = {s["name"] for s in spans}
        assert {"outer", "inner"} <= names
        inner = next(s for s in spans if s["name"] == "inner")
        outer = next(s for s in spans if s["name"] == "outer")
        assert inner["parentSpanId"] == outer["spanId"]
        assert inner["traceId"] == outer["traceId"]
        assert int(inner["endTimeUnixNano"]) >= int(inner["startTimeUnixNano"])
    finally:
        tracing.disable_tracing()


def test_cross_process_trace_propagation(rt_start):
    """A traced submission's context rides the TaskSpec into the executor
    (reference: _DictPropagator through task metadata)."""
    import ray_tpu
    from ray_tpu import remote
    from ray_tpu.util import tracing

    tracing.clear()
    tracing.enable_tracing()
    try:
        @remote
        def traced():
            return 1

        with tracing.span("driver", kind="client"):
            ref = traced.remote()
        assert ray_tpu.get(ref, timeout=30) == 1
        by_name = {s.name: s for s in tracing.spans()}
        assert "driver" in by_name and "traced" in by_name
        assert by_name["traced"].trace_id == by_name["driver"].trace_id
    finally:
        tracing.disable_tracing()


def test_cli_status_and_list(rt_start, capsys):
    from ray_tpu.scripts.cli import main

    assert main(["status"]) == 0
    out = capsys.readouterr().out
    assert "Cluster resources" in out and "CPU" in out
    assert main(["list", "nodes", "--json"]) == 0
    import json as _json

    rows = _json.loads(capsys.readouterr().out)
    assert isinstance(rows, list)


def test_cli_timeline(rt_start, tmp_path, capsys):
    import ray_tpu
    from ray_tpu import remote
    from ray_tpu.scripts.cli import main

    @remote
    def work():
        return 1

    ray_tpu.get([work.remote() for _ in range(3)])
    out = str(tmp_path / "tl.json")
    assert main(["timeline", "--out", out]) == 0
    import json as _json

    events = _json.load(open(out))
    assert isinstance(events, list)


def test_usage_recording(rt_start, tmp_path, monkeypatch):
    from ray_tpu import usage

    usage.record_library_usage("train")
    usage.record_library_usage("train")  # dedup
    assert "library:train" in usage.recorded_features()
    monkeypatch.setenv("RTPU_USAGE_STATS_ENABLED", "0")
    usage.record_library_usage("secret")
    assert "library:secret" not in usage.recorded_features()


class TestLogs:
    def test_list_and_tail_worker_logs(self):
        """Per-node worker log listing + tail through the daemons
        (reference: `ray logs` via the dashboard agent)."""
        import time

        from ray_tpu.cluster_utils import Cluster
        from ray_tpu.core.remote_function import remote
        from ray_tpu.core.worker import global_worker
        from ray_tpu.util.state.api import get_log, list_logs
        from ray_tpu.utils.ids import JobID

        import ray_tpu

        c = Cluster()
        c.add_node(num_cpus=2)
        rt = c.connect()
        old = (global_worker.runtime, global_worker.worker_id,
               global_worker.node_id, global_worker.mode,
               global_worker.job_id)
        global_worker.runtime = rt
        global_worker.worker_id = rt.worker_id
        global_worker.node_id = rt.node_id
        global_worker.job_id = JobID.from_random()
        global_worker.mode = "cluster"
        try:
            @remote
            def noisy():
                print("log-marker-xyzzy")
                return 1

            assert ray_tpu.get(noisy.remote(), timeout=60) == 1
            time.sleep(0.3)  # let the worker's write hit the file
            logs = list_logs()
            assert logs and all("filename" in l and "node_id" in l
                                for l in logs)
            found = any(
                "log-marker-xyzzy" in get_log(l["filename"], l["node_id"])
                for l in logs)
            assert found, "worker print not found in any log file"
            with pytest.raises(FileNotFoundError):
                get_log("../etc/passwd", logs[0]["node_id"])
        finally:
            rt.shutdown()
            c.shutdown()
            (global_worker.runtime, global_worker.worker_id,
             global_worker.node_id, global_worker.mode,
             global_worker.job_id) = old
