"""Connector pipelines: composable observation/action transforms.

Capability parity with the reference's connector framework (reference:
rllib/connectors/ — ConnectorV2 pieces composed into env-to-module and
module-to-env pipelines that every EnvRunner applies; previously these
transforms were ad hoc per algorithm). A pipeline is an ordered list of
connectors; env-to-module runs on observations before the policy, and
module-to-env runs on the policy's actions before the environment.

Stateful connectors (running normalizers, frame stacks) expose
state_dict/set_state so checkpoints capture them; runner-local state is
the compact substitution for the reference's cross-runner state merge.
"""

from __future__ import annotations


import numpy as np


class Connector:
    """One transform stage. ``__call__(batch)`` maps a [N, ...] numpy
    batch to its transformed batch. ``frozen`` applies the transform
    without advancing internal state (bootstrap observations); every
    stateful connector must honor it — the base default makes the
    contract uniform."""

    frozen = False

    def __call__(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def reset(self, env_index: int) -> None:
        """Episode boundary for one vectorized env (frame stacks etc.)."""

    def state_dict(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: list[Connector] | None = None):
        self.connectors = list(connectors or [])

    def __call__(self, x: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            x = c(x)
        return x

    def reset(self, env_index: int) -> None:
        for c in self.connectors:
            c.reset(env_index)

    def state_dict(self) -> dict:
        return {i: c.state_dict() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])

    def frozen_apply(self, x: np.ndarray) -> np.ndarray:
        """Apply without advancing any connector's state (bootstrap
        observations ride through; the pipeline owns the contract)."""
        prior = [(c, c.frozen) for c in self.connectors]
        for c in self.connectors:
            c.frozen = True
        try:
            return self(x)
        finally:
            for c, old in prior:
                c.frozen = old

    @property
    def output_multiplier(self) -> int:
        """Observation-width growth factor (frame stacking)."""
        m = 1
        for c in self.connectors:
            m *= getattr(c, "output_multiplier", 1)
        return m


# ---------------------------------------------------------- env-to-module --

class NormalizeObservations(Connector):
    """Running mean/std observation normalization (reference:
    connectors/env_to_module/mean_std_filter.py)."""

    def __init__(self, clip: float = 10.0):
        self.clip = clip
        self._count = 1e-4
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None
        self.frozen = False  # evaluation mode: apply without updating

    def __call__(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        if self._mean is None:
            self._mean = np.zeros(x.shape[-1], np.float64)
            self._m2 = np.ones(x.shape[-1], np.float64)
        if not self.frozen:
            # Batched Welford merge (Chan et al.): one vectorized pass per
            # batch instead of a per-row Python loop on the rollout path.
            rows = x.reshape(-1, x.shape[-1]).astype(np.float64)
            n = rows.shape[0]
            b_mean = rows.mean(0)
            b_m2 = ((rows - b_mean) ** 2).sum(0)
            delta = b_mean - self._mean
            tot = self._count + n
            self._mean = self._mean + delta * (n / tot)
            self._m2 = (self._m2 + b_m2
                        + delta**2 * (self._count * n / tot))
            self._count = tot
        std = np.sqrt(self._m2 / self._count) + 1e-6
        out = (x - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def state_dict(self) -> dict:
        # Copies: the live arrays keep mutating, and a restored connector
        # must never alias the donor's state.
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state: dict) -> None:
        self._count = state["count"]
        self._mean = (None if state["mean"] is None
                      else np.array(state["mean"], np.float64))
        self._m2 = (None if state["m2"] is None
                    else np.array(state["m2"], np.float64))


class FrameStack(Connector):
    """Stack the last k observations per env (reference:
    connectors/env_to_module/frame_stacking.py). Output width = k × obs."""

    def __init__(self, k: int = 4):
        self.k = k
        self._buf: np.ndarray | None = None
        self._refill: set[int] = set()  # envs awaiting post-reset refill

    @property
    def output_multiplier(self) -> int:
        return self.k

    def __call__(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        n, d = x.shape
        if self._buf is None or self._buf.shape[0] != n:
            self._buf = np.tile(x[:, None, :], (1, self.k, 1))
            self._refill.clear()
        elif self.frozen:
            # Peek: stack as if pushed, without mutating (bootstrap obs).
            return np.concatenate(
                [self._buf[:, 1:], x[:, None, :]], axis=1).reshape(
                    n, self.k * d)
        else:
            self._buf = np.concatenate(
                [self._buf[:, 1:], x[:, None, :]], axis=1)
            # Post-reset envs refill ALL frames with the reset observation
            # (reference behavior) — zero frames would be inputs the
            # policy never sees at init.
            for i in self._refill:
                self._buf[i] = x[i]
            self._refill.clear()
        return self._buf.reshape(n, self.k * d)

    def reset(self, env_index: int) -> None:
        self._refill.add(int(env_index))

    def state_dict(self) -> dict:
        return {"buf": None if self._buf is None else self._buf.copy(),
                "refill": set(self._refill)}

    def set_state(self, state: dict) -> None:
        self._buf = (None if state["buf"] is None
                     else np.array(state["buf"], np.float32))
        self._refill = set(state.get("refill", ()))


class ClipObservations(Connector):
    def __init__(self, lo: float = -10.0, hi: float = 10.0):
        self.lo, self.hi = lo, hi

    def __call__(self, x: np.ndarray) -> np.ndarray:
        return np.clip(np.asarray(x, np.float32), self.lo, self.hi)


# ---------------------------------------------------------- module-to-env --

class ClipActions(Connector):
    """Clip continuous actions to the env's bounds (reference:
    connectors/module_to_env/... action clipping)."""

    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def __call__(self, a: np.ndarray) -> np.ndarray:
        return np.clip(a, -self.limit, self.limit)


class UnsquashActions(Connector):
    """Map tanh-squashed [-1, 1] model actions onto [-limit, limit]."""

    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def __call__(self, a: np.ndarray) -> np.ndarray:
        return np.clip(a, -1.0, 1.0) * self.limit
