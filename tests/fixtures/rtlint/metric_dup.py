"""R4 fixture: the PR-8 same-name metric double-registration bug.

Router and replica each constructed ``Counter("serve_shed_total")``; the
registry keeps ONE object per name, so whichever side lost the race
incremented a counter the exporter could no longer see — sheds silently
vanished from /metrics. Also reproduces the PR-9 reserved ``node_id``
label misuse (federation stamps node_id head-side; a local label would
collide)."""

from ray_tpu.util.metrics import Counter


def router_metrics():
    return Counter("fixture_shed_total", "sheds at the router",
                   tag_keys=("deployment",))


def replica_metrics():
    # BUG (PR-8): same metric name registered at a second call site.
    return Counter("fixture_shed_total", "sheds at the replica",
                   tag_keys=("deployment",))


def federated_wrong():
    # BUG (PR-9): node_id is reserved for head federation.
    return Counter("fixture_node_counter", "per-node things",
                   tag_keys=("node_id",))
