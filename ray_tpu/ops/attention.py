"""Attention ops: naive reference, blockwise (memory-efficient, autodiff-able),
and a Pallas TPU flash-attention forward kernel.

This layer is new work relative to the reference framework — Ray delegates
intra-model compute to torch/vLLM (reference: SURVEY.md §5 "long-context ...
the reference has none"); a TPU-native framework owns its attention kernels.

Design:
- ``attention_reference``: O(S²) jnp softmax attention — ground truth in tests.
- ``blockwise_attention``: lax.scan over KV blocks with online softmax; O(S)
  activations, differentiable, runs anywhere. This is also the inner step of
  ring attention (ray_tpu/ops/ring_attention.py).
- ``flash_attention``: pl.pallas_call kernel (MXU-tiled, VMEM-resident online
  softmax, causal masking with block skipping); custom_vjp whose backward
  recomputes through ``blockwise_attention``.

Shapes: q [B, H, Sq, D], k/v [B, Hkv, Skv, D]; GQA when Hkv < H.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

NEG_INF = -1e30
LOG2E = 1.4426950408889634  # log2(e): kernels run base-2 softmax (exp2 is
LN2 = 0.6931471805599453    # the VPU-native transcendental; exp = mul+exp2)

# When True, Pallas kernels run in interpreter mode (and the Pallas path is
# taken off-TPU too) — lets CPU tests exercise the exact kernel code.
INTERPRET = False

# Fused dq+dkv backward (one kernel, 5 matmuls per block pair instead of 7
# across the split kernels). RTPU_FLASH_FUSED_BWD=0 falls back to the split
# dq / dkv kernels.
import os as _os

FUSED_BWD = _os.environ.get("RTPU_FLASH_FUSED_BWD", "1") != "0"


def _env_int(name: str, default: int) -> int:
    v = _os.environ.get(name)
    if not v:
        return default
    try:
        n = int(v)
    except ValueError:
        return default
    return n if n > 0 else default


def flash_blocks(block_q: int | None = None,
                 block_k: int | None = None) -> tuple[int, int]:
    """Resolve flash-attention kernel block sizes: explicit argument wins,
    then the RTPU_FLASH_BLOCK_Q / RTPU_FLASH_BLOCK_K env overrides (the
    autotuner sets these per candidate before tracing — block size is a
    compile-time grid parameter, so each value is a separate compile), then
    the 512 default chip-measured best at the bench geometry. Values must
    divide the sequence length; the pallas wrappers assert that loudly."""
    return (block_q or _env_int("RTPU_FLASH_BLOCK_Q", 512),
            block_k or _env_int("RTPU_FLASH_BLOCK_K", 512))

# Scoped-VMEM ceiling for the flash kernels, by TPU generation: v5e/v5p/v6
# expose 128 MB of VMEM per core, where the compiler's default 16 MB scoped
# limit is too tight for packed blocks but a flat 96 MB would OVERSUBSCRIBE
# the 16 MB VMEM of v2-v4 (the compiler rejects or spills). Unknown chips
# (and CPU interpret runs) keep the compiler default. Override with
# RTPU_FLASH_VMEM_LIMIT_MB (0 = force the compiler default).
_VMEM_LIMIT_MB_BY_GEN = {"v5": 96, "v6": 96, "v7": 96}
_vmem_limit_cache: list = []  # [int | None] once resolved


def _compiler_params(pltpu, **kwargs):
    """pltpu.CompilerParams across jax versions (older releases ship it
    as TPUCompilerParams; same fields)."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def _flash_vmem_limit_bytes() -> int | None:
    """vmem_limit_bytes for pallas CompilerParams, derived from the
    detected TPU generation; None means 'leave the compiler default'."""
    if _vmem_limit_cache:
        return _vmem_limit_cache[0]
    limit: int | None = None
    env = _os.environ.get("RTPU_FLASH_VMEM_LIMIT_MB")
    if env is not None:
        try:
            mb = int(env)
            limit = mb * 1024 * 1024 if mb > 0 else None
        except ValueError:
            limit = None
    else:
        try:
            kind = jax.devices()[0].device_kind.lower()  # e.g. "tpu v5 lite"
            gen = None
            for tok in kind.replace("tpu", " ").split():
                if tok.startswith("v") and len(tok) >= 2 and \
                        tok[1].isdigit():
                    gen = tok[:2]
                    break
            if gen is not None:
                mb = _VMEM_LIMIT_MB_BY_GEN.get(gen)
                if mb is not None:
                    limit = mb * 1024 * 1024
        except Exception:
            limit = None  # backend unavailable: compiler default
    _vmem_limit_cache.append(limit)
    return limit


def _repeat_kv(k: jax.Array, num_heads: int) -> jax.Array:
    """Expand KV heads to match query heads (GQA)."""
    b, hkv, s, d = k.shape
    if hkv == num_heads:
        return k
    rep = num_heads // hkv
    return jnp.repeat(k, rep, axis=1)


def attention_reference(q, k, v, causal: bool = True, sm_scale: float | None = None,
                        q_offset: int = 0):
    """O(S²) reference. q_offset: absolute position of q[0] (for ring/chunked)."""
    b, h, sq, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(sq)[:, None] + q_offset
        kpos = jnp.arange(k.shape[2])[None, :]
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def blockwise_attention(q, k, v, causal: bool = True,
                        sm_scale: float | None = None,
                        kv_block: int = 512, q_offset: int = 0,
                        kv_offset: int = 0):
    """Online-softmax attention scanned over KV blocks.

    Activation memory is O(Sq · D) regardless of Skv. Differentiable (autodiff
    through the scan); combine with jax.checkpoint for long sequences.
    """
    b, h, sq, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    skv = k.shape[2]
    kv_block = min(kv_block, skv)
    nblocks = (skv + kv_block - 1) // kv_block
    pad = nblocks * kv_block - skv
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    kb = k.reshape(b, h, nblocks, kv_block, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblocks, kv_block, d).transpose(2, 0, 1, 3, 4)

    qpos = jnp.arange(sq) + q_offset

    def step(carry, inputs):
        o, m, l = carry
        blk_idx, kblk, vblk = inputs
        kpos = blk_idx * kv_block + jnp.arange(kv_block) + kv_offset
        # preferred_element_type (bf16 MXU inputs, f32 accumulate) rather
        # than a bf16 dot + astype: the cast form miscompiles under XLA
        # fusion in the scan's backward (NaN dq/dk on CPU and TPU for
        # multi-block bf16 inputs) and is lower-precision anyway.
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk,
                       preferred_element_type=jnp.float32) * scale
        valid = (kpos[None, :] - kv_offset) < skv  # mask zero-padding
        if causal:
            full_mask = (kpos[None, :] <= qpos[:, None]) & valid
        else:
            full_mask = valid
        s = jnp.where(full_mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)
        return (o_new, m_new, l_new), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    idxs = jnp.arange(nblocks)
    (o, m, l), _ = lax.scan(step, (o0, m0, l0), (idxs, kb, vb))
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention forward kernel
# ---------------------------------------------------------------------------

def _pick_pack(rep: int) -> int:
    """Q-heads packed per kernel invocation. Packing P heads that share one
    GQA kv head row-concatenates their q blocks into [P*block_q, d] tiles:
    every matmul and VPU softmax op becomes P× larger (amortizing per-op
    overheads that dominate at head_dim 64) while the causal block-skip
    granularity stays block_q. Chip-measured fwd at the bench geometry
    (B4 H32 KV8 S2048 D64): 36.5 → 48.0 TF/s with pack=4 + the inline
    diagonal (devbench/prof_flash_pack.py, r5)."""
    for p in (4, 2):
        if rep % p == 0:
            return p
    return 1


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, kv_seq_len: int,
                      block_k: int, sm_scale: float, causal: bool,
                      inline_diag: bool):
    """Grid: (batch*heads/pack, q_blocks). K/V stream through VMEM in
    block_k chunks; online softmax state lives in registers/VMEM. Emits the
    per-row logsumexp so the backward can recompute p = exp(s - lse)
    without a second online pass (FlashAttention-2 shape).

    Causal modes:
    - inline_diag (block_q == block_k, sq == skv): a mask-free fori_loop
      over the fully-visible kv blocks, then the single partial (diagonal)
      block unrolled as straight-line code with a LOCAL triangular mask
      (identical for every qi). Two fori_loops pipeline worse in Mosaic
      (r4 + r5 measurements); one loop + an unrolled tail does not.
    - generic: per-block global position mask with a traced upper bound.
    """
    from jax.experimental import pallas as pl  # local: TPU-only dependency

    qi = pl.program_id(1)
    # Keep q bf16: the MXU runs bf16×bf16 with f32 accumulation at full
    # rate; casting inputs to f32 would fall off the fast path (~6x
    # slower). The base-2 scale (p = exp2(s2 - m2)) is folded into q ONCE
    # per packed q tile instead of multiplying every [rows, bk] score
    # block on the VPU; the extra bf16 rounding of q·scale is ~0.4%
    # relative on the logit — inside flash-attention's bf16 error budget.
    q = q_ref[...]                       # [pack, bq, d]
    pack, bq, d = q.shape
    rows = pack * bq
    q2 = q.reshape(rows, d)
    scale2 = sm_scale * LOG2E
    qs = (q2.astype(jnp.float32) * scale2).astype(q2.dtype)

    nkv = kv_seq_len // block_k

    def body(j, carry, masked, local_tri=False):
        o, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jnp.dot(qs, k.T,
                    preferred_element_type=jnp.float32)  # [rows, bk]
        if masked:
            # Packed row r is query position qi*bq + (r mod bq).
            lq = lax.rem(lax.broadcasted_iota(jnp.int32, s.shape, 0), bq)
            lk = lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if local_tri:
                # Diagonal block: same local triangular pattern for all qi.
                s = jnp.where(lk <= lq, s, NEG_INF)
            else:
                s = jnp.where(j * block_k + lk <= qi * bq + lq, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        # Fold the row-sum of p into the p@v matmul via a ones column
        # appended to v: the MXU (at ~30% utilization here) absorbs the
        # reduction the VPU would otherwise do across the lane dimension
        # (chip-measured fwd 2.35 -> 2.10 ms at the bench geometry). Note
        # l now sums the BF16-quantized p — the same p the o matmul uses —
        # so o/l stay mutually consistent, but lse shifts ~1e-3 relative
        # vs an f32-accumulated sum; the backward recomputes p from this
        # same lse, keeping gradients self-consistent.
        v1 = jnp.concatenate(
            [v, jnp.ones((v.shape[0], 1), v.dtype)], axis=1)
        ov = jnp.dot(p.astype(v.dtype), v1,
                     preferred_element_type=jnp.float32)
        l_new = l * alpha + lax.slice(ov, (0, d), (rows, d + 1))[:, 0]
        o_new = o * alpha[:, None] + lax.slice(ov, (0, 0), (rows, d))
        return o_new, m_new, l_new

    o0 = jnp.zeros((rows, d), jnp.float32)
    m0 = jnp.full((rows,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows,), jnp.float32)

    if causal and inline_diag:
        carry = lax.fori_loop(
            0, qi, functools.partial(body, masked=False), (o0, m0, l0))
        o, m, l = body(qi, carry, masked=True, local_tri=True)
    elif causal:
        upper = lax.div((qi + 1) * bq + block_k - 1, block_k)
        upper = jnp.minimum(upper, nkv)
        o, m, l = lax.fori_loop(
            0, upper, functools.partial(body, masked=True), (o0, m0, l0))
    else:
        o, m, l = lax.fori_loop(
            0, nkv, functools.partial(body, masked=False), (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype).reshape(pack, bq, d)
    lse_ref[...] = ((m + jnp.log2(l)) * LN2).reshape(pack, bq)  # natural-log


def _packed_qspecs(pack, block_q, d, kv_div, skv):
    """BlockSpecs for the packed-head layout: q-side arrays live as
    [b*h/pack, pack, sq, d] (adjacent heads grouped, so flat group index
    i // (rep/pack) is exactly the flat kv-head index), row statistics as
    [b*h/pack, pack, sq]."""
    from jax.experimental import pallas as pl

    return (
        pl.BlockSpec((None, pack, block_q, d), lambda i, j: (i, 0, j, 0)),
        pl.BlockSpec((None, skv, d), lambda i, j: (i // kv_div, 0, 0)),
        pl.BlockSpec((None, pack, block_q), lambda i, j: (i, 0, j)),
    )


def _flash_fwd_pallas(q, k, v, causal: bool, sm_scale: float,
                      block_q: int | None = None, block_k: int | None = None):
    """GQA-native: k/v stay [B, Hkv, S, D]; the BlockSpec index maps send
    each packed q-head group to its kv head — no materialized repeat."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q, block_k = flash_blocks(block_q, block_k)
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    rep = h // hkv
    block_q = min(block_q, sq)
    # The inline-diagonal causal mode needs square blocks on the diagonal.
    inline_diag = causal and sq == skv and sq % block_q == 0
    block_k = block_q if inline_diag else min(block_k, skv)
    assert sq % block_q == 0 and skv % block_k == 0, (
        "flash_attention requires seq lengths divisible by block sizes"
    )
    pack = _pick_pack(rep)
    g = b * h // pack
    kv_div = rep // pack
    qf = q.reshape(g, pack, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)

    kernel = functools.partial(
        _flash_fwd_kernel, kv_seq_len=skv, block_k=block_k,
        sm_scale=sm_scale, causal=causal, inline_diag=inline_diag,
    )
    qspec, kvspec, rowspec = _packed_qspecs(pack, block_q, d, kv_div, skv)
    out, lse = pl.pallas_call(
        kernel,
        grid=(g, sq // block_q),
        in_specs=[qspec, kvspec, kvspec],
        out_specs=[qspec, rowspec],
        out_shape=[
            jax.ShapeDtypeStruct((g, pack, sq, d), q.dtype),
            # Row statistics as [g, pack, sq] blocks of (pack, block_q):
            # the sublane dim equals the array dim (TPU tiling requires the
            # last two block dims be (8k, 128k) or match the array), without
            # the official kernel's 128-lane broadcast copy of every row.
            jax.ShapeDtypeStruct((g, pack, sq), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "arbitrary"),
            # Generation-derived scoped-vmem ceiling (96 MB on 128 MB-VMEM
            # chips, compiler default elsewhere) — leaves headroom for
            # pipelining without oversubscribing small-VMEM generations.
            **({"vmem_limit_bytes": _flash_vmem_limit_bytes()}
               if _flash_vmem_limit_bytes() is not None else {}),
        ),
        interpret=INTERPRET,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _rows_3d(x, bh, s):
    """[B, H, S] row-statistics → the [B*H, 1, S] kernel layout."""
    return x.reshape(bh, 1, s)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, kv_seq_len: int, block_k: int,
                         sm_scale: float, causal: bool, block_q: int):
    """dQ, one q block per grid step: dq = Σ_j (p ∘ (dO·Vᵀ − Δ))·K · scale
    with p recomputed from the saved logsumexp."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[...]                       # [bq, d] bf16
    do = do_ref[...].astype(jnp.float32)
    lse2 = lse_ref[0, :] * LOG2E         # [bq] f32, base-2
    delta = delta_ref[0, :]              # [bq] f32
    nkv = kv_seq_len // block_k
    scale2 = sm_scale * LOG2E
    # Same bf16 q·scale folding as the forward — the saved lse encodes
    # logits computed from the ROUNDED qs, so the backward must recompute
    # s identically or exp2(s - lse) rows stop summing to 1.
    qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])  # [bq, bk]
        dp = jnp.dot(do.astype(v.dtype), v.T,
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    if causal:
        upper = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        upper = jnp.minimum(upper, nkv)
    else:
        upper = nkv
    d = q_ref.shape[-1]
    dq = lax.fori_loop(0, upper, body, jnp.zeros((q.shape[0], d), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, q_seq_len: int, block_q: int,
                          sm_scale: float, causal: bool, block_k: int):
    """dK/dV, one kv block per grid step: dv = Σ_i pᵀ·dO,
    dk = Σ_i (p ∘ (dO·Vᵀ − Δ))ᵀ·Q · scale. Causal skips q blocks above
    the diagonal (they can't attend to this kv block)."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    k = k_ref[...]                       # [bk, d] bf16
    v = v_ref[...]
    nq = q_seq_len // block_q
    scale2 = sm_scale * LOG2E

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse2 = lse_ref[0, pl.ds(i * block_q, block_q)] * LOG2E
        delta = delta_ref[0, pl.ds(i * block_q, block_q)]
        # Rounded q·scale fold matches the forward's lse (see dq kernel).
        qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])  # [bq, bk]
        dv = dv + jnp.dot(p.astype(do.dtype).T, do,
                          preferred_element_type=jnp.float32)
        dp = jnp.dot(do.astype(v.dtype), v.T,
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jnp.dot(ds.astype(q.dtype).T, q,
                          preferred_element_type=jnp.float32)
        return dk, dv

    lower = lax.div(ki * block_k, block_q) if causal else 0
    d = k_ref.shape[-1]
    z = jnp.zeros((k.shape[0], d), jnp.float32)
    dk, dv = lax.fori_loop(lower, nq, body, (z, z))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                            kv_seq_len: int, block_k: int, sm_scale: float,
                            causal: bool, inline_diag: bool):
    """Fused backward: ONE pass over (q block, kv block) pairs computes
    dq, dk and dv together — the split dq/dkv kernels each recompute
    s = q·kᵀ, p and dp = dO·vᵀ for every pair (7 matmuls/pair across the
    two kernels); fused needs 5 and reads q/k/v/dO/lse/Δ once.

    Grid: (batch*heads/pack, q_blocks). dq is written per q block. dk/dv
    accumulate in f32 VMEM scratch across the whole q sweep (scratch
    persists over the sequential inner grid dim) and flush ONCE to HBM in
    the kernel's native dtype at the last q block — the HBM buffers stay
    bf16-sized instead of the f32 accumulator layout.

    Head packing bonus: the packed heads share one kv head, so the
    dv += p_catᵀ·dO_cat and dk += ds_catᵀ·q_cat matmuls (contraction over
    the packed rows) compute the GQA head-group fold for free — dk/dv HBM
    outputs shrink by pack× and the external fold pass disappears when
    pack == rep. Causal modes as in _flash_fwd_kernel (inline_diag:
    mask-free loop + the single diagonal block unrolled straight-line)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    nq = pl.num_programs(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[...]                       # [pack, bq, d] bf16
    pack, bq, d = q.shape
    rows = pack * bq
    q2 = q.reshape(rows, d)
    do = do_ref[...].reshape(rows, d)    # bf16
    # Row stats stay [pack, bq]: Mosaic supports collapsing LEADING dims
    # (same lane layout) but not a 2D→1D shape cast, so per-row broadcasts
    # below go through a [pack, bq, bk] view.
    lse2 = lse_ref[...] * LOG2E          # [pack, bq] f32, base-2
    delta = delta_ref[...]               # [pack, bq] f32
    nkv = kv_seq_len // block_k
    scale2 = sm_scale * LOG2E
    # Scale folding (see _flash_fwd_kernel): the logit scale rides q into
    # the s matmul, and ds's sm_scale rides the [*, d]-shaped matmul
    # OPERANDS (q for dk, k for dq) — two fewer [rows, bk] VPU multiplies
    # per block pair, at one extra bf16 rounding (~0.4%) on the operand.
    qs = (q2.astype(jnp.float32) * scale2).astype(q2.dtype)
    q_sc = (q2.astype(jnp.float32) * sm_scale).astype(q2.dtype)

    def body(j, dq, masked, local_tri=False):
        kslc = pl.ds(j * block_k, block_k)
        k = k_ref[kslc, :]
        v = v_ref[kslc, :]
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
        if masked:
            lq = lax.rem(lax.broadcasted_iota(jnp.int32, s.shape, 0), bq)
            lk = lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if local_tri:
                s = jnp.where(lk <= lq, s, NEG_INF)
            else:
                s = jnp.where(j * block_k + lk <= qi * bq + lq, s, NEG_INF)
        bk = s.shape[1]
        p = jnp.exp2(
            (s.reshape(pack, bq, bk) - lse2[..., None]).reshape(rows, bk))
        dp = jnp.dot(do.astype(v.dtype), v.T,
                     preferred_element_type=jnp.float32)
        ds = p * (dp.reshape(pack, bq, bk)
                  - delta[..., None]).reshape(rows, bk)  # unscaled;
        # the sm_scale rides the matmul operands below
        k_sc = (k.astype(jnp.float32) * sm_scale).astype(k.dtype)
        dv_acc[kslc, :] += jnp.dot(p.astype(do.dtype).T, do,
                                   preferred_element_type=jnp.float32)
        dk_acc[kslc, :] += jnp.dot(ds.astype(q2.dtype).T, q_sc,
                                   preferred_element_type=jnp.float32)
        return dq + jnp.dot(ds.astype(k.dtype), k_sc,
                            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((rows, d), jnp.float32)
    if causal and inline_diag:
        dq = lax.fori_loop(0, qi, functools.partial(body, masked=False), dq0)
        dq = body(qi, dq, masked=True, local_tri=True)
    elif causal:
        upper = lax.div((qi + 1) * bq + block_k - 1, block_k)
        upper = jnp.minimum(upper, nkv)
        dq = lax.fori_loop(0, upper, functools.partial(body, masked=True),
                           dq0)
    else:
        dq = lax.fori_loop(0, nkv, functools.partial(body, masked=False),
                           dq0)
    dq_ref[...] = dq.astype(dq_ref.dtype).reshape(pack, bq, d)

    @pl.when(qi == nq - 1)
    def _flush():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_fused_pallas(q, k, v, out, lse, g, causal: bool,
                            sm_scale: float,
                            block_q: int | None = None,
                            block_k: int | None = None):
    """Single-kernel backward (see _flash_bwd_fused_kernel). dk/dv come
    back folded to kv heads [B, Hkv, S, D] — the pack-group fold happens
    inside the kernel's accumulation; any remaining rep/pack groups are
    folded here in f32. The f32 accumulation lives in VMEM scratch, not
    HBM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q, block_k = flash_blocks(block_q, block_k)
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    rep = h // hkv
    block_q = min(block_q, sq)
    inline_diag = causal and sq == skv and sq % block_q == 0
    block_k = block_q if inline_diag else min(block_k, skv)
    pack = _pick_pack(rep)
    grp = b * h // pack
    kv_div = rep // pack
    qf = q.reshape(grp, pack, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)
    dof = g.reshape(grp, pack, sq, d).astype(q.dtype)
    lsef = lse.reshape(grp, pack, sq)
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    deltaf = delta.reshape(grp, pack, sq)

    qspec, kvspec, rowspec = _packed_qspecs(pack, block_q, d, kv_div, skv)
    dkvspec = pl.BlockSpec((None, skv, d), lambda i, j: (i, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_fused_kernel, kv_seq_len=skv,
                          block_k=block_k, sm_scale=sm_scale, causal=causal,
                          inline_diag=inline_diag),
        grid=(grp, sq // block_q),
        in_specs=[qspec, kvspec, kvspec, qspec, rowspec, rowspec],
        out_specs=[qspec, dkvspec, dkvspec],
        out_shape=[
            jax.ShapeDtypeStruct((grp, pack, sq, d), q.dtype),
            jax.ShapeDtypeStruct((grp, skv, d), q.dtype),
            jax.ShapeDtypeStruct((grp, skv, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((skv, d), jnp.float32),
            pltpu.VMEM((skv, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "arbitrary"),
            # Generation-derived scoped-vmem ceiling (96 MB on 128 MB-VMEM
            # chips, compiler default elsewhere) — leaves headroom for
            # pipelining without oversubscribing small-VMEM generations.
            **({"vmem_limit_bytes": _flash_vmem_limit_bytes()}
               if _flash_vmem_limit_bytes() is not None else {}),
        ),
        interpret=INTERPRET,
    )(qf, kf, vf, dof, lsef, deltaf)
    dq = dq.reshape(b, h, sq, d)
    if kv_div > 1:  # fold the remaining head groups per kv head, in f32
        dk = dk.astype(jnp.float32).reshape(b, hkv, kv_div, skv, d).sum(2)
        dv = dv.astype(jnp.float32).reshape(b, hkv, kv_div, skv, d).sum(2)
    else:
        dk = dk.reshape(b, hkv, skv, d)
        dv = dv.reshape(b, hkv, skv, d)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, causal: bool, sm_scale: float,
                      block_q: int | None = None, block_k: int | None = None):
    """GQA-native like the forward: k/v stay [B, Hkv, S, D]; dk/dv come back
    per *query* head [B, H, S, D] (caller folds the group dimension)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q, block_k = flash_blocks(block_q, block_k)
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    rep = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)
    dof = g.reshape(b * h, sq, d).astype(q.dtype)
    lsef = _rows_3d(lse, b * h, sq)
    # Δ_i = rowsum(dO ∘ O): the softmax-normalization term of ds.
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    deltaf = _rows_3d(delta, b * h, sq)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, kv_seq_len=skv,
                          block_k=block_k, sm_scale=sm_scale, causal=causal,
                          block_q=block_q),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // rep, 0, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // rep, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=INTERPRET,
    )(qf, kf, vf, dof, lsef, deltaf)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, q_seq_len=sq,
                          block_q=block_q, sm_scale=sm_scale, causal=causal,
                          block_k=block_k),
        grid=(b * h, skv // block_k),
        in_specs=[
            pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i // rep, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i // rep, j, 0)),
            pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, sq), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, sq), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, skv, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, skv, d), q.dtype),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=INTERPRET,
    )(qf, kf, vf, dof, lsef, deltaf)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, skv, d),
            dv.reshape(b, h, skv, d))


# ---------------------------------------------------------------------------
# Chunk kernels: flash attention between a LOCAL q block and a VISITING K/V
# chunk whose global positions are runtime values (ring attention rotates
# chunks with lax.ppermute, so offsets are traced axis_index products, not
# Python ints). Causality is data-driven via position-vector inputs, the
# kernel emits (out, lse), and the backward supports an lse cotangent —
# the online cross-chunk combiner differentiates through both.
#
# These deliberately DUPLICATE the static-causal kernels above rather than
# generalize them: the static path's compile-time diagonal skip (upper
# bound on the kv loop) is worth ~2x on long causal self-attention and
# cannot survive runtime positions. Optimization levers landed in one pair
# (ones-column row-sum, scale folding — see PERF_STEP.json) must be
# mirrored in the other.


def _flash_chunk_fwd_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref,
                            o_ref, lse_ref, *, kv_seq_len: int, block_k: int,
                            sm_scale: float, causal: bool):
    from jax.experimental import pallas as pl

    q = q_ref[...]
    scale2 = sm_scale * LOG2E
    qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
    qpos = qpos_ref[0, :]                # [bq] i32, GLOBAL positions
    nkv = kv_seq_len // block_k

    def body(j, carry):
        o, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
        if causal:
            kpos = kpos_ref[0, pl.ds(j * block_k, block_k)]
            s = jnp.where(kpos[None, :] <= qpos[:, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        v1 = jnp.concatenate(
            [v, jnp.ones((v.shape[0], 1), v.dtype)], axis=1)
        ov = jnp.dot(p.astype(v.dtype), v1,
                     preferred_element_type=jnp.float32)
        d_ = v.shape[1]
        l_new = l * alpha + lax.slice(ov, (0, d_), (ov.shape[0], d_ + 1))[:, 0]
        o_new = o * alpha[:, None] + lax.slice(ov, (0, 0), (ov.shape[0], d_))
        return o_new, m_new, l_new

    d = q_ref.shape[-1]
    o0 = jnp.zeros((q.shape[0], d), jnp.float32)
    m0 = jnp.full((q.shape[0],), NEG_INF, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    # No static diagonal skip: chunk visibility depends on runtime offsets,
    # and visiting chunks are all-visible or all-masked except the one
    # diagonal chunk per ring sweep — a full pass wastes ~(1/2n) of work.
    o, m, l = lax.fori_loop(0, nkv, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype)
    lse_ref[0, :] = (m + jnp.log2(l)) * LN2


def _flash_chunk_bwd_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, do_ref,
                            lse_ref, delta_ref, glse_ref,
                            dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                            kv_seq_len: int, block_k: int, sm_scale: float,
                            causal: bool):
    """Fused dq/dk/dv for one chunk pair, with the lse-cotangent term:
    ds = p ∘ (dO·vᵀ − Δ + g_lse) — lse depends on s with dlse/ds = p, so
    a cotangent on lse adds a per-row bias inside the p product."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    nq = pl.num_programs(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[...]
    do = do_ref[...]
    lse2 = lse_ref[0, :] * LOG2E
    rowbias = glse_ref[0, :] - delta_ref[0, :]  # (g_lse − Δ) per row
    qpos = qpos_ref[0, :]
    nkv = kv_seq_len // block_k
    scale2 = sm_scale * LOG2E
    qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
    q_sc = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)

    def body(j, dq):
        kslc = pl.ds(j * block_k, block_k)
        k = k_ref[kslc, :]
        v = v_ref[kslc, :]
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
        if causal:
            kpos = kpos_ref[0, kslc]
            s = jnp.where(kpos[None, :] <= qpos[:, None], s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dp = jnp.dot(do.astype(v.dtype), v.T,
                     preferred_element_type=jnp.float32)
        ds = p * (dp + rowbias[:, None])
        k_sc = (k.astype(jnp.float32) * sm_scale).astype(k.dtype)
        dv_acc[kslc, :] += jnp.dot(p.astype(do.dtype).T, do,
                                   preferred_element_type=jnp.float32)
        dk_acc[kslc, :] += jnp.dot(ds.astype(q.dtype).T, q_sc,
                                   preferred_element_type=jnp.float32)
        return dq + jnp.dot(ds.astype(k.dtype), k_sc,
                            preferred_element_type=jnp.float32)

    d = q_ref.shape[-1]
    dq = lax.fori_loop(0, nkv, body,
                       jnp.zeros((q.shape[0], d), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _flush():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _chunk_specs(b, h, hkv, sq, skv, d, block_q):
    from jax.experimental import pallas as pl

    rep = h // hkv
    return [
        pl.BlockSpec((None, 1, block_q), lambda i, j: (0, 0, j)),  # qpos
        pl.BlockSpec((None, 1, skv), lambda i, j: (0, 0, 0)),      # kpos
        pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # q
        pl.BlockSpec((None, skv, d), lambda i, j: (i // rep, 0, 0)),
        pl.BlockSpec((None, skv, d), lambda i, j: (i // rep, 0, 0)),
    ]


def _chunk_blocks(sq: int, skv: int, block_q: int, block_k: int):
    """POWER-OF-TWO block sizes that DIVIDE the chunk — ring shards can be
    any S/N, a floor-divided grid would silently drop the tail, and Mosaic
    tiling needs 8-aligned blocks (so an unaligned length must fail loudly
    here, not with an opaque TPU compile error)."""

    def pick(n: int, cap: int) -> int:
        b = min(cap, 1 << (n.bit_length() - 1))  # largest pow2 <= n
        while b > 8 and n % b:
            b //= 2
        return b

    block_q = pick(sq, block_q)
    block_k = pick(skv, block_k)
    if block_q < 8 or block_k < 8 or sq % block_q or skv % block_k:
        raise ValueError(
            f"flash_attention_chunk needs seq lengths with a power-of-two "
            f"block divisor >= 8 (got sq={sq}, skv={skv}); pad the ring "
            f"shard length or use impl='einsum'")
    return block_q, block_k


def _flash_chunk_fwd_pallas(q, k, v, qpos, kpos, causal, sm_scale,
                            block_q: int | None = None,
                            block_k: int | None = None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q, block_k = flash_blocks(block_q, block_k)
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    block_q, block_k = _chunk_blocks(sq, skv, block_q, block_k)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)
    qposf = qpos.astype(jnp.int32).reshape(1, 1, sq)
    kposf = kpos.astype(jnp.int32).reshape(1, 1, skv)

    out, lse = pl.pallas_call(
        functools.partial(_flash_chunk_fwd_kernel, kv_seq_len=skv,
                          block_k=block_k, sm_scale=sm_scale, causal=causal),
        grid=(b * h, sq // block_q),
        in_specs=_chunk_specs(b, h, hkv, sq, skv, d, block_q),
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            # f32 out: the cross-chunk log-sum-exp combiner accumulates in
            # f32 and casts ONCE at the end — a bf16 out here would add
            # one rounding per ring step (error growing with ring size).
            jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "arbitrary"),
            # Ring shards can be long (skv-sized K/V + f32 scratch):
            # generation-derived scoped-vmem ceiling (see
            # _flash_vmem_limit_bytes), compiler default on small-VMEM
            # or unknown chips.
            **({"vmem_limit_bytes": _flash_vmem_limit_bytes()}
               if _flash_vmem_limit_bytes() is not None else {}),
        ),
        interpret=INTERPRET,
    )(qposf, kposf, qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _flash_chunk_bwd_pallas(q, k, v, qpos, kpos, out, lse, g_out, g_lse,
                            causal, sm_scale,
                            block_q: int | None = None,
                            block_k: int | None = None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_q, block_k = flash_blocks(block_q, block_k)
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    block_q, block_k = _chunk_blocks(sq, skv, block_q, block_k)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)
    dof = g_out.reshape(b * h, sq, d).astype(q.dtype)
    lsef = _rows_3d(lse, b * h, sq)
    delta = (g_out.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    deltaf = _rows_3d(delta, b * h, sq)
    glsef = _rows_3d(g_lse.astype(jnp.float32), b * h, sq)
    qposf = qpos.astype(jnp.int32).reshape(1, 1, sq)
    kposf = kpos.astype(jnp.int32).reshape(1, 1, skv)

    row = pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_chunk_bwd_kernel, kv_seq_len=skv,
                          block_k=block_k, sm_scale=sm_scale, causal=causal),
        grid=(b * h, sq // block_q),
        in_specs=_chunk_specs(b, h, hkv, sq, skv, d, block_q) + [
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # dO
            row, row, row,                                   # lse, Δ, g_lse
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, skv, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, skv, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((skv, d), jnp.float32),
            pltpu.VMEM((skv, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "arbitrary"),
            # Ring shards can be long (skv-sized K/V + f32 scratch):
            # generation-derived scoped-vmem ceiling (see
            # _flash_vmem_limit_bytes), compiler default on small-VMEM
            # or unknown chips.
            **({"vmem_limit_bytes": _flash_vmem_limit_bytes()}
               if _flash_vmem_limit_bytes() is not None else {}),
        ),
        interpret=INTERPRET,
    )(qposf, kposf, qf, kf, vf, dof, lsef, deltaf, glsef)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, skv, d),
            dv.reshape(b, h, skv, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def flash_attention_chunk(q, k, v, qpos, kpos, causal: bool = True,
                          sm_scale: float | None = None):
    """(out, lse) for local q against one visiting K/V chunk, with
    GLOBAL positions supplied as arrays (qpos [Sq], kpos [Skv] — runtime
    values, e.g. ring-step offsets from lax.axis_index). lse is natural-log
    and differentiable, so cross-chunk online combiners (ring attention)
    backprop exactly. GQA-native like flash_attention."""
    return _chunk_fwd(q, k, v, qpos, kpos, causal, sm_scale)[0]


def _chunk_fwd(q, k, v, qpos, kpos, causal, sm_scale):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, lse = _flash_chunk_fwd_pallas(q, k, v, qpos, kpos, causal, scale)
    return (out, lse), (q, k, v, qpos, kpos, out, lse)


def _chunk_bwd(causal, sm_scale, res, cts):
    q, k, v, qpos, kpos, out, lse = res
    g_out, g_lse = cts
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    h, hkv = q.shape[1], k.shape[1]
    dq, dk, dv = _flash_chunk_bwd_pallas(
        q, k, v, qpos, kpos, out, lse, g_out, g_lse, causal, scale)
    if hkv != h:  # GQA fold
        b, _, skv, d = dk.shape
        rep = h // hkv
        dk = dk.astype(jnp.float32).reshape(b, hkv, rep, skv, d).sum(2)
        dv = dv.astype(jnp.float32).reshape(b, hkv, rep, skv, d).sum(2)
    import numpy as _np

    # Integer position inputs carry float0 cotangents (jax's convention
    # for non-differentiable array args under custom_vjp).
    zq = _np.zeros(qpos.shape, dtype=jax.dtypes.float0)
    zk = _np.zeros(kpos.shape, dtype=jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            zq, zk)


flash_attention_chunk.defvjp(_chunk_fwd, _chunk_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: float | None = None, use_pallas: bool = True):
    """Flash attention: Pallas TPU kernels for forward AND backward
    (dq/dk/dv with p recomputed inside the kernel from the saved lse).

    Falls back to ``blockwise_attention`` off-TPU (or use_pallas=False).
    """
    return _flash_fwd(q, k, v, causal, sm_scale, use_pallas)[0]


def _flash_fwd(q, k, v, causal, sm_scale, use_pallas):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    on_tpu = jax.default_backend() == "tpu"
    if use_pallas and (on_tpu or INTERPRET):
        out, lse = _flash_fwd_pallas(q, k, v, causal, scale)
        out = out.astype(q.dtype)
        # Under jax.checkpoint, a policy that saves 'flash_resid' keeps these
        # residuals across the remat boundary so the backward pass does NOT
        # re-run the forward kernel (see models/llama.py _remat_wrap 'dots').
        out = checkpoint_name(out, "flash_resid")
        lse = checkpoint_name(lse, "flash_resid")
        return out, (q, k, v, out, lse)
    out = blockwise_attention(q, k, v, causal=causal, sm_scale=scale)
    return out, (q, k, v, None, None)


def _flash_bwd(causal, sm_scale, use_pallas, res, g):
    q, k, v, out, lse = res
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if lse is not None:
        h, hkv = q.shape[1], k.shape[1]
        if FUSED_BWD:
            # dk/dv come back already folded to kv heads (pack-group fold
            # inside the kernel, remainder inside the wrapper).
            dq, dk, dv = _flash_bwd_fused_pallas(q, k, v, out, lse, g,
                                                 causal, scale)
        else:
            dq, dk, dv = _flash_bwd_pallas(q, k, v, out, lse, g, causal,
                                           scale)
            if hkv != h:  # GQA: fold the repeated query-head groups back
                b, _, skv, d = dk.shape
                rep = h // hkv
                dk = dk.astype(jnp.float32).reshape(
                    b, hkv, rep, skv, d).sum(2)
                dv = dv.astype(jnp.float32).reshape(
                    b, hkv, rep, skv, d).sum(2)
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)
    # Off-TPU: recompute through the differentiable blockwise path.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal,
                                               sm_scale=sm_scale),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
