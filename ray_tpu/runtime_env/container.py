"""Container runtime_env: run workers inside an image.

Capability parity with the reference's ``image_uri`` runtime-env plugin
(reference: python/ray/_private/runtime_env/image_uri.py — the worker
command is wrapped in ``podman run`` with the host network, the session
dir mounted, and the worker env forwarded via ``-e``): a task or actor
declaring ``runtime_env={"image_uri": ...}`` gets a worker process whose
entire lifetime runs inside that container.

The wrapping happens at WORKER FORK time in the node daemon (the reference
wraps in the raylet's worker-pool startup for the same reason): an already
running Python process cannot move itself into an image, so container envs
brand their worker at birth and are only ever matched by exact env hash.

The container runner binary is ``podman`` by default and is injectable via
``RTPU_CONTAINER_RUNNER`` — tests point it at a stub that mimics the
``run`` CLI, so the command-construction and env-propagation contract is
exercised without a container daemon on the box.
"""

from __future__ import annotations

import json
import os
from typing import Any


def canonical_env_json(env: dict | None) -> str:
    """THE canonical serialized form of a runtime_env ("" when empty).

    Worker-brand matching in the node daemon compares these strings
    byte-for-byte across three producers (task scheduling keys, actor
    registration, container fork branding) — every producer must call this
    one function (reference: runtime-env hash in worker_pool.h plays the
    same role)."""
    if not env:
        return ""
    return json.dumps(env, sort_keys=True, default=str)


def container_spec(env: dict | None) -> dict | None:
    """Extract the container request from a runtime_env dict (or its JSON
    string form, which is what rides the lease protocol as env_hash)."""
    if not env:
        return None
    if isinstance(env, str):
        try:
            env = json.loads(env)
        except ValueError:
            return None
    if not isinstance(env, dict):
        return None
    uri = env.get("image_uri")
    if not uri:
        return None
    return {"image_uri": uri,
            "run_options": list(env.get("container_run_options") or ())}


def validate_container_fields(env: dict) -> None:
    uri = env.get("image_uri")
    if uri is not None and not isinstance(uri, str):
        raise TypeError("image_uri must be an image reference string")
    opts = env.get("container_run_options")
    if opts is not None and (
            not isinstance(opts, (list, tuple))
            or not all(isinstance(o, str) for o in opts)):
        raise TypeError("container_run_options must be a list of strings")


def runner_binary() -> str:
    return os.environ.get("RTPU_CONTAINER_RUNNER", "podman")


def wrap_worker_command(cmd: list[str], env: dict[str, str],
                        spec: dict[str, Any]) -> list[str]:
    """Build the containerized worker command.

    - host network/IPC: the worker must reach head/daemon ports and the
      node's shared-memory arena (reference wraps with --network=host).
    - the package root and temp dir are bind-mounted so the framework code
      and log/shm paths resolve identically inside the image.
    - the ENTIRE worker environment is forwarded with ``-e`` — that is the
      env-propagation contract (runtime_env env_vars, RTPU_* bootstrap
      addresses, PYTHONPATH all cross the boundary).
    """
    import ray_tpu
    from ray_tpu.utils.config import get_config

    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    temp_dir = get_config().temp_dir
    out = [runner_binary(), "run", "--rm",
           "--network=host", "--ipc=host", "--pid=host",
           "-v", f"{pkg_root}:{pkg_root}:ro",
           "-v", f"{temp_dir}:{temp_dir}"]
    for k, v in sorted(env.items()):
        out += ["-e", f"{k}={v}"]
    out += list(spec.get("run_options") or ())
    out.append(spec["image_uri"])
    # The host interpreter's absolute path (a venv, typically) does not
    # exist inside the image: run the IMAGE's python3. The framework code
    # itself arrives via the pkg_root bind-mount + forwarded PYTHONPATH
    # (reference expects the image to carry a compatible runtime the same
    # way).
    if cmd and os.path.basename(cmd[0]).startswith("python"):
        cmd = ["python3"] + list(cmd[1:])
    out += list(cmd)
    return out
