"""Shared AST symbol model for the rtlint rules.

One parse per module; a per-class walk collects everything the race (R1)
and lock-order (R2) checkers need — attribute mutations/reads with the
set of locks held at each site, the intra-class call graph, inferred
thread entry points (threading.Thread targets, executor submissions,
RPC-handler registrations, ``call_soon_threadsafe`` callbacks), and the
with-statement lock-acquisition edges. R3–R5 do their own lighter passes
over the same parsed trees.

Execution-context model: every (method, nested-scope) site is assigned a
set of *contexts* — ``init`` (``__init__``), ``loop`` (async bodies, RPC
handlers, loop callbacks: one event-loop thread), ``thread:<name>`` (a
dedicated ``threading.Thread`` target), ``pool`` (executor submissions),
or ``caller`` (everything else: whatever thread calls the public API).
Contexts propagate through ``self.method()`` calls to a fixpoint. An
attribute touched from two distinct non-``init`` contexts is *shared*;
an unlocked mutation of a shared attribute is the R1 race signal.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

# Method names that mutate their receiver container in place.
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort", "reverse", "put", "put_nowait",
})

# Names that construct a threading-level lock (module "threading" or
# bare, via `from threading import Lock`).
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})
_LOCKISH_FRAGMENTS = ("lock", "mutex", "_cv", "cond")

# Constructors whose instances are internally synchronized: mutating
# calls on attributes bound to these are not race material.
_THREADSAFE_CTORS = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "Event",
})


def _name_is_lockish(name: str) -> bool:
    low = name.lower()
    return any(f in low for f in _LOCKISH_FRAGMENTS)


@dataclass
class Site:
    """One attribute access: where, what, and the locks held there."""

    attr: str
    line: int
    kind: str  # assign | augassign | mutcall | subscript | delete | read
    locks: frozenset[str]
    scope: str | None = None  # nested-function name, None = method body
    flag_literal: bool = False  # assignment of a bare constant literal


@dataclass
class MethodInfo:
    name: str
    node: ast.AST
    is_async: bool
    lineno: int
    self_calls: set[str] = field(default_factory=set)
    mutations: list[Site] = field(default_factory=list)
    reads: list[Site] = field(default_factory=list)
    # (outer_lock, inner_lock, line) acquisition-order edges.
    lock_edges: list[tuple[str, str, int]] = field(default_factory=list)
    # (line, held-threading-locks) at each `await` expression.
    awaits: list[tuple[int, frozenset[str]]] = field(default_factory=list)
    guard_lock: str | None = None  # @guarded_by("<lock>") method form
    contexts: set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    module: "ModuleInfo"
    methods: dict[str, MethodInfo] = field(default_factory=dict)
    locks: set[str] = field(default_factory=set)  # self-attr lock names
    guarded: dict[str, str] = field(default_factory=dict)  # attr -> lock
    safe: set[str] = field(default_factory=set)  # thread-safe containers
    loop_confined: bool = False  # @loop_confined: one event-loop thread
    # (method, nested-scope-name) -> context label for inferred entries.
    entries: dict[tuple[str, str | None], str] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    path: str
    relpath: str
    tree: ast.Module
    source: str
    classes: list[ClassInfo] = field(default_factory=list)
    functions: list[MethodInfo] = field(default_factory=list)  # top-level
    module_locks: set[str] = field(default_factory=set)


def parse_module(path: str, relpath: str, source: str) -> ModuleInfo | None:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    mod = ModuleInfo(path=path, relpath=relpath, tree=tree, source=source)
    for node in tree.body:
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    mod.module_locks.add(t.id)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            mod.classes.append(_build_class(node, mod))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions.append(_build_method(node, None, mod))
    for cls in mod.classes:
        _assign_contexts(cls)
    return mod


def _is_threadsafe_ctor(expr: ast.AST) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    fn = expr.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return name in _THREADSAFE_CTORS


def _is_lock_ctor(expr: ast.AST) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    fn = expr.func
    if isinstance(fn, ast.Name):
        return fn.id in _LOCK_CTORS
    if isinstance(fn, ast.Attribute):
        return (fn.attr in _LOCK_CTORS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "threading")
    return False


def _guarded_by_args(deco: ast.AST) -> tuple[str, list[str]] | None:
    """Parse a ``@guarded_by("lock", *attrs)`` decorator call."""
    if not isinstance(deco, ast.Call):
        return None
    fn = deco.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if name != "guarded_by" or not deco.args:
        return None
    vals = []
    for a in deco.args:
        if not (isinstance(a, ast.Constant) and isinstance(a.value, str)):
            return None
        vals.append(a.value)
    return vals[0], vals[1:]


def _build_class(node: ast.ClassDef, mod: ModuleInfo) -> ClassInfo:
    cls = ClassInfo(name=node.name, node=node, module=mod)
    method_guards: dict[str, str] = {}
    for deco in node.decorator_list:
        dname = deco.id if isinstance(deco, ast.Name) else (
            deco.attr if isinstance(deco, ast.Attribute) else None)
        if dname == "loop_confined":
            cls.loop_confined = True
        parsed = _guarded_by_args(deco)
        if parsed:
            lock, attrs = parsed
            for a in attrs:
                cls.guarded[a] = lock
    # First pass: find declared locks (self.X = threading.Lock() anywhere)
    # and thread-safe containers (queue.Queue / threading.Event — their
    # mutating calls are internally synchronized).
    for item in ast.walk(node):
        if isinstance(item, ast.Assign):
            targets, value = item.targets, item.value
        elif isinstance(item, ast.AnnAssign) and item.value is not None:
            targets, value = [item.target], item.value
        else:
            continue
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                if _is_lock_ctor(value):
                    cls.locks.add(t.attr)
                elif _is_threadsafe_ctor(value):
                    cls.safe.add(t.attr)
    cls.locks.update(cls.guarded.values())
    # Second pass: per-method walk.
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in item.decorator_list:
            parsed = _guarded_by_args(deco)
            if parsed and not parsed[1]:
                method_guards[item.name] = parsed[0]
        info = _build_method(item, cls, mod)
        info.guard_lock = method_guards.get(item.name)
        if info.guard_lock:
            # Body runs with the declared lock held: rebase every site.
            held = frozenset({f"self.{info.guard_lock}"})
            for site in info.mutations + info.reads:
                site.locks = site.locks | held
            info.awaits = [(ln, lk | held) for ln, lk in info.awaits]
        cls.methods[item.name] = info
    _find_entries(cls)
    return cls


class _FnWalker(ast.NodeVisitor):
    """Walks one function body tracking held locks, attribute sites,
    self-calls, lock-order edges, and awaits. Nested function bodies are
    walked too (fresh lock stack — they run later, possibly elsewhere)
    with their sites tagged by the nested scope name so entry inference
    can place e.g. a ``threading.Thread(target=pump)`` closure in its own
    context."""

    def __init__(self, info: MethodInfo, cls: ClassInfo | None,
                 mod: ModuleInfo):
        self.info = info
        self.cls = cls
        self.mod = mod
        self.locks: list[str] = []  # sync (threading) locks, inner last
        self.async_locks: list[str] = []
        self.scope: str | None = None

    # -- lock identity ---------------------------------------------------
    def _lock_name(self, expr: ast.AST) -> str | None:
        """Canonical identity of a with-item if it acquires a lock."""
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                known = self.cls is not None and expr.attr in self.cls.locks
                if known or _name_is_lockish(expr.attr):
                    return f"self.{expr.attr}"
                return None
            if _name_is_lockish(expr.attr):
                try:
                    return ast.unparse(expr)
                except Exception:
                    return expr.attr
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.mod.module_locks or _name_is_lockish(expr.id):
                return f"{_mod_base(self.mod)}:{expr.id}"
            return None
        return None

    def _held(self) -> frozenset[str]:
        return frozenset(self.locks) | frozenset(self.async_locks)

    # -- with ------------------------------------------------------------
    def visit_With(self, node: ast.With):
        self._with(node, is_async=False)

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self._with(node, is_async=True)

    def _with(self, node, is_async: bool):
        acquired: list[tuple[str, bool]] = []
        for item in node.items:
            self.visit(item.context_expr)
            name = self._lock_name(item.context_expr)
            if name is None:
                continue
            for outer in self.locks + self.async_locks:
                if outer != name:
                    self.info.lock_edges.append((outer, name, node.lineno))
            (self.async_locks if is_async else self.locks).append(name)
            acquired.append((name, is_async))
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for name, was_async in reversed(acquired):
            (self.async_locks if was_async else self.locks).remove(name)

    # -- attribute sites -------------------------------------------------
    def _self_attr(self, expr: ast.AST) -> str | None:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr
        return None

    def _mutate(self, attr: str, line: int, kind: str,
                flag_literal: bool = False):
        self.info.mutations.append(Site(
            attr=attr, line=line, kind=kind, locks=self._held(),
            scope=self.scope, flag_literal=flag_literal))

    def visit_Assign(self, node: ast.Assign):
        reads_self = {self._self_attr(n) for n in ast.walk(node.value)
                      if self._self_attr(n)}
        for t in node.targets:
            attr = self._self_attr(t)
            if attr is not None:
                is_rmw = attr in reads_self
                is_flag = (isinstance(node.value, ast.Constant)
                           and not is_rmw)
                self._mutate(attr, node.lineno,
                             "augassign" if is_rmw else "assign",
                             flag_literal=is_flag)
                continue
            if isinstance(t, ast.Subscript):
                attr = self._self_attr(t.value)
                if attr is not None:
                    self._mutate(attr, node.lineno, "subscript")
                    self.visit(t.slice)
                    continue
            self.visit(t)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        attr = self._self_attr(node.target)
        if attr is not None:
            self._mutate(attr, node.lineno, "augassign")
        elif isinstance(node.target, ast.Subscript):
            sub = self._self_attr(node.target.value)
            if sub is not None:
                self._mutate(sub, node.lineno, "subscript")
            self.visit(node.target.slice)
        else:
            self.visit(node.target)
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            attr = self._self_attr(t)
            if attr is None and isinstance(t, ast.Subscript):
                attr = self._self_attr(t.value)
            if attr is not None:
                self._mutate(attr, node.lineno, "delete")
            else:
                self.visit(t)

    def visit_Attribute(self, node: ast.Attribute):
        attr = self._self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self.info.reads.append(Site(
                attr=attr, line=node.lineno, kind="read",
                locks=self._held(), scope=self.scope))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv_attr = self._self_attr(fn.value)
            if recv_attr is not None and fn.attr in MUTATOR_METHODS:
                self._mutate(recv_attr, node.lineno, "mutcall")
            if (isinstance(fn.value, ast.Name) and fn.value.id == "self"):
                self.info.self_calls.add(fn.attr)
        self.generic_visit(node)

    def visit_Await(self, node: ast.Await):
        self.info.awaits.append((node.lineno, frozenset(self.locks)))
        self.generic_visit(node)

    # -- nested scopes ---------------------------------------------------
    def _nested(self, node, name: str):
        outer_scope, outer_locks, outer_async = (
            self.scope, self.locks, self.async_locks)
        self.scope = name if outer_scope is None else f"{outer_scope}.{name}"
        self.locks, self.async_locks = [], []
        for stmt in node.body:
            self.visit(stmt)
        self.scope, self.locks, self.async_locks = (
            outer_scope, outer_locks, outer_async)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._nested(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._nested(node, node.name)

    def visit_Lambda(self, node: ast.Lambda):
        prev, self.scope = self.scope, (self.scope or "<lambda>")
        self.visit(node.body)
        self.scope = prev


def _build_method(node, cls: ClassInfo | None, mod: ModuleInfo) -> MethodInfo:
    info = MethodInfo(name=node.name, node=node,
                      is_async=isinstance(node, ast.AsyncFunctionDef),
                      lineno=node.lineno)
    walker = _FnWalker(info, cls, mod)
    for stmt in node.body:
        walker.visit(stmt)
    return info


def _callback_target(arg: ast.AST) -> tuple[str | None, str | None]:
    """(self-method-name, local-function-name) a callable argument names."""
    if (isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name)
            and arg.value.id == "self"):
        return arg.attr, None
    if isinstance(arg, ast.Name):
        return None, arg.id
    if isinstance(arg, ast.Lambda):
        for sub in ast.walk(arg.body):
            if (isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                return sub.attr, None
    return None, None


def _find_entries(cls: ClassInfo) -> None:
    """Infer thread entry points from spawn/registration calls anywhere in
    the class body (reference: the review checklist this rule mechanizes —
    reaper/flusher/watchdog loops are threading.Thread targets, RPC
    handlers run on the event loop, call_soon_threadsafe callbacks too)."""
    for mname, meth in cls.methods.items():
        if meth.is_async:
            cls.entries.setdefault((mname, None), "loop")
        for node in ast.walk(meth.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if fname == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        self_m, local_f = _callback_target(kw.value)
                        if self_m:
                            cls.entries[(self_m, None)] = f"thread:{self_m}"
                        elif local_f:
                            cls.entries[(mname, local_f)] = \
                                f"thread:{local_f}"
            elif fname == "call_soon_threadsafe" and node.args:
                self_m, local_f = _callback_target(node.args[0])
                if self_m:
                    cls.entries.setdefault((self_m, None), "loop")
                elif local_f:
                    cls.entries.setdefault((mname, local_f), "loop")
            elif fname == "submit" and node.args:
                self_m, local_f = _callback_target(node.args[0])
                if self_m:
                    cls.entries.setdefault((self_m, None), "pool")
                elif local_f:
                    cls.entries.setdefault((mname, local_f), "pool")
            elif fname in ("register", "register_raw", "handler"):
                # rpc.register("name", self._handler): handler runs on the
                # event-loop thread (async handlers are caught by is_async
                # already; register_raw handlers are sync loop-side).
                for arg in node.args[1:]:
                    self_m, _ = _callback_target(arg)
                    if self_m:
                        cls.entries.setdefault((self_m, None), "loop")


def _assign_contexts(cls: ClassInfo) -> None:
    """Base context per method, then propagate through self-calls to a
    fixpoint so a helper called from a reaper thread inherits the reaper's
    context."""
    called_in_class: set[str] = set()
    for meth in cls.methods.values():
        called_in_class |= meth.self_calls
    for mname, meth in cls.methods.items():
        if mname == "__init__":
            meth.contexts = {"init"}
        elif (mname, None) in cls.entries:
            meth.contexts = {cls.entries[(mname, None)]}
        elif meth.is_async:
            meth.contexts = {"loop"}
        elif cls.loop_confined:
            # @loop_confined: public sync methods are loop-side too (their
            # callers are async handlers elsewhere); only explicit thread
            # entries above escape the loop context.
            meth.contexts = {"loop"}
        elif mname.startswith("_") and not mname.startswith("__") \
                and mname in called_in_class:
            # Private helper with in-class callers: it runs wherever its
            # callers run — let propagation fill the contexts in instead
            # of presuming an external caller thread (the Head/daemon
            # classes live entirely on the event loop; stamping "caller"
            # on every _helper would fabricate cross-thread sharing).
            meth.contexts = set()
        else:
            meth.contexts = {"caller"}
    changed = True
    while changed:
        changed = False
        for meth in cls.methods.values():
            for callee in meth.self_calls:
                target = cls.methods.get(callee)
                if target is None or callee == "__init__":
                    continue
                add = meth.contexts - target.contexts
                if add:
                    target.contexts |= add
                    changed = True


def site_contexts(cls: ClassInfo, meth: MethodInfo, site: Site) -> set[str]:
    """Contexts a given site executes under (nested-scope aware)."""
    if site.scope is not None:
        scope_head = site.scope.split(".", 1)[0]
        label = cls.entries.get((meth.name, scope_head))
        if label is not None:
            return {label}
    return set(meth.contexts)


def _mod_base(mod: ModuleInfo) -> str:
    rel = mod.relpath.replace("\\", "/")
    return rel[:-3] if rel.endswith(".py") else rel
