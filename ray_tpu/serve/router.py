"""Router: assigns requests to replicas (power-of-two-choices).

Capability parity with the reference's router (reference:
python/ray/serve/_private/router.py:510 Router.assign_request :1028 →
request_router/pow_2_router.py:27 PowerOfTwoChoicesRequestRouter
.choose_replicas :52 — sample two replicas, pick the one with the smaller
queue; requests queue router-side when all replicas are saturated), plus
the request-resilience layer (ray_tpu/serve/resilience.py):

- queue waits are bounded by the request's absolute deadline;
- admission control sheds with :class:`Overloaded` once
  ``max_queued_requests`` callers are parked (bounded queues, not
  unbounded latency);
- the choose loop never picks a draining replica, a replica the caller
  already tried (retry exclusion), or one whose circuit breaker is open;
- per-replica breakers track consecutive failures and latency outliers
  from the completion watcher, blacklist sick replicas with half-open
  recovery probes, and nudge the controller's health check on open.

KV-block-aware prefix routing (reference: serve prefix-aware routing
policy + vLLM prefix caching): replicas publish the chain hashes of the
prompt prefixes their engines hold (serve/prefix.py, piggybacked on the
long-poll snapshot); a request carrying ``prefix_hashes`` is scored by
matched prefix length and lands on the best-matched replica while its
load stays within the balance delta — a shared-prefix burst hits the
replica already holding the KV blocks instead of scattering pow-2.
Entries age out (TTL) and dead/draining replicas are dropped from the map
on every snapshot, so the router never hint-routes into a drain.

Hot path: the router is sized for 10k+ routing decisions/sec on one
process — metrics are pre-bound series (no per-call tag merging), replica
actor handles are cached per replica id, completion watching is ONE
reaper thread over all in-flight refs (a thread per request was ~100 µs
of create/teardown plus a parked stack each), and tracing spans are
skipped entirely when tracing is disabled.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import ray_tpu
from ray_tpu.devtools.annotations import guarded_by
from ray_tpu.serve.config import ReplicaInfo
from ray_tpu.serve.prefix import match_len
from ray_tpu.serve.resilience import (
    DEADLINE_KEY,
    CircuitBreaker,
    DeadlineExceeded,
    Overloaded,
    ResilienceSettings,
    shed_metrics,
)
from ray_tpu.util import tracing

_router_metrics = None
_router_metrics_lock = threading.Lock()


def _get_router_metrics():
    """Process-wide router metrics: admission wait, parked-caller depth,
    request count, and the resilience counters (shed/expired/retry/hedge/
    breaker) per deployment (reference: serve's
    ray_serve_num_router_requests / queued gauges). Lock-guarded creation:
    two racing first-requests must not register two metric objects and
    strand increments on the one the exporter can't see."""
    global _router_metrics
    with _router_metrics_lock:
        if _router_metrics is not None:
            return _router_metrics
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _router_metrics = {
            "queue_wait": Histogram(
                "serve_router_queue_wait_s",
                "time a request waited in the router for a replica slot",
                tag_keys=("deployment",)),
            "queue_depth": Gauge(
                "serve_router_queue_depth",
                "callers currently parked waiting for replica capacity",
                tag_keys=("deployment",)),
            "requests": Counter(
                "serve_router_requests_total",
                "requests assigned to replicas", tag_keys=("deployment",)),
            "prefix_hits": Counter(
                "serve_router_prefix_hits_total",
                "requests routed by prefix-cache match",
                tag_keys=("deployment",)),
            "retries": Counter(
                "serve_retries_total",
                "assignment retries after replica failure/rejection",
                tag_keys=("deployment",)),
            "hedges": Counter(
                "serve_hedges_total",
                "tail-hedge duplicate attempts launched",
                tag_keys=("deployment",)),
            "breaker_transitions": Counter(
                "serve_breaker_transitions_total",
                "circuit breaker open transitions",
                tag_keys=("deployment", "replica")),
            "breaker_open": Gauge(
                "serve_breaker_open_replicas",
                "replicas currently blacklisted by the circuit breaker",
                tag_keys=("deployment",)),
        }
    return _router_metrics


@guarded_by("_cv", "_pending", "_obs_backlog")
class _CompletionReaper:
    """One thread watching EVERY in-flight unary ref of a router: releases
    the replica slot the moment a reply lands and hands outcome
    observation (a possibly-blocking local fetch in cluster mode) to a
    small pool. Replaces a watcher thread per request — at router hot-path
    rates, thread create/teardown alone was most of the per-request
    cost."""

    # Outcome observations queued behind the pool beyond this are settled
    # NEUTRAL instead (probe slot returned, no breaker signal): in cluster
    # mode one observation can block seconds on a result fetch, and an
    # unbounded backlog would defer breaker feedback minutes behind
    # completions — bounded-late health signal beats unbounded-late.
    OBS_BACKLOG_MAX = 256

    def __init__(self, router: "Router"):
        self._router = router
        self._cv = threading.Condition()
        self._pending: dict = {}  # ref -> (rid, t_submit, is_probe)
        self._stopped = False
        self._obs_backlog = 0  # guarded by _cv
        # Observation pool: outcome gets are usually instant (actor
        # replies land in the caller's store) but a cluster-mode fetch can
        # block — it must never stall slot release for other requests.
        self._observe = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="serve-reap")
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serve-reaper-{router._deployment}")
        self._thread.start()

    def add(self, ref, rid: str, t_submit: float, is_probe: bool) -> None:
        with self._cv:
            self._pending[ref] = (rid, t_submit, is_probe)
            self._cv.notify()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()
        self._observe.shutdown(wait=False)

    def _loop(self) -> None:
        from ray_tpu.core.worker import global_worker

        router = self._router
        born_runtime = global_worker.runtime
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                refs = list(self._pending)
            if global_worker.runtime is not born_runtime:
                return  # our runtime is gone (LongPollClient discipline)
            try:
                # First-completion wake (event-driven in both runtimes),
                # then a zero-timeout sweep to drain everything already
                # ready in one pass. The timeout bounds the blind spot for
                # refs ADDED mid-wait (they're absent from this snapshot):
                # their observed latency — a breaker outlier input — is
                # overstated by at most one cycle, so keep it short.
                ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.05,
                                        fetch_local=False)
                if ready and len(refs) > 1:
                    ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                            timeout=0, fetch_local=False)
            except Exception:
                if self._stopped or \
                        global_worker.runtime is not born_runtime:
                    return
                # One poisoned ref must not wedge the SHARED reaper (the
                # per-request watchers it replaced failed one request per
                # bad ref): evict the refs wait() rejects individually,
                # releasing their slots with a neutral settle.
                self._evict_poisoned(refs)
                time.sleep(0.05)
                continue
            if not ready:
                continue
            now = time.perf_counter()
            done = []
            with self._cv:
                for ref in ready:
                    rec = self._pending.pop(ref, None)
                    if rec is not None:
                        done.append((ref, rec))
            for ref, (rid, t_submit, is_probe) in done:
                # Release first: _settle may block on a result fetch, and
                # parked callers must not wait out that fetch for a slot
                # the replica already freed.
                router._release(rid)
                with self._cv:
                    saturated = self._obs_backlog >= self.OBS_BACKLOG_MAX
                    if not saturated:
                        self._obs_backlog += 1
                if saturated:
                    router._settle_neutral(rid, is_probe)
                    continue
                try:
                    self._observe.submit(self._settle_one, ref, rid,
                                         now - t_submit, is_probe)
                except RuntimeError:  # shutting down
                    return

    def _settle_one(self, ref, rid: str, latency: float,
                    is_probe: bool) -> None:
        try:
            self._router._settle(ref, rid, latency, is_probe)
        finally:
            with self._cv:
                self._obs_backlog -= 1

    def _evict_poisoned(self, refs) -> None:
        """Drop every pending ref that ray_tpu.wait rejects on its own:
        its slot is released and settled neutral (no outcome will ever
        arrive for it), so the rest of the pending set keeps draining."""
        for ref in refs:
            try:
                ray_tpu.wait([ref], num_returns=1, timeout=0,
                             fetch_local=False)
            except Exception:
                with self._cv:
                    rec = self._pending.pop(ref, None)
                if rec is not None:
                    rid, _, is_probe = rec
                    self._router._release(rid)
                    self._router._settle_neutral(rid, is_probe)


class Router:
    def __init__(self, deployment_name: str,
                 get_replicas: Callable[[], list[ReplicaInfo]],
                 report_unhealthy: Callable[[str, str], None] | None = None):
        from ray_tpu.utils.config import get_config

        self._deployment = deployment_name
        # Span names interned once — these are stamped per request.
        self._trace_req_name = f"serve.request.{deployment_name}"
        self._trace_att_name = f"serve.attempt.{deployment_name}"
        self._get_replicas = get_replicas
        self._inflight: dict[str, int] = {}  # replica_id -> local in-flight
        self._lock = threading.Lock()
        self._not_saturated = threading.Condition(self._lock)
        self._rng = random.Random()
        self._waiting = 0  # callers parked for capacity (queue-depth gauge)
        # Set by _choose_locked (under _lock) when the chosen replica's
        # admission consumed a half-open breaker probe slot; read by
        # assign_request immediately after, per request.
        self._choice_was_probe = False
        self._report_unhealthy = report_unhealthy
        self.settings = ResilienceSettings()
        self._settings_adopted = False
        self.breaker = CircuitBreaker(self.settings.breaker,
                                      on_open=self._on_breaker_open)
        # Prefix-cache map: replica_id -> (frozenset of chain hashes,
        # receipt stamp). Rebuilt from every snapshot (dead/draining
        # replicas drop out immediately); entries older than the TTL are
        # ignored so a wedged control plane can't pin stale locality.
        self._prefix_map: dict[str, tuple[frozenset, float]] = {}
        cfg = get_config()
        self._prefix_ttl = float(
            getattr(cfg, "serve_prefix_map_ttl_s", 30.0))
        # Cached replica actor handles (get_actor is a name-table lookup —
        # an RPC in cluster mode — and handles are thread-safe now).
        self._actors: dict[str, object] = {}
        # Pre-bound metric series: the per-call tag-dict merge was a
        # measurable slice of the 10k-RPS budget.
        mtr = _get_router_metrics()
        smtr = shed_metrics()
        dep = {"deployment": deployment_name}
        self._m_queue_wait = mtr["queue_wait"].bound(dep)
        self._m_queue_depth = mtr["queue_depth"].bound(dep)
        self._m_requests = mtr["requests"].bound(dep)
        self._m_prefix_hits = mtr["prefix_hits"].bound(dep)
        self._m_retries = mtr["retries"].bound(dep)
        self._m_hedges = mtr["hedges"].bound(dep)
        self._m_breaker_open = mtr["breaker_open"].bound(dep)
        self._m_shed_router = smtr["shed"].bound(
            {**dep, "where": "router"})
        self._m_expired_router = smtr["expired"].bound(
            {**dep, "where": "router"})
        self._mtr = mtr
        self._reaper: _CompletionReaper | None = None
        self._reaper_lock = threading.Lock()

    # ------------------------------------------------------------ settings

    def _adopt_settings(self, replicas: list[ReplicaInfo]) -> None:
        """Adopt the deployment-level resilience settings riding the newest
        replica snapshot (cheap: dict identity check short-circuits)."""
        for r in replicas:
            s = getattr(r, "settings", None)
            if s is not None:
                if s is not getattr(self, "_last_settings_dict", None):
                    self._last_settings_dict = s
                    self.settings = ResilienceSettings.from_dict(s)
                    self.breaker.config = self.settings.breaker
                self._settings_adopted = True
                return

    def _on_breaker_open(self, replica_id: str, reason: str) -> None:
        try:
            self._mtr["breaker_transitions"].inc(
                tags={"deployment": self._deployment, "replica": replica_id})
            self._m_breaker_open.set(self.breaker.open_count())
        except Exception:
            pass
        # Feed the controller's health check: a breaker trip means THIS
        # router has stopped routing there, but only the controller can
        # probe-and-replace a genuinely sick replica for everyone.
        if self._report_unhealthy is not None:
            try:
                self._report_unhealthy(replica_id, reason)
            except Exception:
                pass

    def _get_reaper(self) -> _CompletionReaper:
        reaper = self._reaper
        if reaper is None:
            with self._reaper_lock:
                reaper = self._reaper
                if reaper is None:
                    reaper = self._reaper = _CompletionReaper(self)
        return reaper

    def close(self) -> None:
        """Stop background machinery (called by serve.shutdown via
        handle._reset_routers)."""
        with self._reaper_lock:
            if self._reaper is not None:
                self._reaper.stop()
                self._reaper = None

    # ---------------------------------------------------------- data plane

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       timeout: float | None = None, stream: bool = False,
                       route_hint: str | None = None,
                       deadline: float | None = None,
                       exclude: set[str] | frozenset[str] | None = None,
                       no_park: bool = False,
                       prefix_hashes: tuple | None = None,
                       trace_ctx: dict | None = None,
                       trace_attrs: dict | None = None):
        """Pick a replica, submit, and return ``(result, replica_id)``
        where result is the ObjectRef (or ``(gen, on_done)`` when
        streaming). One attempt — retry/hedge loops live in the handle,
        which excludes already-tried replicas here.

        Placement order: ``prefix_hashes`` (KV-block-aware — the replica
        with the longest matched cached prefix wins while its load stays
        within the balance delta), then ``route_hint`` (rendezvous-hash
        affinity with the same balance bound), then pow-2 on local
        in-flight counts. Both locality mechanisms yield to load
        balancing beyond HINT_BALANCE_DELTA — a deployment-wide shared
        prefix must not pin all traffic to one replica while siblings
        idle.

        The wait for a replica slot is bounded by ``deadline`` (absolute
        wall clock; defaults to now + the deployment's request_timeout_s,
        or the legacy ``timeout`` argument when given). While every
        eligible replica is saturated the caller parks on a Condition that
        is notified on request completion and on replica-set changes — no
        sleep-poll — but only ``settings.max_queued_requests`` callers may
        park: beyond that, :class:`Overloaded` sheds the request
        immediately (admission control, reference: serve's
        max_queued_requests handle option).

        ``trace_ctx`` (a tracing propagation dict) parents this attempt
        under the handle's request-root span; routing decisions that end
        the attempt (shed, expiry, replica vanished) are stamped onto the
        trace as zero-duration point spans, and ``trace_attrs`` (attempt
        number, hedge flag) land on the attempt span."""
        t_enter = time.time()
        if deadline is None:
            budget = timeout if timeout is not None \
                else self.settings.request_timeout_s
            deadline = t_enter + budget
        with self._lock:
            parked = False
            try:
                while True:
                    replicas = self._get_replicas()
                    if replicas and not self._settings_adopted:
                        self._adopt_settings(replicas)
                    if replicas and exclude and all(
                            r.replica_id in exclude or
                            getattr(r, "draining", False)
                            for r in replicas):
                        # Retry exclusion covers every published replica:
                        # nothing a wake can change for THIS call — fail
                        # fast so the handle surfaces the original error
                        # instead of a full-budget park that also occupies
                        # an admission slot (a 0.5s retry-after shed must
                        # not become a 30s stall on a 1-replica app).
                        self._trace_point(trace_ctx, "router.shed",
                                          reason="exhausted")
                        raise Overloaded(
                            f"{self._deployment!r}: every replica already "
                            f"tried by this request", retry_after_s=0.5,
                            where="router")
                    chosen = (self._choose_locked(replicas, route_hint,
                                                  exclude, prefix_hashes)
                              if replicas else None)
                    if chosen is not None:
                        is_probe = self._choice_was_probe
                        self._inflight[chosen.replica_id] = \
                            self._inflight.get(chosen.replica_id, 0) + 1
                        break
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        self._m_expired_router.inc()
                        self._trace_point(trace_ctx, "router.expired",
                                          waited_s=round(
                                              time.time() - t_enter, 6))
                        raise DeadlineExceeded(
                            f"no available replica for {self._deployment!r} "
                            f"within the request budget "
                            f"({deadline - t_enter:.1f}s)")
                    if not parked:
                        if no_park:
                            # Internal opportunistic assignment (hedging):
                            # take a free slot now or give up — a hedge
                            # that parks would add load exactly at
                            # saturation and block the caller's drive
                            # loop. Not counted as a shed: never
                            # user-visible.
                            raise Overloaded(
                                f"{self._deployment!r} has no free replica "
                                f"for an opportunistic assignment",
                                retry_after_s=0.0, where="router")
                        cap = self.settings.max_queued_requests
                        if cap >= 0 and self._waiting >= cap:
                            # Bounded router queue: shed instead of joining
                            # an unbounded wait (the client owns backoff).
                            self._m_shed_router.inc()
                            self._trace_point(trace_ctx, "router.shed",
                                              reason="queue_full")
                            raise Overloaded(
                                f"{self._deployment!r} router queue full "
                                f"({cap} waiting)",
                                retry_after_s=1.0, where="router")
                        parked = True
                        self._waiting += 1
                        self._m_queue_depth.set(self._waiting)
                    # Bounded wait: replica-set changes arrive via
                    # notify_replicas_changed(), completions via _release();
                    # the 0.5 s cap only covers lost-notify edge cases.
                    self._not_saturated.wait(timeout=min(remaining, 0.5))
            finally:
                if parked:
                    self._waiting -= 1
                    self._m_queue_depth.set(self._waiting)
        wait_s = time.time() - t_enter
        self._m_queue_wait.observe(
            wait_s, exemplar=trace_ctx.get("trace_id") if trace_ctx
            else None)
        self._m_requests.inc()

        # Propagate the budget: the replica drops the request if it expires
        # before execution starts (and exposes it to user code / batcher).
        # handle.remote builds a fresh kwargs dict per call, so the key is
        # written in place; retries/hedges sharing the dict skip the copy
        # (the deadline is constant for the request's lifetime).
        if kwargs.get(DEADLINE_KEY) != deadline:
            kwargs[DEADLINE_KEY] = deadline

        rid = chosen.replica_id
        try:
            handle = self._actors.get(rid)
            if handle is None:
                handle = ray_tpu.get_actor(chosen.actor_name,
                                           namespace="serve")
                self._actors[rid] = handle
        except Exception as e:
            # Replica vanished between the long-poll snapshot and submission:
            # give the slot back (a leaked increment would read as permanent
            # saturation), return any half-open probe slot, and count the
            # miss against the breaker. Surfaced as a NEVER-SENT actor death
            # (the request provably didn't reach any replica) carrying the
            # replica id, so the handle's retry loop can exclude it and
            # re-resolve onto a live sibling.
            from ray_tpu.core.exceptions import ActorDiedError

            self._release(rid)
            if is_probe:
                self.breaker.cancel_probe(rid)
            self.breaker.record_failure(rid)
            self._trace_point(trace_ctx, "router.never_sent", replica=rid)
            raise ActorDiedError(
                rid, f"replica {rid} vanished before submit: {e!r}",
                never_sent=True) from e
        # Client span around submission: inject() rides the TaskSpec, so
        # the replica's execution shows up as a child of this span — one
        # trace across processes. When the handle propagated a request-root
        # context (trace_ctx), this becomes the per-ATTEMPT span (retries
        # and hedges each get their own, numbered via trace_attrs) nested
        # under serve.request.<dep>; standalone callers keep the old
        # request-named root. Skipped entirely (nullcontext) when tracing
        # is off: span setup was measurable at router hot-path rates.
        traced = tracing.tracing_enabled() or trace_ctx is not None
        # Unsampled FIRST attempts propagate the context without
        # materializing the attempt span: it would cover only the submit
        # call and duplicate the root's attributes, and at production RPS
        # the skipped Span + id mint + tail-ring insert is the single
        # biggest per-request tracing cost. Retries, hedges, breaker
        # probes, and head-sampled traces keep their numbered attempt
        # spans; the handle stamps the chosen replica onto the root.
        if (trace_ctx is not None and not is_probe
                and (not trace_attrs or trace_attrs.get("attempt", 1) == 1)
                and "sampled" in trace_ctx
                and tracing._coerce_sampled(trace_ctx["sampled"]) is False):
            span = tracing.propagate_only(trace_ctx)
        elif traced:
            name = (self._trace_att_name if trace_ctx is not None
                    else self._trace_req_name)
            attrs = {"method": method_name, "replica": rid}
            if trace_attrs:
                attrs.update(trace_attrs)
            if is_probe:
                attrs["breaker_probe"] = True
            if wait_s > 0.001:
                attrs["queue_wait_s"] = round(wait_s, 6)
            if stream:
                attrs["stream"] = "true"
            span = tracing.span(name, kind="client", attributes=attrs,
                                ctx=trace_ctx)
        else:
            span = contextlib.nullcontext()
        if stream:
            try:
                with span:
                    gen = handle.handle_request_streaming.options(
                        num_returns="streaming").remote(
                            method_name, args, kwargs)
            except Exception:
                self._submit_failed(rid, is_probe)
                raise

            done = threading.Event()

            def on_stream_done():
                # In-flight until the consumer exhausts/abandons the stream
                # (keeps max_ongoing_requests honest for long-lived SSE).
                if not done.is_set():
                    done.set()
                    self._release(rid)
                    if is_probe:
                        # Settle this request's half-open probe slot if no
                        # outcome was recorded (abandoned stream): no-op
                        # once record_success/failure already moved the
                        # breaker out of half-open.
                        self.breaker.cancel_probe(rid)

            return (gen, on_stream_done), rid
        try:
            with span:
                ref = handle.handle_request.remote(method_name, args, kwargs)
        except Exception:
            self._submit_failed(rid, is_probe)
            raise

        self._get_reaper().add(ref, rid, time.perf_counter(), is_probe)
        return ref, rid

    def _trace_point(self, trace_ctx: dict | None, name: str,
                     **attrs) -> None:
        """Zero-duration span stamping a routing decision (shed, expiry,
        vanished replica) onto the request's trace. No-op without a
        propagated context — untraced hot-path requests pay nothing."""
        if trace_ctx is None:
            return
        now = time.time()
        tracing.record_span(name, now, now, attributes=attrs,
                            ctx=trace_ctx)

    def _submit_failed(self, rid: str, is_probe: bool) -> None:
        self._actors.pop(rid, None)  # handle may be bound to a corpse
        self._release(rid)
        if is_probe:
            self.breaker.cancel_probe(rid)
        self.breaker.record_failure(rid)

    def _settle(self, ref, rid: str, latency: float, is_probe: bool) -> None:
        """Breaker bookkeeping for one completed unary call (runs on the
        reaper's observation pool; the slot was already released)."""
        outcome = None
        try:
            outcome = self._observe_outcome(ref)
        finally:
            if outcome is True:
                self.breaker.record_success(rid, latency)
            elif outcome is False:
                self.breaker.record_failure(rid)
            elif is_probe:
                # Neutral (shed/expired/unknown): no health signal
                # either way — but THIS request's half-open probe
                # slot must be returned so the breaker doesn't wedge
                # half-open (and a shed must NOT close the breaker
                # on a still-sick replica). Only the probe request
                # settles the slot: a non-probe neutral completion
                # canceling it would over-admit probes.
                self.breaker.cancel_probe(rid)
            self._refresh_breaker_gauge()

    def _settle_neutral(self, rid: str, is_probe: bool) -> None:
        """Observation-backlog overflow path: no outcome signal either
        way, but a probe's half-open slot must still be returned."""
        if is_probe:
            self.breaker.cancel_probe(rid)
            self._refresh_breaker_gauge()

    def _observe_outcome(self, ref) -> bool | None:
        """Ternary outcome of the completed call: True = healthy answer,
        False = failure (infra or application), None = neutral — sheds and
        deadline expiries say nothing about replica health in EITHER
        direction (counting a fast shed as success would close a half-open
        breaker on a still-overloaded replica and seed its cleared latency
        window with bogus samples). The result is already local (actor
        replies land in the caller's store), so this get is cheap."""
        from ray_tpu.serve import resilience

        try:
            # Bounded get: in cluster mode the reply may still be a local
            # fetch away after wait(fetch_local=False); a timeout here is
            # "unknown" (neutral).
            ray_tpu.get(ref, timeout=5.0)
            return True
        except (resilience.Overloaded, resilience.DeadlineExceeded):
            return None
        except Exception as e:  # noqa: BLE001 - classify
            kind = resilience.classify(e)
            if kind in ("overloaded_replica", "overloaded_router",
                        "expired"):
                return None
            return False

    def _refresh_breaker_gauge(self) -> None:
        try:
            self._m_breaker_open.set(self.breaker.open_count())
        except Exception:
            pass

    # ----------------------------------------------------------- feedback

    def record_stream_outcome(self, replica_id: str, ok: bool,
                              latency_s: float | None = None) -> None:
        """Breaker feedback for streaming calls: the generator wrapper
        reports first-chunk success (with TTFT as the latency sample) or a
        mid-stream failure (the completion watcher can't see stream
        errors — they surface in the consumer)."""
        if ok:
            self.breaker.record_success(replica_id, latency_s or 0.0)
        else:
            self.breaker.record_failure(replica_id)
        self._refresh_breaker_gauge()

    def count_retry(self) -> None:
        try:
            self._m_retries.inc()
        except Exception:
            pass

    def count_hedge(self) -> None:
        try:
            self._m_hedges.inc()
        except Exception:
            pass

    def _release(self, replica_id: str) -> None:
        with self._lock:
            self._inflight[replica_id] -= 1
            self._not_saturated.notify_all()

    def notify_replicas_changed(self,
                                replicas: list[ReplicaInfo] | None = None
                                ) -> None:
        """Wake parked assign loops after a replica-set update (called from
        the long-poll callback in DeploymentHandle). With the new snapshot
        in hand, also adopt its settings, garbage-collect breaker state and
        cached actor handles for replicas the controller no longer
        publishes, and rebuild the prefix-cache map (dead and draining
        replicas drop out of it HERE — the choose loop must never
        prefix-route into a drain)."""
        if replicas is not None:
            self._adopt_settings(replicas)
            live = [r.replica_id for r in replicas]
            self.breaker.forget(live)
            live_set = set(live)
            for rid in list(self._actors):
                if rid not in live_set:
                    self._actors.pop(rid, None)
            now = time.monotonic()
            pm: dict[str, tuple[frozenset, float]] = {}
            for r in replicas:
                blocks = getattr(r, "prefix_blocks", None)
                if blocks and not getattr(r, "draining", False):
                    pm[r.replica_id] = (frozenset(blocks), now)
            self._prefix_map = pm
        with self._lock:
            self._not_saturated.notify_all()

    def touch_prefix_map(self) -> None:
        """Re-stamp every prefix-map entry (called after each successful
        long-poll round, updates or not). The controller republishes only
        on CHANGE, so a healthy deployment with a stable warm cache sends
        no snapshots — without this the TTL would expire exactly the
        steady-state publication it exists to protect, silently shutting
        prefix routing off after serve_prefix_map_ttl_s. The TTL then
        only trips when polling itself stops: a wedged/dead controller."""
        pm = self._prefix_map
        if pm:
            now = time.monotonic()
            self._prefix_map = {rid: (held, now)
                                for rid, (held, _) in pm.items()}

    # How far above the least-loaded replica a hint-preferred replica may
    # be before load balancing overrides cache locality.
    HINT_BALANCE_DELTA = 2

    def _eligible_locked(self, r: ReplicaInfo,
                         exclude) -> bool:
        if getattr(r, "draining", False):
            return False
        if exclude and r.replica_id in exclude:
            return False
        return not self.breaker.is_open(r.replica_id)

    def _choose_prefix_locked(self, replicas: list[ReplicaInfo],
                              prefix_hashes) -> ReplicaInfo | None:
        """Longest-matched-prefix choice over the (already eligible)
        candidate set. Ties on match length break to the least-loaded
        replica; a best-matched replica more than HINT_BALANCE_DELTA above
        the least-loaded one is skipped (locality yields to balance).
        Returns None when nothing matches — the caller falls through to
        rendezvous-hint and pow-2 choice."""
        pm = self._prefix_map
        if not pm:
            return None
        now = time.monotonic()
        ttl = self._prefix_ttl
        inflight = self._inflight
        min_load = min(inflight.get(r.replica_id, 0) for r in replicas)
        best = None
        best_m = 0
        best_load = 0
        for r in replicas:
            ent = pm.get(r.replica_id)
            if ent is None:
                continue
            held, stamp = ent
            if ttl > 0 and now - stamp > ttl:
                continue  # aged out: stale publication, ignore
            m = match_len(prefix_hashes, held)
            if m <= 0:
                continue
            load = inflight.get(r.replica_id, 0)
            if load >= r.max_ongoing_requests:
                continue
            if load - min_load > self.HINT_BALANCE_DELTA:
                continue
            if m > best_m or (m == best_m and load < best_load):
                best, best_m, best_load = r, m, load
        if best is None:
            return None
        ok, probe = self.breaker.allow_ex(best.replica_id)
        if not ok:
            return None  # half-open, probe budget spent: balance instead
        self._choice_was_probe = probe
        try:
            self._m_prefix_hits.inc()
        except Exception:
            pass
        return best

    def _choose_locked(self, replicas: list[ReplicaInfo],
                       route_hint: str | None = None,
                       exclude: set[str] | frozenset[str] | None = None,
                       prefix_hashes: tuple | None = None
                       ) -> ReplicaInfo | None:
        """Choice over the ELIGIBLE set: never a draining replica, never
        one the caller already tried, never one whose breaker is open
        (half-open admission happens below, via breaker.allow_ex).
        Prefix-match first, then rendezvous hint, then pow-2."""
        self._choice_was_probe = False
        replicas = [r for r in replicas if self._eligible_locked(r, exclude)]
        if not replicas:
            return None
        if prefix_hashes:
            got = self._choose_prefix_locked(replicas, prefix_hashes)
            if got is not None:
                return got
        if route_hint is not None:
            # Rendezvous hashing: every router maps the same hint to the
            # same replica without coordination — but only while the hinted
            # replica's load stays within HINT_BALANCE_DELTA of the
            # least-loaded replica. Beyond that, locality yields to pow-2
            # balancing (a deployment-wide shared prefix must not pin all
            # traffic to one replica while siblings idle).
            import zlib

            min_load = min(self._inflight.get(r.replica_id, 0)
                           for r in replicas)
            ranked = sorted(
                replicas,
                key=lambda r: zlib.crc32(
                    f"{route_hint}:{r.replica_id}".encode()),
            )
            for r in ranked:
                load = self._inflight.get(r.replica_id, 0)
                if load >= r.max_ongoing_requests:
                    continue
                if load - min_load <= self.HINT_BALANCE_DELTA:
                    ok, probe = self.breaker.allow_ex(r.replica_id)
                    if ok:
                        self._choice_was_probe = probe
                        return r
                    continue  # half-open and out of probe slots
                break  # hinted replica overloaded — balance instead
        candidates = (self._rng.sample(replicas, 2)
                      if len(replicas) >= 2 else list(replicas))
        best, best_load = None, None
        for r in candidates:
            load = self._inflight.get(r.replica_id, 0)
            if load >= r.max_ongoing_requests:
                continue
            if best_load is None or load < best_load:
                best, best_load = r, load
        if best is None:
            return None
        ok, probe = self.breaker.allow_ex(best.replica_id)
        if not ok:
            # Half-open with its probe budget spent: try the other pow-2
            # candidate; otherwise report saturation (the caller parks and
            # the breaker re-admits on the next wake).
            for r in candidates:
                if r.replica_id == best.replica_id:
                    continue
                load = self._inflight.get(r.replica_id, 0)
                if load < r.max_ongoing_requests:
                    ok2, probe2 = self.breaker.allow_ex(r.replica_id)
                    if ok2:
                        self._choice_was_probe = probe2
                        return r
            return None
        self._choice_was_probe = probe
        return best

    def metrics(self) -> dict[str, int]:
        with self._lock:
            return dict(self._inflight)
