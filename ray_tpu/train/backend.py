"""Framework backends: per-worker process-group bring-up.

Capability parity with the reference's Backend ABC + JAX backend (reference:
python/ray/train/backend.py Backend ABC; v2/jax/config.py:112 _JaxBackend —
worker 0 becomes the coordinator, every worker runs
jax.distributed.initialize(coordinator, num_procs, proc_id) :84, multi-slice
env via ray.util.tpu.get_tpu_coordinator_env_vars :147).
"""

from __future__ import annotations

import socket
from dataclasses import dataclass


@dataclass
class BackendConfig:
    backend_name: str = "noop"


class Backend:
    def on_start(self, worker_group, coordinator_addr: str | None) -> None:
        pass

    def on_shutdown(self, worker_group) -> None:
        pass


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _init_jax_distributed(coordinator_addr: str, num_processes: int,
                          process_id: int) -> None:
    """Runs ON each worker. Idempotent per process."""
    import jax

    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_addr,
        num_processes=num_processes,
        process_id=process_id,
    )


def _set_slice_env(env: dict) -> dict:
    """Runs ON each worker: install the multi-slice coordinator env and
    report it back for verification."""
    import os

    os.environ.update(env)
    return {k: os.environ.get(k) for k in env}


# Latency-hiding-scheduler / async-collective flags for multi-slice training:
# let the compiler overlap DCN collectives (the deferred gradient sync a
# grad_accum step leaves at the microbatch boundary) with the next
# microbatch's compute. They ride LIBTPU_INIT_ARGS, which only libtpu reads —
# inert on CPU/GPU hosts, no unknown-flag errors.
_XLA_PERF_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
)


def _apply_xla_perf_flags() -> str:
    """Runs ON each worker, BEFORE jax/libtpu init. Appends the latency-
    hiding flags to LIBTPU_INIT_ARGS (idempotent; flags already present —
    e.g. user-pinned values — are left alone). Env-overridable:
    RTPU_TRAIN_XLA_PERF_FLAGS=0 disables, RTPU_TRAIN_XLA_PERF_FLAGS_EXTRA
    appends space-separated extra flags. Returns the resulting value for
    verification."""
    import os

    from ray_tpu.utils.config import get_config

    if not get_config().train_xla_perf_flags:
        return os.environ.get("LIBTPU_INIT_ARGS", "")
    current = os.environ.get("LIBTPU_INIT_ARGS", "")
    have = {f.split("=")[0] for f in current.split() if f}
    extra = os.environ.get("RTPU_TRAIN_XLA_PERF_FLAGS_EXTRA", "").split()
    # EXTRA wins over the defaults: a user re-specifying a built-in flag
    # (e.g. ...latency_hiding_scheduler=false) replaces it, not joins it.
    extra_names = {f.split("=")[0] for f in extra}
    defaults = [f for f in _XLA_PERF_FLAGS
                if f.split("=")[0] not in extra_names]
    add = [f for f in (*defaults, *extra)
           if f.split("=")[0] not in have]
    if add:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join(
            ([current] if current else []) + add)
    return os.environ.get("LIBTPU_INIT_ARGS", "")


@dataclass
class JaxBackendConfig(BackendConfig):
    """Bring up a jax.distributed world across the worker group.

    ``distributed=False`` (default for single-host tests) skips
    jax.distributed and leaves each worker with its local devices — gradient
    sync then goes through ray_tpu.collective's host backend instead.

    ``num_slices > 1`` marks a multi-slice (DCN) topology: each worker gets
    the MEGASCALE_* coordinator env for its slice BEFORE jax.distributed
    init (reference: v2/jax/config.py:147 injecting
    ray.util.tpu.get_tpu_coordinator_env_vars — slice_id = rank //
    workers_per_slice; libtpu reads these at first device init).
    """

    backend_name: str = "jax"
    distributed: bool = False
    num_slices: int = 1
    # Apply the latency-hiding-scheduler LIBTPU flags on every worker before
    # backend init (config train_xla_perf_flags gates it process-wide).
    xla_perf_flags: bool = True

    def make_backend(self) -> "JaxBackend":
        return JaxBackend(self)


class JaxBackend(Backend):
    def __init__(self, cfg: JaxBackendConfig):
        self.cfg = cfg
        self.slice_env_applied: list[dict] = []  # per-rank, for asserts
        self.libtpu_args_applied: list[str] = []  # per-rank, for asserts

    def on_start(self, worker_group, coordinator_addr: str | None) -> None:
        import ray_tpu

        n = len(worker_group.workers)
        if self.cfg.xla_perf_flags:
            # Must land before any jax/libtpu init on the worker (both the
            # distributed bring-up below and the user's train_fn import jax).
            self.libtpu_args_applied = ray_tpu.get([
                w.exec_fn.remote(_apply_xla_perf_flags)
                for w in worker_group.workers
            ], timeout=300)
        if self.cfg.num_slices > 1:
            from ray_tpu.util.tpu import get_tpu_coordinator_env_vars

            if n % self.cfg.num_slices != 0:
                raise ValueError(
                    f"{n} workers not divisible into "
                    f"{self.cfg.num_slices} slices")
            per_slice = n // self.cfg.num_slices
            self.slice_env_applied = ray_tpu.get([
                w.exec_fn.remote(
                    _set_slice_env,
                    get_tpu_coordinator_env_vars(
                        coordinator_addr or "127.0.0.1:0",
                        self.cfg.num_slices, rank // per_slice))
                for rank, w in enumerate(worker_group.workers)
            ], timeout=300)
        if not self.cfg.distributed:
            return
        # Every worker initializes against worker 0's coordinator address
        # (reference: v2/jax/config.py:84).
        ray_tpu.get([
            w.exec_fn.remote(_init_jax_distributed, coordinator_addr, n, rank)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=300)
