"""Distributed tracing: spans around every task/actor call, with context
propagated through task metadata.

Capability parity with the reference's tracing helper (reference:
python/ray/util/tracing/tracing_helper.py — _tracing_task_invocation wraps
submission, _inject_tracing_into_class wraps actor methods, _DictPropagator
:165 carries the context dict inside task metadata, enablement via
_enable_tracing :98): submission creates a client span whose context rides in
``TaskSpec.trace_ctx``; the executing worker opens a child span around the user
function. No OpenTelemetry dependency — spans land in an in-process buffer
exportable as dicts (same span fields an OTLP exporter would see) and into the
chrome timeline.

Request tracing at production RPS adds two sampling layers on top:

* **head sampling** — the serve ingress draws a per-request verdict
  (``sample_request(rate)``); the verdict rides the context dict as
  ``sampled`` and every downstream span inherits it, so one decision at
  the handle covers the router, replica, batcher, engine, and DAG hops.
* **tail sampling** — spans of UNsampled traces are not discarded: they
  land in a bounded per-trace tail ring and die quietly with it, unless
  the trace is retroactively *kept* (``mark_keep``) because it ended
  slow / shed / expired / errored / breaker-implicated. A keep promotes
  the ring's spans into the main buffer and enqueues the trace id for
  the telemetry flusher, which piggybacks it on ``report_telemetry``;
  the head gossips keeps back in the reply so every process holding
  fragments of that trace promotes them too — no new RPCs anywhere.

The master gate stays ``enable_tracing()``: with it off every helper is a
no-op and the hot paths keep their nullcontext fast path (the "compiled
off" arm of devbench/trace_bench.py).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import asdict, dataclass, field
from random import random as _rand  # per-request sampling draw


@dataclass(slots=True)
class Span:
    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    kind: str  # "client" | "worker" | "internal"
    start_ts: float
    end_ts: float = 0.0
    status: str = "OK"
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)

    def add_event(self, name: str, attributes: dict | None = None) -> None:
        """Timestamped point event on this span (routing decisions —
        shed, breaker skip, hedge fired — that have no duration)."""
        ev = {"name": name, "ts": time.time()}
        if attributes:
            ev.update(attributes)
        self.events.append(ev)


_enabled = False
_ctx = threading.local()  # .trace_id, .span_id, .sampled
_spans: deque[Span] = deque(maxlen=100_000)
_spans_total = 0  # monotone append count (flush cursor base)
_dropped_metered = 0  # drops already exported to the registry counter
_lock = threading.Lock()

# Tail-sampling state, all guarded by _lock. The ring maps
# trace_id -> (created_monotonic, [spans]) in insertion order so TTL and
# max-traces eviction both pop from the front.
_tail: OrderedDict[str, tuple[float, list[Span]]] = OrderedDict()
_tail_dropped = 0  # tail spans evicted unkept (visibility, not an error)
_kept_ids: set[str] = set()  # traces promoted (late spans go straight in)
_kept_order: deque[str] = deque()  # bounds _kept_ids FIFO
_KEPT_MAX = 4096
_keep_queue: deque = deque(maxlen=1024)  # {"trace_id","reason"} to flush
_tail_cfg: tuple[int, int, float] | None = None
_tail_scan_ts = 0.0  # last amortized TTL sweep (monotonic)

_drop_metrics = None
_drop_metrics_lock = threading.Lock()


def _get_drop_metrics():
    """Lazy: the module must stay importable without the registry."""
    global _drop_metrics
    with _drop_metrics_lock:
        if _drop_metrics is None:
            from ray_tpu.util.metrics import Counter

            _drop_metrics = {
                "dropped": Counter(
                    "tracing_spans_dropped",
                    "finished spans silently discarded by this process's "
                    "bounded span buffer (deque wraparound / clear) — "
                    "nonzero means the timeline has holes"),
            }
        return _drop_metrics


def dropped_spans() -> int:
    """Spans this process has discarded (wraparound + clear), cumulative."""
    with _lock:
        return _spans_total - len(_spans)


def enable_tracing() -> None:
    """Turn span recording on for this process (reference: _enable_tracing)."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


_idbuf = threading.local()


def _new_id(nbytes: int = 8) -> str:
    # One urandom syscall per ~KB of ids, not per id: ids stay
    # crypto-random (fork-safe unique across worker processes — a seeded
    # PRNG would collide after fork) at a fraction of the hot-path cost.
    buf = getattr(_idbuf, "buf", b"")
    if len(buf) < nbytes:
        buf = os.urandom(1024)
    _idbuf.buf = buf[nbytes:]
    return buf[:nbytes].hex()


def current_context() -> tuple[str, str] | None:
    tid = getattr(_ctx, "trace_id", None)
    sid = getattr(_ctx, "span_id", None)
    return (tid, sid) if tid else None


def current_trace_id() -> str | None:
    """The thread's live trace id, if any — the exemplar hook: metric
    observes attach it so histogram buckets link back to traces."""
    return getattr(_ctx, "trace_id", None)


def current_sampled() -> bool | None:
    """The thread's head-sampling verdict: True (main buffer), False
    (tail ring, promotable), None (no verdict — legacy task tracing)."""
    return getattr(_ctx, "sampled", None)


def inject() -> dict | None:
    """Context dict to ship inside a TaskSpec (reference: _DictPropagator.inject)."""
    if not _enabled:
        return None
    cur = current_context()
    if cur is None:
        # Root: submitting from untraced code still starts a trace.
        return {"trace_id": _new_id(16), "parent_span_id": None}
    out = {"trace_id": cur[0], "parent_span_id": cur[1]}
    samp = getattr(_ctx, "sampled", None)
    if samp is not None:
        out["sampled"] = samp
    return out


def adopt(ctx: dict | None) -> None:
    """Set this thread's context from a propagated dict. DAG actor loops
    use this at each hop: the channel read adopts the frame's context so
    the loop's downstream write (its own inject()) chains the NEXT hop
    onto the same trace. ``adopt(None)`` clears the slots — an untraced
    frame must not inherit the previous frame's trace."""
    if ctx is None:
        _ctx.trace_id = None
        _ctx.span_id = None
        _ctx.sampled = None
        return
    _ctx.trace_id = ctx.get("trace_id")
    _ctx.span_id = ctx.get("parent_span_id")
    _ctx.sampled = _coerce_sampled(ctx.get("sampled")) \
        if "sampled" in ctx else None


def _coerce_sampled(value) -> bool | None:
    # Wire contexts may round-trip through stringified metadata.
    if value is None:
        return None
    if isinstance(value, str):
        return value not in ("False", "false", "0", "")
    return bool(value)


def _tail_limits() -> tuple[int, int, float]:
    """(max traces, max spans per trace, ttl seconds) — read from Config
    once, with import-safe fallbacks matching the Config defaults."""
    global _tail_cfg
    if _tail_cfg is None:
        try:
            from ray_tpu.utils.config import get_config

            cfg = get_config()
            _tail_cfg = (int(cfg.trace_tail_traces),
                         int(cfg.trace_tail_spans_per_trace),
                         float(cfg.trace_tail_ttl_s))
        except Exception:  # noqa: BLE001 - config not importable yet
            _tail_cfg = (512, 64, 30.0)
    return _tail_cfg


def configure_tail(max_traces: int | None = None,
                   max_spans_per_trace: int | None = None,
                   ttl_s: float | None = None) -> None:
    """Override the tail-ring bounds for this process (tests, benches)."""
    global _tail_cfg
    cur = _tail_limits()
    _tail_cfg = (max_traces if max_traces is not None else cur[0],
                 max_spans_per_trace if max_spans_per_trace is not None
                 else cur[1],
                 ttl_s if ttl_s is not None else cur[2])


def _append_locked(s: Span) -> None:
    global _spans_total
    _spans.append(s)
    _spans_total += 1


def _tail_put_locked(s: Span) -> None:
    global _tail_dropped, _tail_scan_ts
    max_traces, max_spans, ttl_s = _tail_limits()
    now = time.monotonic()
    # Lazy TTL expiry from the front (insertion order == age order),
    # amortized: at production RPS the put runs thousands of times per
    # second and the scan only needs sub-TTL granularity.
    if now - _tail_scan_ts >= min(0.5, ttl_s / 8.0):
        _tail_scan_ts = now
        while _tail:
            tid, (created, ring) = next(iter(_tail.items()))
            if now - created < ttl_s:
                break
            _tail.popitem(last=False)
            _tail_dropped += len(ring)
    entry = _tail.get(s.trace_id)
    if entry is None:
        while len(_tail) >= max(1, max_traces):
            _, (_, ring) = _tail.popitem(last=False)
            _tail_dropped += len(ring)
        _tail[s.trace_id] = (now, [s])
        return
    ring = entry[1]
    if len(ring) >= max_spans:
        _tail_dropped += 1
        return
    ring.append(s)


def _finish(s: Span, sampled: bool | None) -> None:
    """Route a finished span: unsampled traces go to the tail ring unless
    already kept; everything else lands in the main buffer."""
    with _lock:
        if sampled is False and s.trace_id not in _kept_ids:
            _tail_put_locked(s)
        else:
            _append_locked(s)


def sample_request(rate: float) -> bool:
    """Head-sampling draw for one ingress request. rate >= 1 keeps all,
    <= 0 sends everything to the tail ring (pure tail sampling)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return _rand() < rate


def mark_keep(trace_id: str, reason: str = "") -> None:
    """Retroactively keep a tail-sampled trace: promote its ringed spans
    into the main buffer and enqueue the id for the telemetry flusher so
    every other process holding fragments promotes them too."""
    if not trace_id:
        return
    with _lock:
        _keep_locked(trace_id)
        _keep_queue.append({"trace_id": trace_id, "reason": reason})


def apply_keeps(trace_ids) -> None:
    """Promote head-gossiped keeps locally WITHOUT re-queueing them (the
    head already has them; re-queueing would echo forever)."""
    if not trace_ids:
        return
    with _lock:
        for tid in trace_ids:
            _keep_locked(tid)


def _keep_locked(trace_id: str) -> None:
    if trace_id in _kept_ids:
        entry = _tail.pop(trace_id, None)
        if entry is not None:  # late spans ringed after the first keep
            for s in entry[1]:
                _append_locked(s)
        return
    _kept_ids.add(trace_id)
    _kept_order.append(trace_id)
    while len(_kept_order) > _KEPT_MAX:
        _kept_ids.discard(_kept_order.popleft())
    entry = _tail.pop(trace_id, None)
    if entry is not None:
        for s in entry[1]:
            _append_locked(s)


def drain_keeps() -> list[dict]:
    """Locally-decided keeps awaiting shipment (telemetry flusher)."""
    with _lock:
        if not _keep_queue:
            return []
        out = list(_keep_queue)
        _keep_queue.clear()
        return out


def requeue_keeps(keeps: list[dict]) -> None:
    """Put drained keeps back after a failed flush (head outage): the
    trace stays promotable once the head returns — partial, not lost."""
    with _lock:
        for k in keeps:
            _keep_queue.append(k)


def tail_stats() -> dict:
    with _lock:
        return {"traces": len(_tail),
                "spans": sum(len(r) for _, r in _tail.values()),
                "dropped": _tail_dropped,
                "kept": len(_kept_ids),
                "keep_queue": len(_keep_queue)}


class LatencyWindow:
    """Rolling p99 over the last ``size`` request latencies — the "ended
    slow" tail-keep verdict. O(1) observe; the quantile is refreshed every
    ``refresh`` observes from a sorted copy (a 512-sample sort every 64
    requests is noise next to one RPC)."""

    def __init__(self, size: int = 512, min_samples: int = 64,
                 quantile: float = 0.99, refresh: int = 64):
        self._vals: deque[float] = deque(maxlen=size)
        self._min = min_samples
        self._q = quantile
        self._refresh = refresh
        self._since = 0
        self._p: float | None = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> bool:
        """Record one latency; True iff it exceeds the current p99 AND
        the window has enough history to mean anything."""
        with self._lock:
            self._vals.append(value)
            self._since += 1
            if self._p is None or self._since >= self._refresh:
                if len(self._vals) >= self._min:
                    ordered = sorted(self._vals)
                    idx = min(len(ordered) - 1,
                              int(self._q * len(ordered)))
                    self._p = ordered[idx]
                self._since = 0
            return self._p is not None and value > self._p

    def p99(self) -> float | None:
        with self._lock:
            return self._p


def start_span(name: str, kind: str = "internal",
               attributes: dict | None = None,
               ctx: dict | None = None,
               sampled: bool | None = None) -> Span:
    """Manually-managed span for lifecycles that cross threads (a serve
    request is born on the caller thread and settles on whichever thread
    drives ``result()``): pair with :func:`finish_span`. Does NOT touch
    the thread-local context — use :func:`ctx_for` to parent children."""
    if ctx is not None:
        trace_id = ctx.get("trace_id") or _new_id(16)
        parent_id = ctx.get("parent_span_id")
    else:
        cur = current_context()
        trace_id = cur[0] if cur else _new_id(16)
        parent_id = cur[1] if cur else None
    # The span takes ownership of ``attributes`` (every caller builds a
    # fresh per-call dict) — a defensive copy here ran once per request.
    return Span(trace_id=trace_id, span_id=_new_id(), parent_id=parent_id,
                name=name, kind=kind, start_ts=time.time(),
                attributes=attributes if attributes is not None else {})


def finish_span(s: Span, sampled: bool | None = None,
                status: str | None = None) -> None:
    if s.end_ts == 0.0:
        s.end_ts = time.time()
    if status is not None:
        s.status = status
    _finish(s, sampled)


def ctx_for(s: Span, sampled: bool | None = None) -> dict:
    """Propagation context dict parenting children under ``s``."""
    out = {"trace_id": s.trace_id, "parent_span_id": s.span_id}
    if sampled is not None:
        out["sampled"] = sampled
    return out


class _NullSpanCM:
    """Shared no-op context manager for the tracing-off fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, etype, exc, tb):
        return False


_NULL_SPAN = _NullSpanCM()


class _SpanCM:
    """Hand-rolled context manager for :func:`span` — the request hot
    path enters/exits several of these per call, and the generator
    machinery behind ``@contextlib.contextmanager`` is measurable there."""

    __slots__ = ("_span", "_sampled", "_prev")

    def __init__(self, s: Span, sampled: bool | None):
        self._span = s
        self._sampled = sampled

    def __enter__(self) -> Span:
        s = self._span
        # Save the raw thread-local slots (not current_context(), which
        # collapses partial state to None): executor pool threads are
        # reused across unrelated work, and an inexact restore leaks this
        # span's ids into the next task on the same thread.
        self._prev = (getattr(_ctx, "trace_id", None),
                      getattr(_ctx, "span_id", None),
                      getattr(_ctx, "sampled", None))
        _ctx.trace_id, _ctx.span_id = s.trace_id, s.span_id
        _ctx.sampled = self._sampled
        return s

    def __exit__(self, etype, exc, tb):
        s = self._span
        if etype is not None:
            s.status = f"ERROR: {etype.__name__}"
            s.attributes["exception.type"] = etype.__name__
            s.attributes["exception.message"] = str(exc)
        s.end_ts = time.time()
        _ctx.trace_id, _ctx.span_id, _ctx.sampled = self._prev
        _finish(s, self._sampled)
        return False


class _CtxOnlyCM:
    """Propagation without materialization: pushes a propagated context
    onto the thread-local slots (so ``inject()`` inside the block chains
    children correctly) but records NO span. The unsampled happy path
    uses this where a span would carry no information beyond its parent —
    the tail ring keeps one fewer span per request and the hot path skips
    a Span + id mint + buffer insert."""

    __slots__ = ("_ctxd", "_prev")

    def __init__(self, ctxd: dict):
        self._ctxd = ctxd

    def __enter__(self):
        self._prev = (getattr(_ctx, "trace_id", None),
                      getattr(_ctx, "span_id", None),
                      getattr(_ctx, "sampled", None))
        c = self._ctxd
        _ctx.trace_id = c.get("trace_id")
        _ctx.span_id = c.get("parent_span_id")
        _ctx.sampled = _coerce_sampled(c.get("sampled")) \
            if "sampled" in c else None
        return None

    def __exit__(self, etype, exc, tb):
        _ctx.trace_id, _ctx.span_id, _ctx.sampled = self._prev
        return False


def propagate_only(ctx: dict) -> _CtxOnlyCM:
    """Context manager that propagates ``ctx`` without recording a span."""
    return _CtxOnlyCM(ctx)


def span(name: str, kind: str = "internal", attributes: dict | None = None,
         ctx: dict | None = None):
    """Record a span; nests under the thread's current span unless ``ctx``
    (a propagated context) is given."""
    if not _enabled and ctx is None:
        return _NULL_SPAN
    if ctx is not None:
        trace_id = ctx.get("trace_id") or _new_id(16)
        parent_id = ctx.get("parent_span_id")
        sampled = _coerce_sampled(ctx.get("sampled")) \
            if "sampled" in ctx else getattr(_ctx, "sampled", None)
    else:
        cur = current_context()
        trace_id = cur[0] if cur else _new_id(16)
        parent_id = cur[1] if cur else None
        sampled = getattr(_ctx, "sampled", None)
    s = Span(
        trace_id=trace_id, span_id=_new_id(), parent_id=parent_id, name=name,
        kind=kind, start_ts=time.time(),
        attributes=attributes if attributes is not None else {},
    )
    return _SpanCM(s, sampled)


def record_span(name: str, start_ts: float, end_ts: float,
                kind: str = "internal",
                attributes: dict | None = None,
                ctx: dict | None = None) -> Span | None:
    """Append an already-finished span (the goodput ledger lane: phase
    intervals are classified after the fact, so there is no ``with``
    block to wrap). ``ctx`` parents it under a propagated context — the
    engine's scheduler thread and the batcher's loop use this to stamp
    per-request phases onto the request's own trace from a thread that
    never entered it. No-op when tracing is off and no context rode in.
    Returns the recorded span (callers that chain — the DAG hop read —
    parent follow-up work under it)."""
    if not _enabled and ctx is None:
        return None
    if ctx is not None:
        trace_id = ctx.get("trace_id") or _new_id(16)
        parent_id = ctx.get("parent_span_id")
        sampled = _coerce_sampled(ctx.get("sampled")) \
            if "sampled" in ctx else None
    else:
        trace_id, parent_id, sampled = _new_id(16), None, None
    s = Span(
        trace_id=trace_id, span_id=_new_id(), parent_id=parent_id, name=name,
        kind=kind, start_ts=float(start_ts), end_ts=float(end_ts),
        attributes=attributes if attributes is not None else {},
    )
    _finish(s, sampled)
    return s


def task_span(name: str, trace_ctx: dict | None, kind: str = "worker",
              attributes: dict | None = None):
    """Worker-side span around task execution; no-op unless the submitter
    propagated a context or this process has tracing on."""
    if trace_ctx is None and not _enabled:
        return _NULL_SPAN
    return span(name, kind=kind, attributes=attributes, ctx=trace_ctx)


def spans() -> list[Span]:
    with _lock:
        return list(_spans)


def export() -> list[dict]:
    return [asdict(s) for s in spans()]


def _wire_events(events: list) -> list[dict]:
    return [{k: (v if isinstance(v, (int, float)) else str(v))
             for k, v in ev.items()} for ev in events]


def flush_new(cursor: int, limit: int = 2000) -> tuple[list[dict], int]:
    """Finished spans recorded since ``cursor`` as wire dicts, plus the new
    cursor. The telemetry flusher ships these to the head WITHOUT removing
    them locally (the in-process buffer stays useful for the flight recorder
    and local /api/traces); attribute values are stringified so the batch
    always survives msgpack. Bounded per call like the event flush
    (reference: task_event_buffer.h kMaxNumTaskEventsToFlush)."""
    import itertools

    global _dropped_metered
    with _lock:
        # _spans_total is monotone across clear() (cleared spans count as
        # dropped), so a caller's cursor can never exceed it and there is
        # no window where post-clear spans get skipped.
        dropped = _spans_total - len(_spans)
        start = max(0, min(cursor, _spans_total) - dropped)
        batch = list(itertools.islice(_spans, start, start + limit))
        new_cursor = dropped + start + len(batch)
        new_drops, _dropped_metered = \
            dropped - _dropped_metered, max(dropped, _dropped_metered)
    if new_drops > 0:
        # Surfaced on the flush path (every process with a telemetry
        # flusher calls it) so /metrics shows span loss without adding a
        # counter inc to the hot span-record path.
        try:
            _get_drop_metrics()["dropped"].inc(new_drops)
        except Exception:  # noqa: BLE001 - visibility must not break flush
            pass
    out = [{
        "trace_id": s.trace_id, "span_id": s.span_id,
        "parent_id": s.parent_id, "name": s.name, "kind": s.kind,
        "start_ts": s.start_ts, "end_ts": s.end_ts, "status": s.status,
        "attributes": {k: str(v) for k, v in s.attributes.items()},
        "events": _wire_events(s.events),
    } for s in batch]
    return out, new_cursor


def clear() -> None:
    # _spans_total deliberately NOT reset: it is the monotone cursor base
    # for flush_new(), and cleared spans simply count as dropped.
    global _tail_dropped
    with _lock:
        _spans.clear()
        _tail.clear()
        _kept_ids.clear()
        _kept_order.clear()
        _keep_queue.clear()
        _tail_dropped = 0


# -- exporters --------------------------------------------------------------


def export_otlp() -> dict:
    """Spans in OTLP/JSON shape (resourceSpans → scopeSpans → spans) — the
    wire format OTel collectors ingest (reference: tracing_helper.py exports
    through opentelemetry SDK; here the structure is emitted directly so no
    SDK dependency is needed)."""
    def ns(ts: float) -> str:
        return str(int(ts * 1e9))

    otel_spans = []
    for s in spans():
        otel_spans.append({
            "traceId": s.trace_id,
            "spanId": s.span_id,
            "parentSpanId": s.parent_id or "",
            "name": s.name,
            "kind": {"client": 3, "worker": 2,
                     "internal": 1}.get(s.kind, 1),
            "startTimeUnixNano": ns(s.start_ts),
            "endTimeUnixNano": ns(s.end_ts),
            "status": {"code": 1 if s.status == "OK" else 2,
                       "message": s.status},
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in s.attributes.items()
            ],
            "events": [
                {"name": str(ev.get("name", "")),
                 "timeUnixNano": ns(float(ev.get("ts", 0.0))),
                 "attributes": [
                     {"key": k, "value": {"stringValue": str(v)}}
                     for k, v in ev.items() if k not in ("name", "ts")
                 ]}
                for ev in s.events
            ],
        })
    return {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "ray_tpu"}}]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.tracing"},
                "spans": otel_spans,
            }],
        }]
    }


def save_otlp(path: str) -> str:
    import json

    with open(path, "w") as f:
        json.dump(export_otlp(), f)
    return path


@contextlib.contextmanager
def profile(logdir: str):
    """XLA profiler capture around a block: writes an xplane trace viewable
    in TensorBoard/XProf alongside a framework span (reference: SURVEY §5 —
    hooks to dump jax.profiler traces into the same timeline channel)."""
    import jax

    with span("jax.profile", attributes={"logdir": logdir}):
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
