"""Head-packed flash-attention experiments on the real chip.

Hypothesis (VERDICT r4 task 1): the fwd kernel is VPU-bound at head_dim 64
— per-block online-softmax VPU work on 512x512 f32 score blocks rivals the
K=64 MXU time. Packing P q-heads that share one GQA kv-head into a single
kernel invocation (row-concat into [P*block_q, d] tiles) makes every matmul
and VPU op P x larger (amortizing per-op overheads and keeping the MXU fed)
without losing the causal block-skip granularity.

Variants:
  v0: current flash_attention fwd (baseline)
  bq1024 / bk1024 / bq1024bk1024: block-size sweep on the baseline kernel
  pack2 / pack4: P q-heads row-packed per invocation

Run:  python devbench/prof_flash_pack.py [--check]
"""
import argparse
import functools
import math
import time

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import (
    LOG2E, LN2, NEG_INF, _flash_fwd_pallas, attention_reference)

B, S, H, KV, HD = 4, 2048, 32, 8, 64
L1, L2 = 8, 56


def timed_slope_chain(make_step, carry0, reps=5):
    def run_for(length):
        @jax.jit
        def run(c):
            def body(c, _):
                return make_step(c), None
            c, _ = lax.scan(body, c, None, length=length)
            return jax.tree_util.tree_reduce(
                lambda a, x: a + x.ravel()[0].astype(jnp.float32), c, 0.0)
        return run

    r1, r2 = run_for(L1), run_for(L2)
    float(r1(carry0)); float(r2(carry0))
    slopes = []
    for _ in range(reps):
        t0 = time.perf_counter(); float(r1(carry0)); t1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(r2(carry0)); t2 = time.perf_counter() - t0
        slopes.append((t2 - t1) / (L2 - L1))
    slopes.sort()
    return slopes[len(slopes) // 2]


# --------------------------------------------------------------------------
# Packed forward kernel: P q-heads sharing one kv head per grid row.
# --------------------------------------------------------------------------

def _packed_fwd_epi_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                           kv_seq_len, block_k, sm_scale, causal, block_q,
                           pack):
    """Like _packed_fwd_kernel but the causal mask runs only on the partial
    diagonal blocks: a mask-free fori_loop over fully-visible kv blocks, then
    a statically-unrolled masked epilogue for the (at most ceil(bq/bk)+1)
    partial blocks."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[...]
    p_, bq, d = q.shape
    q2 = q.reshape(p_ * bq, d)
    scale2 = sm_scale * LOG2E
    qs = (q2.astype(jnp.float32) * scale2).astype(q2.dtype)
    nkv = kv_seq_len // block_k
    rows = p_ * bq
    row_iota = lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
    qpos = qi * bq + lax.rem(row_iota, bq)

    def make_body(masked):
        def body(j, carry):
            o, m, l = carry
            k = k_ref[pl.ds(j * block_k, block_k), :]
            v = v_ref[pl.ds(j * block_k, block_k), :]
            s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
            if masked:
                kpos = j * block_k + lax.broadcasted_iota(
                    jnp.int32, s.shape, 1)
                s = jnp.where(kpos <= qpos, s, NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))
            p = jnp.exp2(s - m_new[:, None])
            alpha = jnp.exp2(m - m_new)
            v1 = jnp.concatenate(
                [v, jnp.ones((v.shape[0], 1), v.dtype)], axis=1)
            ov = jnp.dot(p.astype(v.dtype), v1,
                         preferred_element_type=jnp.float32)
            l_new = l * alpha + lax.slice(ov, (0, d), (rows, d + 1))[:, 0]
            o_new = o * alpha[:, None] + lax.slice(ov, (0, 0), (rows, d))
            return o_new, m_new, l_new
        return body

    o0 = jnp.zeros((rows, d), jnp.float32)
    m0 = jnp.full((rows,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows,), jnp.float32)
    if not causal:
        o, m, l = lax.fori_loop(0, nkv, make_body(False), (o0, m0, l0))
    else:
        # kv block j is fully visible iff (j+1)*bk - 1 <= qi*bq (min qpos).
        full = lax.div(qi * bq, block_k)
        upper = jnp.minimum(lax.div((qi + 1) * bq + block_k - 1, block_k),
                            nkv)
        carry = lax.fori_loop(0, full, make_body(False), (o0, m0, l0))
        # Partial-diagonal epilogue: at most ceil(bq/bk)+? blocks; unroll a
        # static worst case of n_partial = upper-full <= ceil(bq/bk) blocks
        # guarded by pl.when-free select (masked body is idempotent for
        # fully-masked blocks? NO — run only real ones via fori_loop).
        carry = lax.fori_loop(full, upper, make_body(True), carry)
        o, m, l = carry
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype).reshape(p_, bq, d)
    lse_ref[...] = ((m + jnp.log2(l)) * LN2).reshape(p_, bq)


def _packed_fwd_inl_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                           kv_seq_len, block_k, sm_scale, causal, block_q,
                           pack):
    """block_q == block_k variant: exactly ONE partial (diagonal) kv block
    per q block, unrolled as straight-line code after a mask-free fori_loop
    — not a second loop (split loops pipeline worse, r4 + epi variant).
    The diagonal mask is the same local triangular pattern for every qi."""
    from jax.experimental import pallas as pl

    assert block_q == block_k
    qi = pl.program_id(1)
    q = q_ref[...]
    p_, bq, d = q.shape
    q2 = q.reshape(p_ * bq, d)
    scale2 = sm_scale * LOG2E
    qs = (q2.astype(jnp.float32) * scale2).astype(q2.dtype)
    nkv = kv_seq_len // block_k
    rows = p_ * bq

    def make_body(masked):
        def body(j, carry):
            o, m, l = carry
            k = k_ref[pl.ds(j * block_k, block_k), :]
            v = v_ref[pl.ds(j * block_k, block_k), :]
            s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
            if masked:
                # Diagonal block: local triangular mask, identical for all qi.
                lq = lax.rem(lax.broadcasted_iota(jnp.int32, s.shape, 0), bq)
                lk = lax.broadcasted_iota(jnp.int32, s.shape, 1)
                s = jnp.where(lk <= lq, s, NEG_INF)
            m_new = jnp.maximum(m, s.max(axis=-1))
            p = jnp.exp2(s - m_new[:, None])
            alpha = jnp.exp2(m - m_new)
            v1 = jnp.concatenate(
                [v, jnp.ones((v.shape[0], 1), v.dtype)], axis=1)
            ov = jnp.dot(p.astype(v.dtype), v1,
                         preferred_element_type=jnp.float32)
            l_new = l * alpha + lax.slice(ov, (0, d), (rows, d + 1))[:, 0]
            o_new = o * alpha[:, None] + lax.slice(ov, (0, 0), (rows, d))
            return o_new, m_new, l_new
        return body

    o0 = jnp.zeros((rows, d), jnp.float32)
    m0 = jnp.full((rows,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows,), jnp.float32)
    if not causal:
        o, m, l = lax.fori_loop(0, nkv, make_body(False), (o0, m0, l0))
    else:
        carry = lax.fori_loop(0, qi, make_body(False), (o0, m0, l0))
        o, m, l = make_body(True)(qi, carry)
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype).reshape(p_, bq, d)
    lse_ref[...] = ((m + jnp.log2(l)) * LN2).reshape(p_, bq)


def packed_fwd_inl(q, k, v, causal, sm_scale, pack=2, block_q=512):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    rep = h // hkv
    assert rep % pack == 0 and h % pack == 0
    block_q = min(block_q, sq)
    block_k = block_q
    g = b * h // pack
    qf = q.reshape(g, pack, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)
    kv_div = rep // pack

    kernel = functools.partial(
        _packed_fwd_inl_kernel, kv_seq_len=skv, block_k=block_k,
        sm_scale=sm_scale, causal=causal, block_q=block_q, pack=pack)
    out, lse = pl.pallas_call(
        kernel,
        grid=(g, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, pack, block_q, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // kv_div, 0, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // kv_div, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, pack, block_q, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((None, pack, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, pack, sq, d), q.dtype),
            jax.ShapeDtypeStruct((g, pack, sq), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def packed_fwd_epi(q, k, v, causal, sm_scale, pack=2, block_q=512,
                   block_k=512):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    rep = h // hkv
    assert rep % pack == 0 and h % pack == 0
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    g = b * h // pack
    qf = q.reshape(g, pack, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)
    kv_div = rep // pack

    kernel = functools.partial(
        _packed_fwd_epi_kernel, kv_seq_len=skv, block_k=block_k,
        sm_scale=sm_scale, causal=causal, block_q=block_q, pack=pack)
    out, lse = pl.pallas_call(
        kernel,
        grid=(g, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, pack, block_q, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // kv_div, 0, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // kv_div, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, pack, block_q, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((None, pack, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, pack, sq, d), q.dtype),
            jax.ShapeDtypeStruct((g, pack, sq), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)

def _packed_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, kv_seq_len,
                       block_k, sm_scale, causal, block_q, pack):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[...]                        # [pack, bq, d]
    p_, bq, d = q.shape
    q2 = q.reshape(p_ * bq, d)
    scale2 = sm_scale * LOG2E
    qs = (q2.astype(jnp.float32) * scale2).astype(q2.dtype)
    nkv = kv_seq_len // block_k

    rows = p_ * bq
    # Row r of the packed block is query position qi*bq + (r mod bq).
    row_iota = lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
    qpos = qi * bq + lax.rem(row_iota, bq)

    def body(j, carry, masked):
        o, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)
        if masked:
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m - m_new)
        v1 = jnp.concatenate([v, jnp.ones((v.shape[0], 1), v.dtype)], axis=1)
        ov = jnp.dot(p.astype(v.dtype), v1, preferred_element_type=jnp.float32)
        l_new = l * alpha + lax.slice(ov, (0, d), (rows, d + 1))[:, 0]
        o_new = o * alpha[:, None] + lax.slice(ov, (0, 0), (rows, d))
        return o_new, m_new, l_new

    o0 = jnp.zeros((rows, d), jnp.float32)
    m0 = jnp.full((rows,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows,), jnp.float32)
    if causal:
        upper = lax.div((qi + 1) * bq + block_k - 1, block_k)
        upper = jnp.minimum(upper, nkv)
        o, m, l = lax.fori_loop(0, upper,
                                functools.partial(body, masked=True),
                                (o0, m0, l0))
    else:
        o, m, l = lax.fori_loop(0, nkv, functools.partial(body, masked=False),
                                (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype).reshape(p_, bq, d)
    lse_ref[...] = ((m + jnp.log2(l)) * LN2).reshape(p_, bq)


def packed_fwd(q, k, v, causal, sm_scale, pack=2, block_q=512, block_k=512):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    rep = h // hkv
    assert rep % pack == 0 and h % pack == 0
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    g = b * h // pack                    # head-group grid rows
    # [b, h, s, d] -> [b*h/pack, pack, s, d]: adjacent heads share kv.
    qf = q.reshape(g, pack, sq, d)
    kf = k.reshape(b * hkv, skv, d)
    vf = v.reshape(b * hkv, skv, d)
    kv_div = rep // pack                 # grid rows per kv head

    kernel = functools.partial(
        _packed_fwd_kernel, kv_seq_len=skv, block_k=block_k,
        sm_scale=sm_scale, causal=causal, block_q=block_q, pack=pack)
    out, lse = pl.pallas_call(
        kernel,
        grid=(g, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, pack, block_q, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // kv_div, 0, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i // kv_div, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, pack, block_q, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((None, pack, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, pack, sq, d), q.dtype),
            jax.ShapeDtypeStruct((g, pack, sq), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--only", default="")
    args = ap.parse_args()

    key = jax.random.PRNGKey(0)
    kq, kk, kv_, _ = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, H, S, HD), jnp.bfloat16)
    k = jax.random.normal(kk, (B, KV, S, HD), jnp.bfloat16)
    v = jax.random.normal(kv_, (B, KV, S, HD), jnp.bfloat16)
    scale = 1.0 / math.sqrt(HD)

    if args.check:
        # Small-geometry correctness vs reference on the chip.
        qs = q[:1, :8, :1024]; ks = k[:1, :2, :1024]; vs = v[:1, :2, :1024]
        ref = attention_reference(qs, ks, vs, causal=True, sm_scale=scale)
        for pack in (2, 4):
            got, _ = packed_fwd(qs, ks, vs, True, scale, pack=pack)
            err = jnp.max(jnp.abs(got.astype(jnp.float32) -
                                  ref.astype(jnp.float32)))
            print(f"pack{pack} max|err| = {float(err):.5f}")
        for pack, bq in ((2, 512), (4, 256), (4, 512)):
            got, _ = packed_fwd_epi(qs, ks, vs, True, scale, pack=pack,
                                    block_q=bq)
            err = jnp.max(jnp.abs(got.astype(jnp.float32) -
                                  ref.astype(jnp.float32)))
            print(f"epi_pack{pack}_bq{bq} max|err| = {float(err):.5f}")
        for pack, bq in ((2, 512), (4, 512), (4, 256)):
            got, _ = packed_fwd_inl(qs, ks, vs, True, scale, pack=pack,
                                    block_q=bq)
            err = jnp.max(jnp.abs(got.astype(jnp.float32) -
                                  ref.astype(jnp.float32)))
            print(f"inl_pack{pack}_bq{bq} max|err| = {float(err):.5f}")
        return

    flops = 2 * 2 * B * H * S * S * HD / 2  # causal fwd QK^T + PV

    def mk(fn):
        def step(c):
            o, _ = fn(c, k, v)
            return o
        return step

    # NOTE: _flash_fwd_pallas now IS the packed+inline-diag kernel (the r5
    # winner landed in ops/attention.py), so "prod" measures the shipped
    # path; the historical block-size sweep of the old kernel was removed
    # because the old kernel no longer exists (it forced block_k=block_q
    # under inline_diag, making those labels lie).
    variants = {
        "prod": lambda q_, k_, v_: _flash_fwd_pallas(
            q_, k_, v_, True, scale),
        # Larger/smaller square blocks through the SHIPPED kernel — the
        # r5 96 MB scoped-vmem raise may admit shapes the 16 MB default
        # rejected.
        "prod_bq1024": lambda q_, k_, v_: _flash_fwd_pallas(
            q_, k_, v_, True, scale, block_q=1024),
        "prod_bq256": lambda q_, k_, v_: _flash_fwd_pallas(
            q_, k_, v_, True, scale, block_q=256),
        "pack2": lambda q_, k_, v_: packed_fwd(q_, k_, v_, True, scale, 2),
        "pack4": lambda q_, k_, v_: packed_fwd(q_, k_, v_, True, scale, 4),
        "pack2_bk1024": lambda q_, k_, v_: packed_fwd(
            q_, k_, v_, True, scale, 2, block_k=1024),
        "pack4_bk1024": lambda q_, k_, v_: packed_fwd(
            q_, k_, v_, True, scale, 4, block_k=1024),
        "pack4_bq256": lambda q_, k_, v_: packed_fwd(
            q_, k_, v_, True, scale, 4, block_q=256),
        "pack4_bq256_bk256": lambda q_, k_, v_: packed_fwd(
            q_, k_, v_, True, scale, 4, block_q=256, block_k=256),
        "epi_pack4_bq256": lambda q_, k_, v_: packed_fwd_epi(
            q_, k_, v_, True, scale, 4, block_q=256),
        "epi_pack4_bq512": lambda q_, k_, v_: packed_fwd_epi(
            q_, k_, v_, True, scale, 4, block_q=512),
        "epi_pack2_bq512": lambda q_, k_, v_: packed_fwd_epi(
            q_, k_, v_, True, scale, 2, block_q=512),
        "epi_pack4_bq256_bk256": lambda q_, k_, v_: packed_fwd_epi(
            q_, k_, v_, True, scale, 4, block_q=256, block_k=256),
        "inl_pack4_bq512": lambda q_, k_, v_: packed_fwd_inl(
            q_, k_, v_, True, scale, 4, block_q=512),
        "inl_pack2_bq512": lambda q_, k_, v_: packed_fwd_inl(
            q_, k_, v_, True, scale, 2, block_q=512),
        "inl_pack4_bq256": lambda q_, k_, v_: packed_fwd_inl(
            q_, k_, v_, True, scale, 4, block_q=256),
        "inl_pack1_bq512": lambda q_, k_, v_: packed_fwd_inl(
            q_, k_, v_, True, scale, 1, block_q=512),
        "inl_pack2_bq1024": lambda q_, k_, v_: packed_fwd_inl(
            q_, k_, v_, True, scale, 2, block_q=1024),
        "inl_pack4_bq1024": lambda q_, k_, v_: packed_fwd_inl(
            q_, k_, v_, True, scale, 4, block_q=1024),
    }
    for name, fn in variants.items():
        if args.only and args.only not in name:
            continue
        try:
            ms = timed_slope_chain(mk(fn), q) * 1e3
            print(f"{name:20s} {ms:7.3f} ms  {flops / (ms * 1e-3) / 1e12:6.1f} TF/s")
        except Exception as e:  # noqa: BLE001
            print(f"{name:20s} FAILED: {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
