"""Head/driver-side aggregation: per-process captures + span timeline →
one chrome-trace JSON and one fleet flamegraph.

The chrome trace interleaves three kinds of rows so the whole capture loads
as one Perfetto/chrome://tracing document:

- span slices (ph="X") from the PR-1 span timeline, one row per trace;
- sampling tracks per captured process: one slice per stack sample, named by
  the leaf frame (the "what was it doing" track);
- memory counters (ph="C") per process from the capture's snapshots.

The fleet flamegraph is plain collapsed-stack text: every process's stacks
prefixed with a ``kind:id@node`` root frame, counts summed — one file feeds
any flamegraph renderer (inferno, speedscope, flamegraph.pl).
"""

from __future__ import annotations

import json
import os


def _capture_label(cap: dict) -> str:
    meta = cap.get("meta") or {}
    kind = meta.get("kind", "process")
    ident = (meta.get("worker_id") or meta.get("source")
             or str(cap.get("pid", "?")))[:8]
    node = (meta.get("node_id") or "")[:8]
    return f"{kind}:{ident}@{node}" if node else f"{kind}:{ident}"


def merge_flamegraph(captures: list[dict]) -> str:
    """Sum collapsed stacks across captures, each rooted at its process
    label, so one flamegraph spans the fleet."""
    agg: dict[str, int] = {}
    for cap in captures:
        if not cap or cap.get("error"):
            continue
        label = _capture_label(cap)
        for line in (cap.get("collapsed") or "").splitlines():
            stack, _, n = line.rpartition(" ")
            if not stack or not n.isdigit():
                continue
            key = f"{label};{stack}"
            agg[key] = agg.get(key, 0) + int(n)
    return "\n".join(f"{k} {v}" for k, v in
                     sorted(agg.items(), key=lambda kv: (-kv[1], kv[0])))


def merge_chrome_trace(captures: list[dict],
                       spans: list[dict] | None = None) -> dict:
    """Chrome-trace object document merging sample tracks, memory counters,
    and the span timeline (same span-row shape as the ``timeline`` CLI, so
    the two artifacts never drift visually)."""
    events: list[dict] = []
    seen_spans = set()
    has_goodput = False
    for s in spans or []:
        # Span ids are minted per process: dedup on (trace_id, span_id) so
        # a cross-process collision can't swallow someone else's row.
        sid = (s.get("trace_id"), s.get("span_id"))
        if sid in seen_spans:
            continue
        seen_spans.add(sid)
        # Goodput phase chunks get their own lane, one row per (run, rank),
        # so the badput breakdown reads as a horizontal timeline next to
        # the sample tracks instead of drowning in the RPC span soup.
        attrs = s.get("attributes") or {}
        name = s.get("name", "")
        if name.startswith("goodput."):
            has_goodput = True
            pid = "goodput"
            tid = f"{attrs.get('run', '?')}/r{attrs.get('rank', '?')}"
        else:
            pid = "spans"
            tid = (s.get("trace_id") or "")[:8]
        events.append({
            "name": name, "cat": f"span:{s.get('kind', '')}",
            "ph": "X", "ts": s.get("start_ts", 0.0) * 1e6,
            "dur": max(0.0, (s.get("end_ts", 0.0) -
                             s.get("start_ts", 0.0)) * 1e6),
            "pid": pid, "tid": tid,
            "args": {"trace_id": s.get("trace_id"), "span_id": sid,
                     "status": s.get("status"), **attrs},
        })
    if spans is not None:
        events.append({"name": "process_name", "ph": "M", "pid": "spans",
                       "args": {"name": "ray_tpu spans"}})
    if has_goodput:
        events.append({"name": "process_name", "ph": "M", "pid": "goodput",
                       "args": {"name": "goodput phases"}})

    for cap in captures:
        if not cap or cap.get("error"):
            continue
        label = _capture_label(cap)
        hz = float(cap.get("sample_hz") or 100.0)
        dur_us = 1e6 / hz
        events.append({"name": "process_name", "ph": "M", "pid": label,
                       "args": {"name": f"samples {label}"}})
        for ev in cap.get("sample_events") or []:
            events.append({
                "name": ev.get("leaf") or "(idle)", "cat": "sample",
                "ph": "X", "ts": ev.get("ts", 0.0) * 1e6, "dur": dur_us,
                "pid": label, "tid": ev.get("thread", "thread"),
            })
        for which in ("memory_before", "memory"):
            mem = cap.get(which) or {}
            if not mem:
                continue
            events.append({
                "name": "rss_bytes", "ph": "C",
                "ts": mem.get("ts", 0.0) * 1e6, "pid": label,
                "args": {"rss": mem.get("rss_bytes", 0)},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_artifacts(result: dict, out_dir: str,
                    trace: dict | None = None,
                    flame: str | None = None) -> dict:
    """Write the merged artifacts of one cluster profile under ``out_dir``:
    trace.json (chrome trace), flame.txt (collapsed stacks), memory.json
    (per-process snapshots), captures.json (raw bundles, sample events
    elided — they are already in the trace). Returns the path map. Pass
    ``trace``/``flame`` when the caller already merged them (a fleet merge
    over thousands of sample events is not free to redo)."""
    os.makedirs(out_dir, exist_ok=True)
    captures = result.get("captures") or []
    if trace is None:
        trace = merge_chrome_trace(captures, result.get("spans"))
    if flame is None:
        flame = merge_flamegraph(captures)
    paths = {
        "trace": os.path.join(out_dir, "trace.json"),
        "flamegraph": os.path.join(out_dir, "flame.txt"),
        "memory": os.path.join(out_dir, "memory.json"),
        "captures": os.path.join(out_dir, "captures.json"),
    }
    with open(paths["trace"], "w") as f:
        json.dump(trace, f)
    with open(paths["flamegraph"], "w") as f:
        f.write(flame + ("\n" if flame else ""))
    with open(paths["memory"], "w") as f:
        json.dump([{"label": _capture_label(c),
                    "memory": c.get("memory"),
                    "memory_before": c.get("memory_before")}
                   for c in captures if c and not c.get("error")],
                  f, indent=2, default=str)
    slim = []
    for c in captures:
        c = dict(c or {})
        c.pop("sample_events", None)
        slim.append(c)
    with open(paths["captures"], "w") as f:
        json.dump({"captures": slim, "errors": result.get("errors") or {}},
                  f, default=str)
    return paths
