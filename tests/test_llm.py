"""LLM engine + serving tests (reference test model: vLLM-engine stage tests
in ray.llm tests; here the engine itself is under test)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams
from ray_tpu.llm.engine import decode_step, init_kv_cache, prefill, sample_tokens
from ray_tpu.models.llama import LlamaConfig, forward, init_params


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_prefill_decode_matches_full_forward(tiny):
    """Incremental decoding must produce the same logits as a full forward
    pass over the concatenated sequence (the KV-cache correctness spec)."""
    cfg, params = tiny
    prompt = np.array([5, 7, 11, 13], np.int32)
    n_extra = 3
    cache = init_kv_cache(cfg, max_slots=2, max_seq=32)

    # Reference: full forward over prompt + extra tokens.
    extra = np.array([17, 19, 23], np.int32)
    full = np.concatenate([prompt, extra])
    ref_logits = np.asarray(
        forward(cfg, params, jnp.asarray(full)[None], attn_impl="blockwise",
                remat=False))[0]

    # Engine path: prefill the prompt, then decode the extra tokens one by
    # one in slot 1 (slot 0 stays empty to catch slot-indexing bugs).
    toks = np.zeros((16,), np.int32)
    toks[:4] = prompt
    cache, last = prefill(cfg, params, cache, jnp.asarray(toks),
                          jnp.int32(4), jnp.int32(1))
    np.testing.assert_allclose(np.asarray(last), ref_logits[3], rtol=2e-4,
                               atol=2e-4)

    for i in range(n_extra):
        tokens = np.zeros((2,), np.int32)
        positions = np.zeros((2,), np.int32)
        tokens[1] = extra[i]
        positions[1] = 4 + i
        cache, logits = decode_step(cfg, params, cache,
                                    jnp.asarray(tokens),
                                    jnp.asarray(positions))
        np.testing.assert_allclose(np.asarray(logits[1]), ref_logits[4 + i],
                                   rtol=2e-4, atol=2e-4)


def test_sample_tokens_greedy_and_topp():
    logits = jnp.asarray([[0.0, 5.0, 1.0, 2.0],
                          [10.0, 0.0, 0.0, 0.0]], jnp.float32)
    # Greedy (temp 0)
    out = sample_tokens(logits, jnp.zeros(2), jnp.ones(2), 0,
                        jax.random.PRNGKey(0))
    assert list(np.asarray(out)) == [1, 0]
    # top_p=tiny keeps only the argmax even at high temperature
    out = sample_tokens(logits, jnp.full((2,), 5.0), jnp.full((2,), 1e-6), 0,
                        jax.random.PRNGKey(1))
    assert list(np.asarray(out)) == [1, 0]
    # top_k=1 likewise
    out = sample_tokens(logits, jnp.full((2,), 5.0), jnp.ones(2), 1,
                        jax.random.PRNGKey(2))
    assert list(np.asarray(out)) == [1, 0]


def test_engine_generate_deterministic():
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64)
    eng = LLMEngine(cfg)
    try:
        r1 = eng.generate("hello", SamplingParams(max_tokens=8))
        r2 = eng.generate("hello", SamplingParams(max_tokens=8))
        assert r1.token_ids == r2.token_ids  # greedy → deterministic
        assert 0 < len(r1.token_ids) <= 8
        assert r1.finish_reason in ("stop", "length")
    finally:
        eng.shutdown()


def test_engine_continuous_batching_concurrent():
    """More concurrent requests than slots: all must complete, and the
    engine must have had >1 slot active at once (continuous batching)."""
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64)
    eng = LLMEngine(cfg)
    try:
        peak = [0]
        stop = threading.Event()

        def watch():
            while not stop.is_set():
                peak[0] = max(peak[0], eng.stats()["active"])

        w = threading.Thread(target=watch, daemon=True)
        w.start()
        results = [None] * 5
        def gen(i):
            results[i] = eng.generate(f"prompt number {i}",
                                      SamplingParams(max_tokens=12))
        threads = [threading.Thread(target=gen, args=(i,)) for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop.set()
        assert all(r is not None for r in results)
        assert peak[0] >= 2
        # Each result matches its own solo regeneration (no cross-request
        # cache contamination).
        solo = eng.generate("prompt number 3", SamplingParams(max_tokens=12))
        assert solo.token_ids == results[3].token_ids
    finally:
        eng.shutdown()


def test_engine_streaming():
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64)
    eng = LLMEngine(cfg)
    try:
        chunks = list(eng.generate_stream("stream me",
                                          SamplingParams(max_tokens=6)))
        assert 1 <= len(chunks) <= 6
    finally:
        eng.shutdown()


def test_llm_server_openai_surface():
    ray_tpu.init()
    try:
        from ray_tpu import serve
        from ray_tpu.llm import build_openai_app

        app = build_openai_app(LLMConfig(model="tiny", max_num_seqs=2,
                                         max_seq_len=64))
        handle = serve.run(app, route_prefix=None, _blocking_timeout=120.0)
        out = handle.completions.remote("hi there").result(timeout=120)
        assert out["object"] == "text_completion"
        assert isinstance(out["choices"][0]["text"], str)
        assert out["usage"]["completion_tokens"] > 0

        chat = handle.chat.remote(
            [{"role": "user", "content": "hello"}]).result(timeout=120)
        assert chat["choices"][0]["message"]["role"] == "assistant"
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
