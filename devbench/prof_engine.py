"""Split engine-core throughput from the serve-stack overhead.

bench_serve.py (proxy → router → replica → engine, SSE streaming) measures
~41 tok/s on the chip; this drives LLMEngine DIRECTLY with the same
geometry/load so the difference attributes the gap.

PYTHONPATH=. python devbench/prof_engine.py [tiny]
"""
import sys
import threading
import time

from ray_tpu.llm import LLMConfig, SamplingParams
from ray_tpu.llm.engine import LLMEngine

tiny = "tiny" in sys.argv[1:]
cfg = LLMConfig(model="tiny" if tiny else "llama3_1b",
                max_num_seqs=8, max_seq_len=256 if tiny else 1024,
                dtype=None if tiny else "bfloat16")
eng = LLMEngine(cfg)

import os
N = int(os.environ.get("RTPU_PROF_N", "48"))
CONC, MAXTOK = 8, 32
print("warming...", flush=True)
eng.generate("warm " * 4, SamplingParams(max_tokens=15))

sem = threading.Semaphore(CONC)
lock = threading.Lock()
stats = {"tokens": 0, "ttfts": []}


def worker(i):
    with sem:
        t0 = time.perf_counter()
        first = []

        # generate() is blocking; use submit + stream queue for TTFT
        req = eng.submit(f"benchmark prompt {i} " * 4,
                         sampling=SamplingParams(max_tokens=MAXTOK),
                         stream=True)
        q = req.stream_queue
        n = 0
        while True:
            tok = q.get(timeout=300)
            if tok is None:
                break
            if not first:
                first.append(time.perf_counter() - t0)
            n += 1
        with lock:
            stats["tokens"] += n
            stats["ttfts"].append(first[0] if first else -1)


t0 = time.perf_counter()
threads = [threading.Thread(target=worker, args=(i,)) for i in range(N)]
for t in threads:
    t.start()
for t in threads:
    t.join()
wall = time.perf_counter() - t0
ttfts_ordered = [t for t in stats["ttfts"] if t >= 0]
qt = max(1, len(ttfts_ordered) // 4)
print(f"ttft first-quartile mean {sum(ttfts_ordered[:qt])/qt*1e3:.0f} ms, "
      f"last-quartile mean {sum(ttfts_ordered[-qt:])/qt*1e3:.0f} ms")
ttfts = sorted(ttfts_ordered)
print(f"engine-direct: {stats['tokens']} tokens in {wall:.1f}s = "
      f"{stats['tokens']/wall:.1f} tok/s; "
      f"ttft p50 {ttfts[len(ttfts)//2]*1e3:.0f} ms", flush=True)
eng.shutdown()
