"""Usage/telemetry recording (opt-out, local-only).

Capability parity with the reference's usage-stats shape (reference:
python/ray/_private/usage/usage_lib.py — feature-flag usage recorded and
(opt-out via RAY_USAGE_STATS_ENABLED=0) periodically reported): here usage
records append to a local JSON file only — nothing leaves the machine.
Disable with RTPU_USAGE_STATS_ENABLED=0.
"""

from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.Lock()
_features: set[str] = set()


def usage_stats_enabled() -> bool:
    return os.environ.get("RTPU_USAGE_STATS_ENABLED", "1") != "0"


def record_library_usage(name: str) -> None:
    """Mark a library (train/serve/data/...) as used this session."""
    _record("library", name)


def record_extra_usage_tag(key: str, value: str) -> None:
    _record("tag", f"{key}={value}")


def _record(kind: str, name: str) -> None:
    if not usage_stats_enabled():
        return
    tag = f"{kind}:{name}"
    with _lock:
        if tag in _features:
            return
        _features.add(tag)
    try:
        from ray_tpu.utils.config import get_config

        path = os.path.join(get_config().temp_dir, "usage_stats.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps({"ts": time.time(), "kind": kind,
                                "name": name}) + "\n")
    except Exception:
        pass


def recorded_features() -> set[str]:
    with _lock:
        return set(_features)
