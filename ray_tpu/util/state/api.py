"""State API: programmatic listing of cluster entities.

Capability parity with the reference's state API (reference:
python/ray/util/state/api.py — list_tasks/list_actors/list_objects/list_nodes/
list_workers/list_placement_groups + summarize_*, fed by GCS GcsTaskManager
and the GCS tables): entity listings with client-side filters. Filters are
``(key, op, value)`` triples with ops ``=``/``!=``, matching the reference's
filter surface.

Tasks come from this process's task-event buffer (the owner records every task
it submitted — in cluster mode that is the driver's view; node-wide events are
on each worker). Everything else comes from the runtime's state snapshot
(single source of truth: the head's tables in cluster mode).
"""

from __future__ import annotations

from typing import Any

from ray_tpu.core.worker import global_worker


def _snapshot() -> dict:
    global_worker.check_connected()
    return global_worker.runtime.state_snapshot()


def _apply_filters(rows: list[dict], filters) -> list[dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op == "=":
                ok = str(have) == str(value)
            elif op == "!=":
                ok = str(have) != str(value)
            else:
                raise ValueError(f"unsupported filter op {op!r} (use '=' or '!=')")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def list_nodes(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot()
    rows = [
        {"node_id": nid, **info} for nid, info in snap.get("nodes", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_actors(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot()
    rows = [
        {"actor_id": aid, **info} for aid, info in snap.get("actors", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_placement_groups(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot()
    rows = [
        {"placement_group_id": pid, **info}
        for pid, info in snap.get("placement_groups", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_workers(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot()
    rows = [
        {"worker_id": wid, **info} for wid, info in snap.get("workers", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 10_000) -> list[dict]:
    """Object-store summary rows (per-store aggregate, not per-object — the
    reference's per-object listing needs the owner scan; aggregate stats serve
    the same memory-debugging purpose here)."""
    snap = _snapshot()
    stats = snap.get("objects", {})
    return _apply_filters([{"store": "local", **stats}], filters)[:limit]


def list_tasks(filters=None, limit: int = 10_000) -> list[dict]:
    """Latest state per task, merging this process's events with the
    cluster-wide events workers flushed to the head (cluster mode)."""
    from ray_tpu.core.events import all_events

    latest: dict[str, dict] = {}
    for ev in sorted(all_events(), key=lambda e: e.ts):
        row = latest.setdefault(ev.task_id, {
            "task_id": ev.task_id, "name": ev.name, "state": ev.state,
            "worker_id": ev.worker_id, "actor_id": ev.actor_id,
            "job_id": ev.job_id, "start_ts": None, "end_ts": None,
        })
        row["state"] = ev.state
        row["name"] = ev.name or row["name"]
        row["worker_id"] = ev.worker_id or row["worker_id"]
        if ev.state == "RUNNING":
            row["start_ts"] = ev.ts
        elif ev.state in ("FINISHED", "FAILED", "CANCELLED"):
            row["end_ts"] = ev.ts
    rows = list(latest.values())
    return _apply_filters(rows, filters)[:limit]


def summarize_tasks() -> dict[str, Any]:
    """Counts by (name, state) — reference: summarize_tasks."""
    summary: dict[str, dict[str, int]] = {}
    for row in list_tasks():
        by_state = summary.setdefault(row["name"], {})
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return summary


def list_flight_records(kind: str | None = None) -> list[dict]:
    """Debug bundles dumped by the failure flight recorder on this host
    (task failures, worker deaths, actor deaths), oldest first. Each row
    has ``name``/``path``/``kind``/``ts_ns``; load one with
    ``get_flight_record(name)``."""
    from ray_tpu.core import flight_recorder

    rows = flight_recorder.list_records()
    if kind:
        rows = [r for r in rows if r["kind"] == kind]
    return rows


def get_flight_record(name: str) -> dict:
    """Load one flight-recorder bundle: the failure's context ids plus the
    last-N task events, finished spans, and a metrics snapshot captured at
    failure time."""
    from ray_tpu.core import flight_recorder

    return flight_recorder.get_record(name)


def list_logs(node_id: str | None = None) -> list[dict]:
    """Per-node worker log files (reference: `ray logs` listing via the
    dashboard agent). Cluster mode only; in-process runtimes have no
    worker processes and return []."""
    global_worker.check_connected()
    rt = global_worker.runtime
    peer = getattr(rt, "_peer", None)
    if peer is None:
        return []
    out: list[dict] = []
    for node in list_nodes():
        if node_id and node["node_id"] != node_id:
            continue
        if not node.get("alive"):
            continue
        try:
            res = peer(tuple(node["addr"])).call("list_logs")
            out.extend(res.get("logs", []))
        except Exception:  # noqa: BLE001 - dead daemon: skip its logs
            continue
    return out


def get_log(filename: str, node_id: str, tail_bytes: int = 65536) -> str:
    """Tail of one worker log file on one node (reference: `ray logs
    <file> --node-id ...`)."""
    global_worker.check_connected()
    rt = global_worker.runtime
    peer = getattr(rt, "_peer", None)
    if peer is None:
        raise ValueError("log access requires cluster mode")
    for node in list_nodes():
        if node["node_id"] == node_id:
            if not node.get("alive"):
                raise ValueError(f"node {node_id!r} is not alive")
            res = peer(tuple(node["addr"])).call(
                "tail_log", filename=filename, tail_bytes=tail_bytes)
            if res.get("error"):
                raise FileNotFoundError(res["error"])
            return res["data"]
    raise ValueError(f"unknown node {node_id!r}")
