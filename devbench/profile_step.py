"""Decompose the Llama-1B train-step time on the real TPU.

Timing protocol (axon tunnel): block_until_ready does not block, so every
measurement chains steps through donated state and ends with a scalar host
fetch; per-step time is the slope between two iteration counts (cancels the
fixed ~70ms dispatch+fetch latency).
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models.llama import LlamaConfig, forward_hidden, init_params, loss_fn, param_logical_axes, unembed_weights
from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.train.spmd import make_llama_train_step

cfg = LlamaConfig(
    vocab_size=32128, hidden_size=2048, intermediate_size=8192,
    num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
    max_seq_len=2048, tie_embeddings=True, dtype="bfloat16",
)
BATCH, SEQ = 4, 2048
N_PARAMS = cfg.num_params()
mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])


def timed_slope(run_n, n1=3, n2=9, reps=3):
    """run_n(n) must execute n chained device steps then fetch a scalar.

    One sample per point is too fragile on the tunnel (a single slow
    dispatch — e.g. a compile-service retry — flips the slope negative).
    Min over the per-point times, then one slope: a slow dispatch inflates
    a single timing, and min-per-point discards it symmetrically (min over
    *slopes* would keep exactly the corrupted n1-inflated sample).
    """
    run_n(1)  # warmup/compile
    run_n(1)  # settle (first post-compile dispatch can still be slow)
    ta = tb = None
    for _ in range(reps):
        t0 = time.perf_counter(); run_n(n1); t = time.perf_counter() - t0
        ta = t if ta is None else min(ta, t)
        t0 = time.perf_counter(); run_n(n2); t = time.perf_counter() - t0
        tb = t if tb is None else min(tb, t)
    s = (tb - ta) / (n2 - n1)
    return s if s > 0 else float("nan")


def report(name, per_step, tokens=BATCH * SEQ):
    tps = tokens / per_step
    mfu = 6.0 * N_PARAMS * tps / 1.97e14
    print(f"{name:34s} {per_step*1e3:8.1f} ms  {tps:9.0f} tok/s  "
          f"model-MFU(v5e)={mfu:.3f}", flush=True)


rng = np.random.default_rng(0)
tokens_h = rng.integers(0, cfg.vocab_size, (BATCH, SEQ), dtype=np.int32)
targets_h = np.roll(tokens_h, -1, axis=1)

# ---- full train step (dots, flash) -----------------------------------------
opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=jnp.bfloat16)
step_fn, init_state, shard = make_llama_train_step(
    cfg, mesh, optimizer=opt, attn_impl="flash", remat="dots")
state = init_state()
tokens = shard(tokens_h)
targets = shard(targets_h)


def run_full(n):
    global state
    for _ in range(n):
        state, m = step_fn(state, tokens, targets)
    float(m["loss"])


report("full step (dots, flash)", timed_slope(run_full))

# ---- full train step, round-4 bench winner (attn remat + compact moments) ---
# Keep only the params from the first state (gradloop sections below need
# them); drop its optimizer moments before allocating the second state or
# the two full states OOM the chip together.
from ray_tpu.train.optim import adamw_lowmem

params = state.params
state = None

step_fn2, init_state2, _ = make_llama_train_step(
    cfg, mesh, optimizer=adamw_lowmem(3e-4, weight_decay=0.1),
    attn_impl="flash", remat="attn")
state2 = init_state2()


def run_full_attn(n):
    global state2
    for _ in range(n):
        state2, m = step_fn2(state2, tokens, targets)
    float(m["loss"])


report("full step (attn, flash, lowmem)", timed_slope(run_full_attn))
state2 = step_fn2 = None

# ---- fwd+bwd only (no optimizer) -------------------------------------------


def make_gradloop(attn_impl, remat, fused_ce=True):
    def gloss(p, t, tg):
        return loss_fn(cfg, p, t, tg, fused_ce=fused_ce, attn_impl=attn_impl,
                       remat=remat)

    @jax.jit
    def gstep(p, t, tg, acc):
        l, g = jax.value_and_grad(gloss)(p, t, tg)
        # chain dependency: fold grads into a scalar accumulator
        return acc + l + 0.0 * jax.tree_util.tree_reduce(
            lambda a, b: a + b.astype(jnp.float32).sum() * 0.0, g, 0.0)

    def run(n):
        acc = jnp.zeros((), jnp.float32)
        for _ in range(n):
            acc = gstep(params, tokens, targets, acc)
        float(acc)
    return run


def safe(name, thunk, tokens=BATCH * SEQ):
    try:
        report(name, timed_slope(thunk), tokens)
    except Exception as e:
        print(f"{name:34s} FAILED: {str(e)[:120]}", flush=True)


safe("fwd+bwd (dots, flash)", make_gradloop("flash", "dots"))
safe("fwd+bwd (full remat, flash)", make_gradloop("flash", "full"))

# ---- fwd only ---------------------------------------------------------------
@jax.jit
def fwd_only(p, t, tg, acc):
    return acc + loss_fn(cfg, p, t, tg, fused_ce=True, attn_impl="flash",
                         remat="none")


def run_fwd(n):
    acc = jnp.zeros((), jnp.float32)
    for _ in range(n):
        acc = fwd_only(params, tokens, targets, acc)
    float(acc)


safe("fwd only (flash, no remat)", run_fwd)

# ---- fwd+bwd of hidden trunk only (no CE head) ------------------------------
def make_trunk(attn_impl):
    def tl(p, t):
        x = forward_hidden(cfg, p, t, attn_impl=attn_impl, remat="dots")
        return x.astype(jnp.float32).mean()

    @jax.jit
    def tstep(p, t, acc):
        l, g = jax.value_and_grad(tl)(p, t)
        return acc + l + 0.0 * jax.tree_util.tree_reduce(
            lambda a, b: a + b.astype(jnp.float32).sum() * 0.0, g, 0.0)

    def run(n):
        acc = jnp.zeros((), jnp.float32)
        for _ in range(n):
            acc = tstep(params, tokens, acc)
        float(acc)
    return run


safe("fwd+bwd trunk only (no CE)", make_trunk("flash"))
