"""Object serialization for the object store and RPC layer.

Capability parity with the reference's serialization layer
(reference: python/ray/_private/serialization.py + cloudpickle/): arbitrary
Python objects via cloudpickle, with a zero-copy fast path for numpy / JAX
host arrays (raw buffer + dtype/shape header instead of pickling), and
out-of-band ObjectRef tracking so refs nested inside arguments/returns are
discovered for ownership/refcounting.
"""

from __future__ import annotations

import io
import sys
import pickle
from typing import Any

import cloudpickle
import numpy as np

# Wire format: 1-byte tag + payload.
_TAG_PICKLE = b"P"
_TAG_NDARRAY = b"N"
_TAG_RAW = b"R"  # pre-serialized bytes passthrough
_TAG_BYTES = b"B"  # top-level bytes/bytearray: payload IS the value


def _extract_refs(obj: Any) -> list:
    """Find ObjectRefs nested anywhere in ``obj`` (via pickle traversal)."""
    from ray_tpu.core.object_ref import ObjectRef

    found: list = []

    class _Scanner(cloudpickle.CloudPickler):
        def persistent_id(self, o):  # noqa: N802 - pickle API name
            if isinstance(o, ObjectRef):
                found.append(o)
                return ("ref", len(found) - 1)
            return None

    _Scanner(io.BytesIO()).dump(obj)
    return found


def find_nested_refs(obj: Any) -> list:
    try:
        return _extract_refs(obj)
    except Exception:
        return []


class _ArgPickler(cloudpickle.CloudPickler):
    """CloudPickler that records ObjectRefs as they stream past."""

    _ref_cls = None  # resolved lazily (import cycle)

    def __init__(self, file, refs: list):
        super().__init__(file, protocol=pickle.HIGHEST_PROTOCOL)
        if _ArgPickler._ref_cls is None:
            from ray_tpu.core.object_ref import ObjectRef

            _ArgPickler._ref_cls = ObjectRef
        self._refs = refs

    def persistent_id(self, o):  # noqa: N802 - pickle API name
        if isinstance(o, _ArgPickler._ref_cls):
            self._refs.append(o)
        return None  # keep normal pickling; we only observe


def serialize_args(args_kwargs: tuple) -> tuple[bytes, list]:
    """Serialize ``(args, kwargs)`` and collect nested ObjectRefs in ONE
    pickle pass (the hot submit path previously paid a discovery dump plus a
    serialization dump — reference: the raylet codepath also discovers refs
    during argument serialization, serialization.py SerializedObject)."""
    found: list = []
    buf = io.BytesIO()
    _ArgPickler(buf, found).dump(args_kwargs)
    return _TAG_PICKLE + buf.getvalue(), found


def dumps_spec(spec) -> bytes:
    """Wire format for Task/ActorCreation specs: plain pickle (protocol 5).
    Specs are plain dataclasses of importable classes — cloudpickle's
    reducer_override machinery is ~3x slower and only needed for code
    objects, which ride pre-serialized in fn_blob/cls_blob."""
    return pickle.dumps(spec, protocol=pickle.HIGHEST_PROTOCOL)


def loads_spec(data: bytes):
    return pickle.loads(data)


def serialize_parts(obj: Any) -> list:
    """Serialize ``obj`` to a list of buffers whose concatenation is the wire
    format. Large array payloads stay as zero-copy memoryviews so the store
    layer can scatter-write them (one memcpy into the shm arena instead of a
    serialize-copy followed by a store-copy — reference: plasma writes the
    pickle5 out-of-band buffers straight into the object's plasma slab)."""
    if isinstance(obj, np.ndarray) and obj.dtype != object:
        header = cloudpickle.dumps((obj.dtype.str, obj.shape))
        buf = np.ascontiguousarray(obj)
        return [
            _TAG_NDARRAY + len(header).to_bytes(4, "little") + header,
            memoryview(buf).cast("B"),
        ]
    if type(obj) is bytes:
        # Tag + raw payload, no pickle framing: the store scatter-writes
        # the buffer without a serialize copy, and deserialize is ONE
        # memcpy (cloudpickle round-trips a large bytes payload through
        # the opcode scanner — measurably slower than memcpy on the
        # multi-GB broadcast path). bytes ONLY: bytearray must round-trip
        # as bytearray, so it stays on the pickle path.
        return [_TAG_BYTES, memoryview(obj)]
    return [_TAG_PICKLE + cloudpickle.dumps(obj)]


def serialize(obj: Any) -> bytes:
    """Serialize ``obj`` to a self-describing byte string."""
    parts = serialize_parts(obj)
    if len(parts) == 1:
        return bytes(parts[0]) if isinstance(parts[0], memoryview) else parts[0]
    return b"".join(parts)


def deserialize(data) -> Any:
    """Deserialize from bytes, a memoryview, or a pinned ArenaView.

    memoryview inputs are sliced zero-copy (no upfront bytes() of the
    whole payload — on the warm-pull path that was a full extra traversal
    of the object). An ArenaView input additionally returns large arrays
    as ZERO-COPY read-only views over the shm arena, pinned until the
    array is garbage-collected.

    READ-ONLY get() CONTRACT (reference: plasma-backed ray.get returns
    read-only arrays): an ndarray materialized from a store-backed view is
    never writable — on >= 3.12 via the PEP 688 __buffer__ export, on
    older Pythons via a read-only frombuffer view whose finalizer holds
    the arena pin, and even on the copying fallback the writeable flag is
    cleared so behavior is uniform across Python versions and store
    paths. Mutating consumers must copy explicitly (np.array(x))."""
    pin = None
    if hasattr(data, "view") and hasattr(data, "release"):  # ArenaView
        pin = data
        data = pin.view
    if isinstance(data, memoryview):
        tag = bytes(data[:1])
        payload = data[1:]  # zero-copy slice
    else:
        tag, payload = data[:1], data[1:]
    try:
        if tag == _TAG_NDARRAY:
            hlen = int.from_bytes(bytes(payload[:4]), "little")
            dtype_str, shape = cloudpickle.loads(payload[4: 4 + hlen])
            body = payload[4 + hlen:]
            if pin is not None and _HAS_PY_BUFFER:
                # READ-ONLY zero-copy view over the arena (the reference's
                # plasma semantics: ray.get returns read-only arrays for
                # store-backed objects; small inline objects stay writable
                # copies). The pin rides as the array's buffer owner and
                # releases on GC.
                arr = np.frombuffer(_PinnedSlice(pin, body),
                                    dtype=np.dtype(dtype_str)).reshape(shape)
                pin = None  # ownership moved to the array's base
                return arr  # read-only: the exported buffer is readonly
            if pin is not None and isinstance(body, memoryview):
                # < 3.12 (no Python-level __buffer__): still zero-copy.
                # frombuffer over the read-only arena slice yields a
                # READ-ONLY array (toreadonly() means nobody can flip
                # writeable back on); the finalizer holds the pin until
                # the last view into the buffer is collected (numpy keeps
                # the base chain alive for every derived view).
                import weakref

                arr = np.frombuffer(body.toreadonly(),
                                    dtype=np.dtype(dtype_str)).reshape(shape)
                weakref.finalize(arr, pin.release)
                pin = None  # ownership moved to the finalizer
                return arr
            arr = np.frombuffer(body, dtype=np.dtype(dtype_str)).reshape(
                shape).copy()
            if pin is not None:
                # Copying fallback for a store-backed view that couldn't be
                # wrapped zero-copy: keep the read-only contract uniform —
                # an array from the object store is NEVER writable, whether
                # it is a pinned arena view or this private copy.
                arr.flags.writeable = False
            return arr
        if tag == _TAG_PICKLE:
            return cloudpickle.loads(payload)
        if tag == _TAG_BYTES:
            return bytes(payload)  # single memcpy out of the arena/buffer
        if tag == _TAG_RAW:
            return bytes(payload) if isinstance(payload, memoryview) \
                else payload
        raise ValueError(f"unknown serialization tag {tag!r}")
    finally:
        if pin is not None:
            pin.release()


# PEP 688 Python-level __buffer__ exists only on 3.12+; older versions
# fall back to the copying path (correct, one traversal slower).
_HAS_PY_BUFFER = sys.version_info >= (3, 12)


class _PinnedSlice:
    """Buffer-protocol shim: exposes a payload slice of a pinned
    ArenaView, keeping the pin alive as np.frombuffer's base."""

    __slots__ = ("_pin", "_body")

    def __init__(self, pin, body: memoryview):
        self._pin = pin
        self._body = body

    def __buffer__(self, flags):  # PEP 688
        # READ-ONLY: a writable export would let callers flip the array's
        # writeable flag back on and mutate the sealed arena object.
        return memoryview(self._body).toreadonly()


def dumps_function(fn) -> bytes:
    """Serialize a function/class definition for code shipping (reference:
    python/ray/_private/function_manager.py ships pickled defs via GCS KV)."""
    return cloudpickle.dumps(fn, protocol=pickle.HIGHEST_PROTOCOL)


def loads_function(data: bytes):
    return cloudpickle.loads(data)
