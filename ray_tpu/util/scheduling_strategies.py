"""Public scheduling-strategy types (reference:
python/ray/util/scheduling_strategies.py — NodeAffinitySchedulingStrategy,
PlacementGroupSchedulingStrategy, and the "DEFAULT"/"SPREAD" strings
accepted by @remote(scheduling_strategy=...)).
"""

from __future__ import annotations

from dataclasses import dataclass

from ray_tpu.core.task_spec import SchedulingStrategy
from ray_tpu.util.placement_group import PlacementGroupSchedulingStrategy

__all__ = [
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to a node by id; soft=True falls back to the default policy when
    the node is dead or lacks capacity (reference:
    scheduling_strategies.py NodeAffinitySchedulingStrategy)."""

    node_id: str
    soft: bool = False

    def to_scheduling_strategy(self) -> SchedulingStrategy:
        return SchedulingStrategy(kind="NODE_AFFINITY",
                                  node_id_hex=self.node_id, soft=self.soft)
