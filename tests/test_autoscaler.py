"""Autoscaler v2: instance FSM, bin-packing, end-to-end elastic capacity.

Mirrors the reference's autoscaler test surface (reference:
python/ray/autoscaler/v2/tests/ — FSM transition asserts, scheduler
bin-packing, FakeMultiNodeProvider end-to-end scale up/down).
"""

import os
import time

import pytest

from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalingConfig,
    FakeMultiNodeProvider,
    InstanceManager,
    InstanceStatus,
    NodeTypeConfig,
    TpuSliceProvider,
    bin_pack_demands,
)


from _test_util import load_factor as _load_factor  # noqa: E402


class TestInstanceFsm:
    def test_happy_path(self):
        mgr = InstanceManager()
        inst = mgr.create("cpu4")
        assert inst.status == InstanceStatus.QUEUED
        mgr.transition(inst.instance_id, InstanceStatus.REQUESTED)
        mgr.transition(inst.instance_id, InstanceStatus.ALLOCATED,
                       cloud_id="c-1")
        mgr.transition(inst.instance_id, InstanceStatus.RAY_RUNNING,
                       node_id="n-1")
        mgr.transition(inst.instance_id, InstanceStatus.RAY_STOPPING)
        mgr.transition(inst.instance_id, InstanceStatus.TERMINATED)
        assert [s for s, _ in mgr.get(inst.instance_id).status_history] == [
            "QUEUED", "REQUESTED", "ALLOCATED", "RAY_RUNNING",
            "RAY_STOPPING", "TERMINATED"]

    def test_illegal_transition_raises(self):
        mgr = InstanceManager()
        inst = mgr.create("cpu4")
        with pytest.raises(ValueError):
            mgr.transition(inst.instance_id, InstanceStatus.RAY_RUNNING)
        mgr.transition(inst.instance_id, InstanceStatus.REQUESTED)
        with pytest.raises(ValueError):
            mgr.transition(inst.instance_id, InstanceStatus.QUEUED)

    def test_allocation_failure_is_terminal(self):
        mgr = InstanceManager()
        inst = mgr.create("cpu4")
        mgr.transition(inst.instance_id, InstanceStatus.REQUESTED)
        mgr.transition(inst.instance_id, InstanceStatus.ALLOCATION_FAILED)
        with pytest.raises(ValueError):
            mgr.transition(inst.instance_id, InstanceStatus.ALLOCATED)
        assert inst not in mgr.active()


class TestBinPacking:
    TYPES = {"cpu4": {"CPU": 4.0}, "cpu16": {"CPU": 16.0},
             "tpu_host": {"CPU": 8.0, "TPU": 4.0}}

    def test_existing_capacity_absorbs(self):
        launches, infeasible = bin_pack_demands(
            [{"CPU": 1.0}] * 3, [{"CPU": 4.0}], self.TYPES)
        assert launches == {} and infeasible == []

    def test_launches_smallest_fitting_type(self):
        launches, _ = bin_pack_demands([{"CPU": 1.0}], [], self.TYPES)
        assert launches == {"cpu4": 1}
        launches, _ = bin_pack_demands([{"CPU": 10.0}], [], self.TYPES)
        assert launches == {"cpu16": 1}
        launches, _ = bin_pack_demands([{"TPU": 4.0}], [], self.TYPES)
        assert launches == {"tpu_host": 1}

    def test_packs_multiple_demands_per_node(self):
        launches, _ = bin_pack_demands([{"CPU": 2.0}] * 4, [], self.TYPES)
        # 8 CPUs of demand: two cpu4 nodes (first-fit into new nodes).
        assert sum(launches.values()) == 2

    def test_max_per_type_and_infeasible(self):
        launches, infeasible = bin_pack_demands(
            [{"CPU": 4.0}] * 3, [], {"cpu4": {"CPU": 4.0}},
            max_new_per_type={"cpu4": 2})
        assert launches == {"cpu4": 2}
        assert len(infeasible) == 1
        _, infeasible = bin_pack_demands([{"GPU": 1.0}], [], self.TYPES)
        assert infeasible == [{"GPU": 1.0}]


class TestTpuSliceProvider:
    def test_atomic_slice_lifecycle(self):
        calls = []
        provider = TpuSliceProvider(
            "v5p-16", "2x2x2",
            create_slice_fn=lambda name, at, topo: calls.append(("create", name, at, topo)),
            delete_slice_fn=lambda name: calls.append(("delete", name)),
        )
        cid = provider.launch_node("tpu_slice", {"TPU": 8.0})
        assert calls[0][0] == "create" and calls[0][2] == "v5p-16"
        assert provider.node_status(cid) == "running"
        provider.terminate_node(cid)
        assert calls[-1][0] == "delete"
        assert provider.node_status(cid) == "terminated"


class TestEndToEnd:
    def test_scale_up_then_down(self):
        """Pending demand launches a real in-process node; idle terminates it."""
        import ray_tpu
        from ray_tpu.core.worker import global_worker

        ray_tpu.shutdown()
        ray_tpu.init(address="local-cluster", num_cpus=1)
        try:
            rt = global_worker.runtime
            config = AutoscalingConfig(
                node_types={"cpu2": NodeTypeConfig({"CPU": 2.0}, max_workers=2)},
                idle_timeout_s=1.0,
            )
            provider = FakeMultiNodeProvider(
                (rt._head_host, rt._head_port))
            scaler = Autoscaler(config, provider, rt.head)

            # Demand beyond the 1-CPU head node: 2 concurrent 1-CPU tasks.
            # SPREAD keeps one task in flight per leased worker, so the
            # excess stays a pending lease request at the daemon — the
            # demand signal the autoscaler reads. (Default scheduling
            # pipelines up to 16 queued tasks onto one worker: whenever
            # the first lease grant beats the burst — warm pools, warm
            # page cache mid-suite — the whole backlog hides inside the
            # pipeline and no demand ever surfaces, which made this test
            # flake by suite order.)
            @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
            def hold(sec):
                time.sleep(sec)
                return 1

            refs = [hold.remote(6) for _ in range(3)]
            # Wait for the daemons to heartbeat their pending queues.
            deadline = time.monotonic() + 15
            launched = {}
            while time.monotonic() < deadline and not launched:
                summary = scaler.update()
                launched = summary["launched"]
                time.sleep(0.5)
            assert launched.get("cpu2", 0) >= 1, "no scale-up happened"

            # With the new node, all tasks complete.
            assert ray_tpu.get(refs, timeout=60) == [1, 1, 1]

            # Node registers as RAY_RUNNING after joining.
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                scaler.update()
                if scaler.instances.instances((InstanceStatus.RAY_RUNNING,)):
                    break
                time.sleep(0.5)
            assert scaler.instances.instances((InstanceStatus.RAY_RUNNING,))

            # Idle: scaled back down past the timeout.
            deadline = time.monotonic() + 20
            terminated = []
            while time.monotonic() < deadline and not terminated:
                terminated = scaler.update()["terminated"]
                time.sleep(0.5)
            assert terminated, "idle node was not terminated"
        finally:
            ray_tpu.shutdown()


class TestSubprocessBootstrap:
    """e2e over the real ``start`` bootstrap path (reference:
    fake_multi_node/node_provider.py:237 + command_runner.py): demand →
    provider launches a node as a detached OS process via the CLI → it
    joins over TCP → the pending task schedules there → idle scale-down
    ``stop``s the process."""

    def test_demand_boots_real_process_node(self, tmp_path):
        import ray_tpu
        from ray_tpu.autoscaler.node_provider import SubprocessNodeProvider
        from ray_tpu.core.worker import global_worker

        ray_tpu.shutdown()
        ray_tpu.init(address="local-cluster", num_cpus=1)
        try:
            rt = global_worker.runtime
            config = AutoscalingConfig(
                node_types={"cpu2": NodeTypeConfig(
                    {"CPU": 2.0, "boot": 1.0}, max_workers=2)},
                idle_timeout_s=1.0,
            )
            provider = SubprocessNodeProvider(
                f"{rt._head_host}:{rt._head_port}", str(tmp_path))
            scaler = Autoscaler(config, provider, rt.head)

            # SPREAD so the backlog surfaces as pending lease demand
            # instead of hiding in one worker's pipeline (see
            # TestEndToEnd.test_scale_up_then_down).
            @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
            def hold(sec):
                time.sleep(sec)
                return os.environ.get("RTPU_NODE_ID", "")

            # Load-scaled deadlines: the booted node's fork+import+register
            # and the 3 serialized hold() leases stretch together under
            # full-suite pressure.
            lf = _load_factor()
            # Saturate the 1-CPU head so later probes cannot land there.
            refs = [hold.remote(18) for _ in range(3)]
            deadline = time.monotonic() + 20 * lf
            launched = {}
            while time.monotonic() < deadline and not launched:
                launched = scaler.update()["launched"]
                time.sleep(0.5)
            assert launched.get("cpu2", 0) >= 1, "no scale-up happened"
            (cloud_id, rec), = list(provider._nodes.items())[:1]
            assert provider.node_status(cloud_id) == "running"
            pid = provider._pid(rec)
            assert pid is not None

            # RAY_RUNNING once the daemon registered under its node id.
            deadline = time.monotonic() + 15 * lf
            while time.monotonic() < deadline:
                scaler.update()
                if scaler.instances.instances((InstanceStatus.RAY_RUNNING,)):
                    break
                time.sleep(0.5)
            assert scaler.instances.instances((InstanceStatus.RAY_RUNNING,))

            # New work requiring the booted node type's marker resource
            # must schedule on the freshly booted process node.
            probes = [hold.options(num_cpus=1, resources={"boot": 0.1})
                      .remote(0) for _ in range(2)]
            homes = ray_tpu.get(probes, timeout=60 * lf)
            assert all(h.startswith("sub-") for h in homes), homes
            assert ray_tpu.get(refs, timeout=60 * lf)

            # Idle scale-down stops the OS process(es). The SPREAD demand
            # may have launched MORE than one cpu2 node; keep running
            # update() until the provider has none left — nodes idle (and
            # terminate) at different times, so stopping at the first
            # termination would leave the other's process running and its
            # pid alive.
            pids = [provider._pid(rec)
                    for rec in list(provider._nodes.values())]
            deadline = time.monotonic() + 30 * lf
            terminated = []
            while time.monotonic() < deadline and provider._nodes:
                terminated += scaler.update()["terminated"]
                time.sleep(0.5)
            assert terminated, "idle node was not terminated"
            assert not provider._nodes, \
                f"nodes never terminated: {provider._nodes}"
            # The provider's `ray_tpu stop` subprocess pays interpreter
            # start + framework import (~seconds on a loaded 1-core box)
            # before SIGTERM, then up to a 5 s grace before SIGKILL — and
            # the SPREAD holds leave worker children to reap too.
            deadline = time.monotonic() + 30 * lf
            live = [p for p in pids if p is not None]
            while time.monotonic() < deadline and live:
                for p in list(live):
                    try:
                        os.kill(p, 0)
                    except ProcessLookupError:
                        live.remove(p)
                time.sleep(0.2)
            assert not live, \
                f"node process(es) still alive after stop: {live}"
        finally:
            ray_tpu.shutdown()


class TestCommandRunners:
    def test_local_runner_runs_and_raises(self):
        from ray_tpu.autoscaler.command_runner import LocalCommandRunner

        out = LocalCommandRunner().run(["echo", "hi"])
        assert out.strip() == "hi"
        with pytest.raises(RuntimeError):
            LocalCommandRunner().run(["false"])

    def test_ssh_runner_builds_command(self):
        from ray_tpu.autoscaler.command_runner import SshCommandRunner

        seen = {}

        def fake_exec(argv, timeout):
            seen["argv"] = argv
            import subprocess

            return subprocess.CompletedProcess(argv, 0, stdout="done",
                                               stderr="")

        r = SshCommandRunner("10.0.0.5", user="worker", ssh_key="/k",
                             exec_fn=fake_exec)
        assert r.run(["python", "-m", "ray_tpu", "start",
                      "--address=h:1"]) == "done"
        argv = seen["argv"]
        assert argv[0] == "ssh" and "worker@10.0.0.5" in argv
        assert "-i" in argv and "/k" in argv
        assert argv[-1] == "python -m ray_tpu start --address=h:1"


class TestGcpProvider:
    """GCE/GKE cloud provider against a mocked REST transport (reference:
    python/ray/autoscaler/_private/gcp/node_provider.py — unverifiable
    live here, so the API surface is exercised through the injectable
    request_fn)."""

    def _mock_gce(self):
        instances = {}
        calls = []

        def request_fn(method, url, body=None):
            calls.append((method, url, body))
            if method == "POST" and url.endswith("/instances"):
                instances[body["name"]] = {"status": "PROVISIONING",
                                           **body}
                return {"name": "op-1"}
            if method == "GET":
                name = url.rsplit("/", 1)[1]
                if name not in instances:
                    raise KeyError(name)
                return instances[name]
            if method == "DELETE":
                name = url.rsplit("/", 1)[1]
                instances.pop(name, None)
                return {"name": "op-2"}
            raise AssertionError(f"unexpected {method} {url}")

        return instances, calls, request_fn

    def test_gce_instance_lifecycle(self):
        from ray_tpu.autoscaler.gcp import GceNodeProvider

        instances, calls, request_fn = self._mock_gce()
        p = GceNodeProvider(
            "proj", "us-central1-a", "mycluster", "10.0.0.2:6379",
            node_configs={"cpu8": {"machine_type": "n2-standard-8"}},
            request_fn=request_fn)
        cid = p.launch_node("cpu8", {"CPU": 8.0})
        name = p._instances[cid]
        create = calls[0]
        assert create[0] == "POST" and "/zones/us-central1-a/" in create[1]
        assert create[2]["labels"]["ray-cluster"] == "mycluster"
        assert create[2]["labels"]["ray-node-type"] == "cpu8"
        assert "n2-standard-8" in create[2]["machineType"]
        assert "--address=10.0.0.2:6379" in \
            create[2]["metadata"]["items"][0]["value"]

        assert p.node_status(cid) == "pending"
        assert p.runtime_node_id(cid) is None
        instances[name]["status"] = "RUNNING"
        assert p.node_status(cid) == "running"
        assert p.runtime_node_id(cid) == name  # joins under its hostname

        p.terminate_node(cid)
        assert calls[-1][0] == "DELETE" and calls[-1][1].endswith(name)
        assert p.node_status(cid) == "terminated"

    def test_tpu_queued_resource_slice(self):
        from ray_tpu.autoscaler.gcp import tpu_slice_provider_from_gcp

        qrs = {}
        calls = []

        def request_fn(method, url, body=None):
            calls.append((method, url, body))
            if method == "POST":
                name = url.split("queuedResourceId=")[1]
                qrs[name] = {"state": {"state": "ACCEPTED"}, **body}
                return {}
            if method == "GET":
                name = url.rsplit("/", 1)[1]
                return qrs[name]
            if method == "DELETE":
                name = url.rsplit("/", 1)[1].split("?")[0]
                qrs.pop(name, None)
                return {}
            raise AssertionError(f"unexpected {method} {url}")

        p = tpu_slice_provider_from_gcp(
            "proj", "us-east5-a", "v5p", "4x4x4", request_fn=request_fn)
        cid = p.launch_node("tpu_slice", {"TPU": 64.0})
        post = calls[0]
        assert "queuedResources?queuedResourceId=" in post[1]
        spec = post[2]["tpu"]["nodeSpec"][0]
        assert spec["node"]["acceleratorConfig"]["topology"] == "4x4x4"

        assert p.node_status(cid) == "pending"  # ACCEPTED -> pending
        name = post[1].split("queuedResourceId=")[1]
        qrs[name]["state"]["state"] = "ACTIVE"
        assert p.node_status(cid) == "running"

        p.terminate_node(cid)
        assert calls[-1][0] == "DELETE" and "force=true" in calls[-1][1]
        assert p.node_status(cid) == "terminated"

    def test_gce_provider_drives_instance_manager(self):
        """The provider slots under the v2-shaped autoscaler FSM: QUEUED ->
        ... -> RAY_RUNNING using only provider callbacks (SURVEY §8.8)."""
        from ray_tpu.autoscaler.gcp import GceNodeProvider
        from ray_tpu.autoscaler.instance_manager import InstanceManager

        instances, _, request_fn = self._mock_gce()
        p = GceNodeProvider("proj", "z", "c", "h:1",
                            node_configs={"cpu8": {}},
                            request_fn=request_fn)
        im = InstanceManager()
        inst = im.create("cpu8")
        im.transition(inst.instance_id, "REQUESTED")
        cid = p.launch_node("cpu8", {"CPU": 8.0})
        im.transition(inst.instance_id, "ALLOCATED", cloud_id=cid)
        instances[p._instances[cid]]["status"] = "RUNNING"
        assert p.node_status(cid) == "running"
        im.transition(inst.instance_id, "RAY_RUNNING",
                      node_id=p.runtime_node_id(cid))
        assert im.get(inst.instance_id).node_id == p._instances[cid]
