"""Train layer: trainer/controller/worker-group E2E, reports, checkpoints,
failure recovery. (Reference shapes: python/ray/train/v2/tests/.)"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    get_context,
    report,
    restore_pytree,
    save_pytree,
)


def test_single_worker_report_flow(rt_start, tmp_path):
    def train_fn(config):
        ctx = get_context()
        for step in range(3):
            report({"step": step, "loss": 1.0 / (step + 1),
                    "rank": ctx.get_world_rank()})
        return "done"

    trainer = JaxTrainer(
        train_fn, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_multi_worker_ddp_with_host_collective(rt_start, tmp_path):
    """BASELINE config 1 shape: 2-worker CPU data-parallel with allreduce
    gradient sync through the host collective backend."""

    def train_fn(config):
        import numpy as np

        import ray_tpu.collective as col

        ctx = get_context()
        rank, world = ctx.get_world_rank(), ctx.get_world_size()
        g = col.init_collective_group(world_size=world, rank=rank,
                                      backend="host", group_name="ddp")
        # toy quadratic: minimize |w - 3|^2 with per-worker data shards
        w = np.zeros(4, np.float32)
        losses = []
        for step in range(5):
            target = np.full(4, 3.0 + 0.1 * rank, np.float32)
            grad = 2 * (w - target)
            grad = g.allreduce(grad) / world  # DDP gradient average
            w -= 0.3 * grad
            losses.append(float(((w - 3.05) ** 2).sum()))
            report({"step": step, "loss": losses[-1]})
        return w.tolist()

    trainer = JaxTrainer(
        train_fn, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ddp", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    # loss decreased and both workers converged to the same averaged target
    losses = [m["loss"] for m in result.metrics_history if m.get("step") == 4]
    assert all(l < 1.0 for l in losses)


def test_checkpoint_save_restore_roundtrip(tmp_path):
    import jax.numpy as jnp

    tree = {"w": jnp.arange(8, dtype=jnp.float32).reshape(2, 4),
            "opt": {"mu": jnp.ones((3,))}}
    d = save_pytree(tree, str(tmp_path / "ck1"), step=7)
    out = restore_pytree(d)
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(tree["w"]))
    np.testing.assert_allclose(np.asarray(out["opt"]["mu"]), 1.0)


def test_checkpoint_reported_and_retained(rt_start, tmp_path):
    def train_fn(config):
        import numpy as np

        ctx = get_context()
        for step in range(4):
            ck = None
            if ctx.get_world_rank() == 0:
                ck_dir = os.path.join(ctx.storage_path, f"checkpoint_{step:08d}")
                os.makedirs(ck_dir, exist_ok=True)
                np.save(os.path.join(ck_dir, "w.npy"), np.full(2, step))
                ck = ck_dir
            report({"step": step}, checkpoint=ck)

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ckpt", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    assert result.checkpoint is not None
    w = np.load(os.path.join(result.checkpoint.path, "w.npy"))
    np.testing.assert_allclose(w, 3.0)


def test_failure_restart_from_checkpoint(rt_start, tmp_path):
    """Worker crashes once; FailurePolicy restarts the group, which resumes
    from the latest reported checkpoint (reference: failure_handling/)."""
    marker = str(tmp_path / "crashed_once")

    def train_fn(config):
        import numpy as np

        ctx = get_context()
        start = 0
        if ctx.get_checkpoint():
            start = int(np.load(os.path.join(ctx.get_checkpoint(), "step.npy"))) + 1
        for step in range(start, 4):
            if step == 2 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("transient failure at step 2")
            ck = None
            if ctx.get_world_rank() == 0:
                ck_dir = os.path.join(ctx.storage_path, f"ck_{step}_{ctx.restart_count}")
                os.makedirs(ck_dir, exist_ok=True)
                np.save(os.path.join(ck_dir, "step.npy"), np.array(step))
                ck = ck_dir
            report({"step": step, "restart": ctx.restart_count}, checkpoint=ck)

    trainer = JaxTrainer(
        train_fn, train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="recover", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    steps = [m["step"] for m in result.metrics_history]
    assert steps[-1] == 3
    # resumed (restart_count 1) from step 2, not from scratch
    restarts = [m["restart"] for m in result.metrics_history]
    assert max(restarts) == 1
    resumed_steps = [m["step"] for m in result.metrics_history if m["restart"] == 1]
    assert min(resumed_steps) == 2


def test_failure_budget_unified(rt_start, tmp_path):
    """max_failures is ONE budget: a run allowed 1 restart restarts exactly
    once, and the second failure ends the run with the structured per-rank
    error (regression: _poll_until_done used to track an undecremented
    failures_left while run() counted restart_count separately, so the
    budget-exhausted path lost the rank attribution)."""
    attempts = str(tmp_path / "attempts")
    os.makedirs(attempts, exist_ok=True)

    def train_fn(config):
        import os as _os

        from ray_tpu.train import get_context

        ctx = get_context()
        open(_os.path.join(config["attempts"],
                           f"a{ctx.restart_count}"), "w").close()
        raise RuntimeError(f"always fails (restart {ctx.restart_count})")

    trainer = JaxTrainer(
        train_fn, train_loop_config={"attempts": attempts},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="budget", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert not result.ok
    # exactly 2 attempts: the original + the single budgeted restart
    assert sorted(os.listdir(attempts)) == ["a0", "a1"]
    # the terminal error is the structured per-rank map, not a controller
    # traceback wrapper
    assert "rank 0" in result.error and "always fails" in result.error
    # the restart decision was recorded with its tier
    assert len(result.restarts) == 1
    assert result.restarts[0]["tier"] in ("checkpoint", "replica")
    assert result.restarts[0]["trigger"] == "worker_error"


def test_async_checkpoint_writer(tmp_path):
    """Write-behind checkpointing: save() returns before the write lands,
    the next save() barriers on the previous one, completed() releases
    directories only after their writes finished, and restore sees the
    LAST snapshot's values even though the tree mutated right after
    save() returned (donation-safety: the snapshot is taken inline)."""
    import jax.numpy as jnp

    from ray_tpu.train import AsyncCheckpointWriter

    writer = AsyncCheckpointWriter()
    tree = {"w": jnp.zeros(4), "step": jnp.int32(0)}
    d1 = writer.save(tree, str(tmp_path / "ck1"), step=1)
    # mutate immediately — the async write must hold the old snapshot
    tree = {"w": jnp.full(4, 9.0), "step": jnp.int32(2)}
    d2 = writer.save(tree, str(tmp_path / "ck2"), step=2)  # barriers on d1
    assert d1 in writer.completed()  # d1 finished before d2 started
    writer.wait()
    assert writer.completed() == [d2]
    r1 = restore_pytree(d1)
    np.testing.assert_allclose(np.asarray(r1["w"]), 0.0)
    r2 = restore_pytree(d2)
    np.testing.assert_allclose(np.asarray(r2["w"]), 9.0)
    # a completed directory carries the meta file (write-finished sentinel)
    from ray_tpu.train import Checkpoint

    assert Checkpoint(d2).metadata()["step"] == 2


def test_async_checkpoint_writer_surfaces_errors(tmp_path):
    from ray_tpu.train import AsyncCheckpointWriter

    writer = AsyncCheckpointWriter()
    blocked = tmp_path / "blocked"
    blocked.write_text("a file where the checkpoint dir should go")
    writer.save({"w": np.ones(2)}, str(blocked / "ck"), step=0)
    with pytest.raises(Exception):
        writer.wait()
    assert writer.completed() == []


def test_jax_train_on_virtual_mesh(rt_start, tmp_path):
    """Tiny llama step inside a train worker on the 8-device CPU mesh —
    the single-process SPMD shape of the TPU fine-tune workload."""

    def train_fn(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.parallel.sharding import shard_params
        from ray_tpu.models.llama import param_logical_axes

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        params = shard_params(params, mesh, param_logical_axes(cfg))
        opt = optax.adamw(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(cfg, p, tokens, targets,
                                  attn_impl="blockwise"))(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        key = jax.random.PRNGKey(1)
        tokens = jax.random.randint(key, (4, 32), 0, cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        losses = []
        for i in range(3):
            params, opt_state, loss = step(params, opt_state, tokens, targets)
            losses.append(float(loss))
            report({"step": i, "loss": losses[-1]})
        assert losses[-1] < losses[0]

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="llama-tiny", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    assert result.metrics_history[-1]["loss"] < result.metrics_history[0]["loss"]


def test_spmd_train_step_factory(cpu_mesh_devices):
    import jax
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.spmd import make_llama_train_step

    cfg = LlamaConfig.tiny()
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2), cpu_mesh_devices)
    step_fn, init_state, shard = make_llama_train_step(
        cfg, mesh, optimizer=optax.adamw(1e-2), attn_impl="blockwise",
        remat=False)
    state = init_state()
    rng = np.random.default_rng(0)
    tokens = shard(rng.integers(0, cfg.vocab_size, (4, 32), dtype=np.int32))
    targets = shard(np.roll(np.asarray(tokens), -1, axis=1))
    state, m1 = step_fn(state, tokens, targets)
    state, m2 = step_fn(state, tokens, targets)
    assert float(m2["loss"]) < float(m1["loss"])
    assert int(state.step) == 2
    # params stayed sharded per rules (normalize both sides: jax 0.4.x
    # keeps P(("fsdp",)) and P("fsdp") distinct objects; >=0.5 normalizes
    # at construction)
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.sharding import normalize_spec
    assert normalize_spec(state.params["layers"]["wq"].sharding.spec) == \
        normalize_spec(P(None, ("fsdp",), "tp"))


def test_elastic_restart_at_smaller_world_size(tmp_path):
    """Chaos: kill a node mid-run; the elastic policy resumes training at a
    smaller world size from the latest checkpoint (reference:
    scaling_policy/elastic.py:29 + failure_handling restart)."""
    import threading
    import time

    from ray_tpu.core.worker import global_worker
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train.backend import JaxBackendConfig
    from ray_tpu.train.controller import TrainController
    from ray_tpu.utils import config as config_mod
    from ray_tpu.utils.ids import JobID

    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    config_mod.set_config(config_mod.Config.load())
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=8, resources={"trainslot": 1.0})
    doomed = c.add_node(num_cpus=2, resources={"trainslot": 1.0})
    rt = c.connect()
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        progress = str(tmp_path / "progress")
        os.makedirs(progress, exist_ok=True)

        def train_fn(config):
            import os
            import time

            import numpy as np

            from ray_tpu.train import get_context, report

            ctx = get_context()
            start = 0
            if ctx.get_checkpoint():
                start = int(np.load(os.path.join(ctx.get_checkpoint(),
                                                 "step.npy"))) + 1
            for step in range(start, 6):
                time.sleep(0.4)
                ck = None
                if ctx.get_world_rank() == 0:
                    d = os.path.join(ctx.storage_path,
                                     f"ck_{step}_{ctx.restart_count}")
                    os.makedirs(d, exist_ok=True)
                    np.save(os.path.join(d, "step.npy"), np.array(step))
                    ck = d
                    open(os.path.join(config["progress"],
                                      f"step_{step}"), "w").close()
                report({"step": step, "world": ctx.get_world_size(),
                        "restart": ctx.restart_count}, checkpoint=ck)

        controller = TrainController(
            train_fn, {"progress": progress},
            ScalingConfig(num_workers=2, min_workers=1, max_workers=2,
                          resources_per_worker={"trainslot": 1.0,
                                                "CPU": 1.0}),
            RunConfig(name="elastic", storage_path=str(tmp_path),
                      failure_config=FailureConfig(max_failures=3)),
            JaxBackendConfig(distributed=False),
        )

        def chaos():
            # wait for training to reach step 2, then kill the second node
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if os.path.exists(os.path.join(progress, "step_2")):
                    break
                time.sleep(0.1)
            c.remove_node(doomed)

        killer = threading.Thread(target=chaos)
        killer.start()
        result = controller.run()
        killer.join()

        assert result.ok, result.error
        worlds = [(m["restart"], m["world"], m["step"])
                  for m in result.metrics_history]
        # started at world 2 ...
        assert any(w == 2 for _, w, _ in worlds)
        # ... and a later restart ran at world 1 (elastic downsize)
        downsized = [(r, w, s) for r, w, s in worlds if w == 1]
        assert downsized, f"never downsized: {worlds}"
        # resumed from checkpoint, not from scratch
        assert min(s for _, _, s in downsized) >= 2
        # and training finished
        assert max(s for _, _, s in worlds) == 5
    finally:
        rt.shutdown()
        c.shutdown()
        global_worker.runtime = None
        config_mod.set_config(config_mod.Config.load())


def test_checkpoint_restore_at_different_world_size(cpu_mesh_devices, tmp_path):
    """A checkpoint sharded over 8 devices restores onto a 4-device mesh
    (the elastic-downsize reload path — reference: restore-from-checkpoint
    at new world size, orbax resharded load)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh8 = build_mesh(MeshSpec(dp=8), cpu_mesh_devices[:8])
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(mesh8, P("dp")))
    tree = {"w": x, "step": jnp.int32(5)}
    d = save_pytree(tree, str(tmp_path / "ck8"), step=5)

    mesh4 = build_mesh(MeshSpec(dp=4), cpu_mesh_devices[:4])
    template = {
        "w": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                  sharding=NamedSharding(mesh4, P("dp"))),
        "step": jax.ShapeDtypeStruct((), jnp.int32),
    }
    restored = restore_pytree(d, template)
    assert restored["w"].sharding.mesh.devices.size == 4
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(64.0).reshape(8, 8))
    assert int(restored["step"]) == 5


def test_trainer_dataset_ingest(tmp_path):
    """datasets= are streaming_split across the worker group and consumed
    via get_dataset_shard (reference: DataParallelTrainer datasets= +
    ray.train.get_dataset_shard; VERDICT M1 ingest wiring)."""
    import ray_tpu.data as rdata
    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.config import RunConfig, ScalingConfig

    def loop(config):
        from ray_tpu.train import get_dataset_shard, session

        it = get_dataset_shard("train")
        seen = []
        for batch in it.iter_batches(batch_size=8):
            seen.extend(int(v) for v in batch["id"])
        session.report({"n": len(seen), "sum": sum(seen)})

    ray_tpu.init(num_cpus=4)
    try:
        ds = rdata.range(64, parallelism=8)
        trainer = JaxTrainer(
            loop, datasets={"train": ds},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(name="ingest", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.ok, result.error
        # both ranks together see every row exactly once
        reports = result.metrics_history
        assert sum(r["n"] for r in reports) == 64
        assert sum(r["sum"] for r in reports) == sum(range(64))
        # equal split: each worker got half
        assert {r["n"] for r in reports} == {32}
    finally:
        ray_tpu.shutdown()
