"""Environments: a dependency-free CartPole + vectorization.

The reference's env runners wrap gymnasium (reference:
rllib/env/single_agent_env_runner.py builds gym vector envs); this image has
no gym, so the classic control task is implemented directly (same physics
and termination constants as CartPole-v1) behind the same reset/step
surface. ``make_env`` is the registry hook custom envs plug into.
"""

from __future__ import annotations

import numpy as np


class CartPoleEnv:
    """CartPole-v1 physics: push a cart ±10N to balance a pole.

    obs = [x, x_dot, theta, theta_dot]; reward 1 per step; terminates at
    |x| > 2.4 or |theta| > 12deg; truncates at 500 steps.
    """

    GRAVITY = 9.8
    CART_M = 1.0
    POLE_M = 0.1
    POLE_L = 0.5  # half-length
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_size = 4
    num_actions = 2

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, np.float64)
        self._steps = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, th, th_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_m = self.CART_M + self.POLE_M
        pm_l = self.POLE_M * self.POLE_L
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + pm_l * th_dot**2 * sin) / total_m
        th_acc = (self.GRAVITY * sin - cos * temp) / (
            self.POLE_L * (4.0 / 3.0 - self.POLE_M * cos**2 / total_m))
        x_acc = temp - pm_l * th_acc * cos / total_m
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        th += self.DT * th_dot
        th_dot += self.DT * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._steps += 1
        terminated = bool(abs(x) > self.X_LIMIT or abs(th) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        return (self._state.astype(np.float32), 1.0, terminated, truncated)


class PendulumEnv:
    """Pendulum-v1 physics: swing up and balance with bounded torque.

    Continuous control: obs = [cos th, sin th, th_dot], action = torque in
    [-2, 2]; reward = -(th^2 + 0.1 th_dot^2 + 0.001 a^2); 200-step
    episodes (no termination). Same constants as the gym classic."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    observation_size = 3
    action_size = 1
    continuous = True
    action_limit = MAX_TORQUE  # |action| bound, part of the env protocol

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._th = 0.0
        self._th_dot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th), self._th_dot],
                        np.float32)

    def reset(self) -> np.ndarray:
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._th_dot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th = ((self._th + np.pi) % (2 * np.pi)) - np.pi  # angle-normalize
        cost = th**2 + 0.1 * self._th_dot**2 + 0.001 * u**2
        self._th_dot += (3 * self.G / (2 * self.L) * np.sin(self._th)
                         + 3.0 / (self.M * self.L**2) * u) * self.DT
        self._th_dot = float(np.clip(self._th_dot, -self.MAX_SPEED,
                                     self.MAX_SPEED))
        self._th += self._th_dot * self.DT
        self._steps += 1
        return self._obs(), -float(cost), False, self._steps >= self.MAX_STEPS


_ENV_REGISTRY = {"CartPole-v1": CartPoleEnv, "Pendulum-v1": PendulumEnv}


def register_env(name: str, ctor) -> None:
    _ENV_REGISTRY[name] = ctor


def make_env(name: str, seed: int = 0):
    try:
        return _ENV_REGISTRY[name](seed=seed)
    except KeyError:
        raise ValueError(f"unknown env {name!r}; register_env() it first")


class VectorEnv:
    """N independent env copies with auto-reset on episode end (reference:
    gym vector env semantics the runner expects)."""

    def __init__(self, name: str, num_envs: int, seed: int = 0):
        self.envs = [make_env(name, seed=seed + i) for i in range(num_envs)]
        self.num_envs = num_envs
        self.episode_returns = np.zeros(num_envs)
        self.completed_returns: list[float] = []
        self.last_terminals = np.zeros(num_envs, np.bool_)

    def reset(self) -> np.ndarray:
        self.episode_returns[:] = 0.0
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions: np.ndarray):
        obs, rewards, dones = [], [], []
        terms, finals = [], []
        for i, (env, a) in enumerate(zip(self.envs, actions)):
            o, r, term, trunc = env.step(
                a if getattr(env, "continuous", False) else int(a))
            self.episode_returns[i] += r
            done = term or trunc
            final = o  # the TRUE successor obs, before any auto-reset
            if done:
                self.completed_returns.append(self.episode_returns[i])
                self.episode_returns[i] = 0.0
                o = env.reset()
            obs.append(o)
            rewards.append(r)
            dones.append(done)
            terms.append(term)
            finals.append(final)
        # TD targets must bootstrap THROUGH time-limit truncations (only
        # true terminations have zero future value) — gym's term/trunc
        # split. last_final_obs carries the pre-reset successor obs so the
        # truncation bootstrap targets V(final state), not V(reset state).
        self.last_terminals = np.asarray(terms, np.bool_)
        self.last_final_obs = np.stack(finals).astype(np.float32)
        return (np.stack(obs), np.asarray(rewards, np.float32),
                np.asarray(dones, np.bool_))

    def drain_episode_returns(self) -> list[float]:
        out, self.completed_returns = self.completed_returns, []
        return out
