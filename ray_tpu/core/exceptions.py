"""User-facing error types.

Capability parity with the reference's exception surface
(reference: python/ray/exceptions.py — RayError/RayTaskError/ActorDiedError/
ObjectLostError/OutOfMemoryError/...): errors raised on ``get`` carry the
remote traceback; actor/object loss is distinguishable and retryable state is
visible to callers.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get`` with the remote traceback."""

    def __init__(self, cause: BaseException, task_desc: str = "", remote_tb: str | None = None):
        self.cause = cause
        self.task_desc = task_desc
        self.remote_tb = remote_tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(f"task {task_desc} failed: {cause!r}\nremote traceback:\n{self.remote_tb}")

    def __reduce__(self):
        # Strip the traceback object (not always picklable); keep its text.
        cause = self.cause
        try:
            import pickle

            pickle.dumps(cause)
        except Exception:
            cause = RuntimeError(repr(self.cause))
        return (TaskError, (cause, self.task_desc, self.remote_tb))


class TaskCancelledError(RayTpuError):
    pass


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """``never_sent=True`` marks calls that provably never reached the dead
    actor (queued caller-side / drained from an unstarted mailbox): they
    cannot have executed, so retrying them is safe even for
    non-idempotent methods. Calls that were in flight on the dead
    incarnation keep the default False (at-most-once: they may have run)."""

    def __init__(self, actor_id_hex: str = "", reason: str = "",
                 never_sent: bool = False):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        self.never_sent = never_sent
        super().__init__(f"actor {actor_id_hex[:12]} died: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason,
                                 self.never_sent))


class ActorUnavailableError(ActorError):
    """Transient: actor restarting; calls may be retried."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_id_hex: str = "", reason: str = "owner or primary copy lost"):
        self.object_id_hex = object_id_hex
        self.reason = reason
        super().__init__(f"object {object_id_hex[:12]} lost: {reason}")

    def __reduce__(self):
        return (type(self), (self.object_id_hex, self.reason))


class OwnerDiedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class LeaseTimeoutError(RayTpuError):
    """A worker-lease request waited out the daemon's grant window. A
    stale-demand signal (the queue that motivated the request drained), not
    a task failure — submitters re-request sized to the current backlog."""


class PlacementGroupSchedulingError(RayTpuError):
    pass
