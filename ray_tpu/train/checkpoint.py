"""Checkpointing: sharded JAX pytrees via orbax + a top-K retention manager.

Capability parity with the reference's checkpoint stack (reference:
python/ray/train/v2/_internal/execution/checkpoint/checkpoint_manager.py:89
register_checkpoint :123 with top-K retention via CheckpointConfig;
storage via pyarrow/fsspec). TPU-native addition: multi-host async sharded
array checkpointing through orbax (each host writes its shards), which the
reference leaves to the user's framework.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from dataclasses import dataclass
from typing import Any

import jax


@dataclass
class Checkpoint:
    path: str

    def metadata(self) -> dict:
        meta_path = os.path.join(self.path, "rtpu_meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return json.load(f)
        return {}


def save_pytree(tree: Any, directory: str, step: int | None = None) -> str:
    """Write a (possibly sharded) jax pytree checkpoint. Multi-host safe —
    orbax coordinates shard writes across processes."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    target = os.path.join(directory, "state")
    if os.path.exists(target):
        shutil.rmtree(target)
    ckptr.save(target, tree)
    ckptr.wait_until_finished()
    with open(os.path.join(directory, "rtpu_meta.json"), "w") as f:
        json.dump({"step": step, "time": time.time()}, f)
    return directory


def restore_pytree(directory: str, template: Any = None) -> Any:
    """Restore a pytree; ``template`` (same structure w/ ShapeDtypeStruct or
    arrays, carrying shardings) controls placement of restored arrays."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    target = os.path.join(os.path.abspath(directory), "state")
    if template is not None:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            template,
        )
        return ckptr.restore(target, abstract)
    return ckptr.restore(target)


class CheckpointManager:
    """Tracks reported checkpoints, retains top-K, exposes the latest."""

    def __init__(self, storage_path: str, num_to_keep: int | None = None):
        self.storage_path = os.path.abspath(storage_path)
        os.makedirs(self.storage_path, exist_ok=True)
        self.num_to_keep = num_to_keep
        self._checkpoints: list[tuple[float, Checkpoint, dict]] = []

    def register(self, checkpoint_dir: str, metrics: dict | None = None) -> Checkpoint:
        ckpt = Checkpoint(checkpoint_dir)
        self._checkpoints.append((time.time(), ckpt, metrics or {}))
        self._enforce_retention()
        return ckpt

    def latest(self) -> Checkpoint | None:
        return self._checkpoints[-1][1] if self._checkpoints else None

    def best(self, metric: str, mode: str = "min") -> Checkpoint | None:
        scored = [(m.get(metric), c) for _, c, m in self._checkpoints
                  if m.get(metric) is not None]
        if not scored:
            return self.latest()
        scored.sort(key=lambda t: t[0], reverse=(mode == "max"))
        return scored[0][1]

    def next_checkpoint_dir(self, step: int) -> str:
        return os.path.join(self.storage_path, f"checkpoint_{step:08d}")

    def _enforce_retention(self):
        if self.num_to_keep is None:
            return
        while len(self._checkpoints) > self.num_to_keep:
            _, old, _ = self._checkpoints.pop(0)
            if os.path.isdir(old.path) and old.path.startswith(self.storage_path):
                shutil.rmtree(old.path, ignore_errors=True)
