"""Ray-Client-equivalent: remote-driver proxy (reference test model:
python/ray/util/client tests — API parity through the proxy)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import remote
from ray_tpu.core.worker import global_worker


@pytest.fixture()
def client_cluster():
    """A LocalRuntime-backed proxy server plus a thin client connected to
    it — the client process's runtime is the forwarding one."""
    from ray_tpu.core.local_runtime import LocalRuntime
    from ray_tpu.util.client import start_client_server

    ray_tpu.shutdown()
    backend = LocalRuntime(num_cpus=8, resources={"TPU": 4.0})
    server = start_client_server(backend)
    addr = f"{server.rpc.host}:{server.rpc.port}"
    ray_tpu.init(address=f"client://{addr}")
    yield backend
    ray_tpu.shutdown()
    try:
        from ray_tpu.core.cluster.protocol import EventLoopThread

        EventLoopThread.get().run(server.stop())
    except Exception:
        pass
    backend.shutdown()


def test_client_tasks_and_objects(client_cluster):
    assert global_worker.mode == "client"

    @remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 3), timeout=30) == 5

    ref = ray_tpu.put(np.arange(10))
    got = ray_tpu.get(ref, timeout=30)
    np.testing.assert_array_equal(got, np.arange(10))

    # ref-as-arg crosses the proxy
    assert ray_tpu.get(add.remote(ray_tpu.put(40), 2), timeout=30) == 42


def test_client_wait_and_errors(client_cluster):
    @remote
    def boom():
        raise ValueError("remote kaboom")

    with pytest.raises(ray_tpu.TaskError, match="remote kaboom"):
        ray_tpu.get(boom.remote(), timeout=30)

    @remote
    def ok():
        return 1

    refs = [ok.remote() for _ in range(3)]
    ready, pending = ray_tpu.wait(refs, num_returns=3, timeout=30)
    assert len(ready) == 3 and not pending


def test_client_actors(client_cluster):
    @remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="cl_ctr").remote(10)
    assert ray_tpu.get(c.inc.remote(), timeout=30) == 11
    h = ray_tpu.get_actor("cl_ctr")
    assert ray_tpu.get(h.inc.remote(), timeout=30) == 12
    ray_tpu.kill(c)


def test_client_kv_and_resources(client_cluster):
    rt = global_worker.runtime
    rt.kv_put("ck", b"cv")
    assert rt.kv_get("ck") == b"cv"
    assert "ck" in rt.kv_keys()
    rt.kv_del("ck")
    assert rt.kv_get("ck") is None
    assert ray_tpu.cluster_resources()["CPU"] == 8.0


def test_client_release_unpins_server_state(client_cluster):
    backend = client_cluster
    import gc

    before = len(backend.store.object_ids())
    refs = [ray_tpu.put(bytes(100)) for _ in range(5)]
    assert len(backend.store.object_ids()) >= before + 5
    del refs
    gc.collect()
    import time

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            len(backend.store.object_ids()) > before:
        time.sleep(0.05)
    assert len(backend.store.object_ids()) <= before
