"""Thread-safety annotations (reference: absl/base/thread_annotations.h
GUARDED_BY / EXCLUSIVE_LOCKS_REQUIRED, as zero-cost Python decorators).

The annotations are inert at runtime — they attach metadata the rtlint
R1 race checker reads statically — so hot paths pay nothing for being
documented.

Class form — declare which lock guards which attributes::

    @guarded_by("_lock", "_replicas", "_pending")
    class Router:
        ...

rtlint then flags ANY mutation of ``self._replicas`` / ``self._pending``
outside ``with self._lock:`` (``__init__`` excepted — construction
happens before the object is shared).

Method form — declare the caller must already hold the lock (absl's
EXCLUSIVE_LOCKS_REQUIRED)::

    @guarded_by("_lock")
    def _evict_locked(self):
        ...

rtlint treats the body as running with ``self._lock`` held, so guarded
attributes may be touched directly; keeping the convention honest is on
the callers (name such helpers ``*_locked`` by convention).

There is a sibling confinement annotation for classes whose state is
owned by ONE event loop thread (the head server, the watchdog)::

    @loop_confined
    class Watchdog:
        ...

It declares that every method — including public sync methods called
from async RPC handlers elsewhere — executes on that loop, so rtlint
stops presuming an external caller thread for them. Real thread entry
points inside the class (``threading.Thread`` targets) keep their own
context: a loop-confined class that spawns a flusher thread still gets
its races detected.
"""

from __future__ import annotations

from typing import Any, Callable, TypeVar

_T = TypeVar("_T")

# Metadata attribute rtlint's runtime-adoption tests can introspect; the
# static checker reads the decorator call from the AST instead.
ATTR = "__rtlint_guarded_by__"
CONFINED_ATTR = "__rtlint_loop_confined__"


def loop_confined(cls: _T) -> _T:
    """Declare every method of ``cls`` as running on one event loop."""
    setattr(cls, CONFINED_ATTR, True)
    return cls


def guarded_by(lock: str, *attrs: str) -> Callable[[_T], _T]:
    """Declare ``attrs`` (class form) or the decorated method's body
    (method form, no attrs) as guarded by ``self.<lock>``."""
    if not isinstance(lock, str) or not lock:
        raise TypeError("guarded_by: lock must be a non-empty attribute "
                        f"name string, got {lock!r}")
    for a in attrs:
        if not isinstance(a, str) or not a:
            raise TypeError(f"guarded_by: attr names must be strings, got {a!r}")

    def deco(obj: Any) -> Any:
        existing = dict(getattr(obj, ATTR, {}) or {})
        if attrs:
            for a in attrs:
                existing[a] = lock
        else:
            existing["<body>"] = lock
        setattr(obj, ATTR, existing)
        return obj

    return deco
